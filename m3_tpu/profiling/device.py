"""Device-memory accounting: the live-buffer half of the device tier.

Answers "what is holding device memory RIGHT NOW" with the same split
the storage layers think in:

- ``resident_pool`` — the paged HBM pool's flat page buffer
  (m3_tpu/resident/: the compressed working set);
- ``decoded_cache`` — the decoded-block cache's arrays
  (m3_tpu/cache/: the byte-budget LRU of decoded lanes);
- ``index`` — the device-resident inverted index tier
  (m3_tpu/index/device/: term-key matrices + postings arrays);
- ``other`` — every other live jax buffer (staging arrays, kernel
  outputs still referenced, query intermediates).

Published as ``m3tpu_device_memory_bytes{kind}`` gauges so the selfmon
pipeline stores the split as series (an OOM-adjacent incident becomes
one PromQL query over ``_m3tpu``), refreshed on the stack sampler's
schedule and on demand by the ``/debug/dump`` ``device_memory.json``
snapshot.

``jax.live_arrays()`` walks the client's live-buffer list — cheap at
the fleet's array counts (the pool and cache keep FEW large arrays by
design), but not free, which is why refresh rides the sampler's slow
``memory_interval`` rather than every sample tick.
"""

from __future__ import annotations

from ..utils.instrument import DEFAULT as METRICS

KINDS = ("resident_pool", "decoded_cache", "index", "other")

_HELP = (
    "live device/process memory by holder: resident_pool = the paged "
    "compressed HBM pool, decoded_cache = decoded-block cache arrays, "
    "index = device-resident inverted index segments, "
    "other = remaining live jax buffers"
)


def _gauge(kind: str):
    return METRICS.gauge("device_memory_bytes", _HELP, labels={"kind": kind})


def collect_device_memory(db=None) -> dict:
    """Snapshot the split, set the gauges, return the dict (the
    ``device_memory.json`` shape). ``db`` is any Database-surface object;
    None (or a cluster SessionDatabase with no local pool/cache) still
    accounts ``other``. Never raises — a jax-less or mid-teardown
    process reports what it can."""
    resident = 0
    cache = 0
    index_bytes = 0
    pool = getattr(db, "resident_pool", None) if db is not None else None
    if pool is not None:
        resident = pool.device_bytes()
    index_store = getattr(db, "index_device_store", None) if db is not None else None
    if index_store is not None:
        index_bytes = index_store.device_bytes()
    block_cache = getattr(db, "block_cache", None) if db is not None else None
    if block_cache is not None:
        try:
            cache = int(block_cache.stats().get("bytes", 0))
        except Exception:
            cache = 0
    total_live = 0
    try:
        # NEVER initiate the jax import from here: this runs on the
        # sampler's daemon thread, and racing the main thread's first
        # `import jax` leaves jax.numpy partially initialized for the
        # request path (observed as AttributeError in RPC handlers). A
        # process that hasn't imported jax has no live buffers to count.
        import sys as _sys

        jax = _sys.modules.get("jax")
        if jax is not None:
            total_live = sum(int(a.nbytes) for a in jax.live_arrays())
        else:
            total_live = resident + index_bytes
    except Exception:
        # partially initialized / backend torn down: report what we can
        total_live = resident + index_bytes
    # the decoded cache may hold HOST arrays (numpy) on some paths — it
    # is accounted from its own byte budget, not subtracted from the
    # live-buffer total (which only sees device arrays)
    other = max(total_live - resident - index_bytes, 0)
    out = {
        "resident_pool": resident,
        "decoded_cache": cache,
        "index": index_bytes,
        "other": other,
        "total_live_jax_bytes": total_live,
    }
    for kind in KINDS:
        _gauge(kind).set(float(out[kind]))
    return out
