"""Block model — the framework's north-star query interface.

Reference: `block.Block` (/root/reference/src/query/block/types.go:55-137)
exposes StepIter/SeriesIter views over a [series, time] result. The TPU-native
block IS the dense array: ``values`` f32[S, T] on a regular step grid with NaN
marking missing samples (the reference uses NaN sentinels the same way), plus
host-side per-series metadata (tags). Step/series views are cheap array
slices instead of iterators.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Sequence

import numpy as np

NANOS = 1_000_000_000

# Tags are tuples of (name, value) bytes pairs, sorted by name — the
# hashable, order-canonical equivalent of models.Tags
# (/root/reference/src/query/models/tags.go).
Tags = tuple[tuple[bytes, bytes], ...]


def make_tags(d: dict[bytes | str, bytes | str] | Sequence[tuple]) -> Tags:
    items = d.items() if isinstance(d, dict) else d
    out = []
    for k, v in items:
        k = k.encode() if isinstance(k, str) else bytes(k)
        v = v.encode() if isinstance(v, str) else bytes(v)
        out.append((k, v))
    return tuple(sorted(out))


@dataclass(frozen=True)
class Bounds:
    """Regular step grid: [start, start + step*steps) — query/block/types.go
    Bounds{Start, Duration, StepSize}."""

    start_nanos: int
    step_nanos: int
    steps: int

    @property
    def step_seconds(self) -> float:
        return self.step_nanos / NANOS

    def timestamps(self) -> np.ndarray:
        return self.start_nanos + self.step_nanos * np.arange(self.steps, dtype=np.int64)

    @property
    def end_nanos(self) -> int:
        return self.start_nanos + self.step_nanos * self.steps


@dataclass(frozen=True)
class SeriesMeta:
    """Per-series metadata (block.SeriesMeta: name + tags)."""

    tags: Tags
    name: bytes = b""


@dataclass
class BlockMeta:
    bounds: Bounds
    series: list[SeriesMeta] = field(default_factory=list)


@dataclass
class ColumnBlock:
    """values[S, T] on meta.bounds' grid; NaN = missing sample."""

    meta: BlockMeta
    values: np.ndarray  # or jnp array — functions are backend-agnostic

    @property
    def num_series(self) -> int:
        return self.values.shape[0]

    @property
    def num_steps(self) -> int:
        return self.values.shape[1]

    # --- view parity with block.Block (types.go:55) ---
    def step_iter(self) -> Iterator[tuple[int, np.ndarray]]:
        """Yields (unix_nanos, values[S]) per step — StepIter equivalent."""
        ts = self.meta.bounds.timestamps()
        vals = np.asarray(self.values)
        for i in range(self.num_steps):
            yield int(ts[i]), vals[:, i]

    def series_iter(self) -> Iterator[tuple[SeriesMeta, np.ndarray]]:
        """Yields (meta, values[T]) per series — SeriesIter equivalent."""
        vals = np.asarray(self.values)
        for i in range(self.num_series):
            meta = self.meta.series[i] if i < len(self.meta.series) else SeriesMeta(())
            yield meta, vals[i]

    def with_values(self, values, series: list[SeriesMeta] | None = None) -> "ColumnBlock":
        meta = BlockMeta(bounds=self.meta.bounds, series=self.meta.series if series is None else series)
        return ColumnBlock(meta=meta, values=values)
