"""Deterministic fault-injection plans for the RPC plane.

The CORE lives here in net/ (stdlib + instrument only) because the server
seam (net/server.py) must be able to consult a plan without importing the
``m3_tpu.testing`` package, whose ``__init__`` forces a virtual CPU mesh
into the process. Tests import the richer surface from
``m3_tpu.testing.faults`` (in-process node wrappers, env helpers), which
re-exports everything defined here.

A plan is a seeded list of rules; each incoming decision point
(client-side node-method call or server-side request dispatch) walks the
matching rules and draws from ONE plan-owned RNG, so a fixed seed plus a
fixed request sequence replays the exact same faults. Actions:

- ``drop``: the request vanishes (server closes the connection without a
  reply; in-process seam raises a ConnectionError) — the transport-failure
  path clients must survive;
- ``error``: a typed retryable ``UnavailableError`` reply;
- ``delay``: injected latency before the request proceeds;
- ``partition``: every matching request drops — a fully unreachable peer.

Spawned servers pick a plan up from the ``M3_TPU_FAULT_PLAN`` env var
(JSON, see :func:`plan_from_env`); nothing is installed when it is unset.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import asdict, dataclass

from ..utils.instrument import DEFAULT as METRICS

FAULT_PLAN_ENV = "M3_TPU_FAULT_PLAN"


class FaultInjectedError(ConnectionError):
    """Injected transport failure (the in-process seam's 'drop')."""


@dataclass
class FaultRule:
    """One match+action row. ``op``/``peer`` of None match anything;
    probabilities are independent draws in [0, 1].

    A peer-SCOPED rule never matches a decision point that has no peer
    (the server seam decides per op only): a fleet-wide env plan carrying
    ``peer="node2"`` rules must not fault every node's server.

    Injected delays can be JITTERED so a faulted straggler resembles a
    real latency tail instead of a fixed sleep: ``jitter`` spreads each
    draw around ``delay`` per ``delay_dist`` — "uniform" (the default;
    delay ± jitter, clamped at 0) or "lognormal" (median ``delay``,
    log-scale sigma ``jitter/delay`` — the heavy right tail real
    stragglers have). Draws come from the PLAN's seeded RNG, so a fixed
    seed plus a fixed request sequence replays the exact same delays."""

    op: str | None = None
    peer: str | None = None
    drop: float = 0.0
    error: float = 0.0
    delay: float = 0.0
    delay_prob: float = 1.0
    jitter: float = 0.0
    delay_dist: str = "uniform"
    partition: bool = False

    def matches(self, op: str, peer: str | None) -> bool:
        if self.op is not None and self.op != op:
            return False
        if self.peer is not None and self.peer != peer:
            return False
        return True

    def draw_delay(self, rng: random.Random) -> float:
        """One delay draw in seconds (``rng`` is the plan's seeded RNG,
        called under the plan lock — determinism rides the plan's single
        draw sequence)."""
        if self.jitter <= 0.0 or self.delay <= 0.0:
            return self.delay
        if self.delay_dist == "lognormal":
            import math

            sigma = self.jitter / self.delay
            return self.delay * math.exp(rng.gauss(0.0, sigma))
        return max(0.0, self.delay + rng.uniform(-self.jitter, self.jitter))


class FaultPlan:
    """Seeded fault schedule over (op, peer) decision points.

    ``exempt_ops`` are never faulted — a 'partitioned' node still answers
    e.g. ``owned_shards`` so a fixture can converge shard state before the
    chaos phase starts (a real switch partition would also leave the
    management network alone).
    """

    def __init__(
        self,
        rules: list[FaultRule],
        seed: int = 0,
        exempt_ops: tuple | list = (),
    ) -> None:
        self.rules = list(rules)
        self.seed = int(seed)
        self.exempt_ops = frozenset(exempt_ops)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._injected = {
            kind: METRICS.counter(
                "faults_injected_total",
                "faults injected by the active FaultPlan",
                labels={"kind": kind},
            )
            for kind in ("drop", "error", "delay", "partition")
        }

    # -- decisions --

    def decide(self, op: str, peer: str | None = None) -> tuple[str, float]:
        """One decision draw: ('pass'|'drop'|'error', delay_seconds)."""
        if op in self.exempt_ops:
            return "pass", 0.0
        delay = 0.0
        with self._lock:
            for rule in self.rules:
                if not rule.matches(op, peer):
                    continue
                if rule.partition:
                    self._injected["partition"].inc()
                    return "drop", delay
                if rule.delay > 0.0 and self._rng.random() < rule.delay_prob:
                    delay += rule.draw_delay(self._rng)
                    self._injected["delay"].inc()
                if rule.drop > 0.0 and self._rng.random() < rule.drop:
                    self._injected["drop"].inc()
                    return "drop", delay
                if rule.error > 0.0 and self._rng.random() < rule.error:
                    self._injected["error"].inc()
                    return "error", delay
        return "pass", delay

    def apply_client(self, op: str, peer: str | None = None) -> None:
        """In-process seam: sleep injected delay, raise injected failure.
        'drop' surfaces as a ConnectionError (what a vanished request
        looks like to a caller); 'error' as the typed retryable
        RemoteError the server seam would have sent."""
        action, delay = self.decide(op, peer)
        if delay > 0.0:
            time.sleep(delay)
        if action == "drop":
            raise FaultInjectedError(f"injected drop: {op} -> {peer or '?'}")
        if action == "error":
            from .client import RemoteError

            raise RemoteError(
                "UnavailableError", f"injected unavailable: {op} -> {peer or '?'}"
            )

    # -- (de)serialization for the env seam --

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "exempt_ops": sorted(self.exempt_ops),
                "rules": [asdict(r) for r in self.rules],
            }
        )

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        spec = json.loads(raw)
        rules = [FaultRule(**r) for r in spec.get("rules", [])]
        return cls(
            rules,
            seed=int(spec.get("seed", 0)),
            exempt_ops=tuple(spec.get("exempt_ops", ())),
        )


def plan_from_env(env=None) -> FaultPlan | None:
    """The spawned-server seam: a FaultPlan from M3_TPU_FAULT_PLAN, or
    None when unset. Malformed JSON raises — a chaos run silently running
    without its faults would pass vacuously."""
    raw = (env if env is not None else os.environ).get(FAULT_PLAN_ENV, "")
    if not raw:
        return None
    return FaultPlan.from_json(raw)
