"""Node RPC server: the network data plane of a storage node.

Reference: /root/reference/src/dbnode/network/server/tchannelthrift/node/
service.go — write (:449), writeTagged, fetch, fetchTagged (:626), query,
aggregate, plus the peer-streaming endpoints the bootstrapper/repair use.
Here: a threaded TCP server speaking the net.wire framing; each connection
is a sequential request/response loop (clients pool connections for
concurrency); per-request errors return {"ok": False} without killing the
connection.
"""

from __future__ import annotations

import os
import socket
import socketserver
import threading
import time

from ..utils.instrument import DEFAULT as METRICS
from ..utils.trace import NOOP_SPAN, TRACER
from ..utils.xtime import Unit
from . import wire
from .faults import plan_from_env
from .resilience import UnavailableError


class RpcMiddleware:
    """Observability + admission middleware over any ``handle(req) ->
    result`` service (x/instrument's tally-scope-per-server role +
    opentracing adoption + the server half of the resilience plane):

    - per-op request/error counters, latency histograms, and an in-flight
      gauge, all labeled {component, op} so one /metrics scrape separates
      dbnode data-plane ops from control-plane KV traffic;
    - trace adoption: an incoming request carrying a wire trace context
      gets a server-side span that JOINS the client's trace (the other half
      of net/client's injection) — a query fanning out coordinator → dbnode
      replicas renders as one stitched tree in /debug/traces;
    - deadline enforcement: a request whose propagated ``_deadline``
      already expired is refused with a typed retryable UnavailableError
      BEFORE dispatch — the caller stopped waiting, so doing the work only
      adds load exactly when the server is slow ("Tail at Scale");
    - load shedding: past ``max_inflight`` concurrent requests the server
      fast-fails new work with the same typed UnavailableError instead of
      queueing into collapse ('metrics' is exempt so overload stays
      observable);
    - a universal ``metrics`` op: services without their own op_metrics
      (raft KV, loadgen agents) still answer a Prometheus scrape, so every
      node in the fleet is scrapable over its existing RPC port.
    """

    def __init__(self, service, component: str = "rpc",
                 max_inflight: int | None = None) -> None:
        self.service = service
        self.component = component
        if max_inflight is None:
            try:
                max_inflight = int(os.environ.get("M3_TPU_RPC_MAX_INFLIGHT", "0"))
            except ValueError:
                max_inflight = 0
        self.max_inflight = max(0, max_inflight)  # 0 = uncapped
        self._inflight_total = 0
        self._load_lock = threading.Lock()
        labels = {"component": component}
        self._deadline_exceeded = METRICS.counter(
            "rpc_deadline_exceeded_total",
            "requests refused because their propagated deadline expired",
            labels=labels,
        )
        self._shed = METRICS.counter(
            "rpc_shed_total",
            "requests fast-failed past the in-flight cap",
            labels=labels,
        )
        # per-op metric handles, resolved once: registry child resolution
        # costs registry-lock round trips — the op set is small and fixed,
        # so every request after the first is one dict lookup
        self._per_op: dict = {}
        self._per_op_lock = threading.Lock()

    # op-label cardinality cap: op names come off the WIRE, and unknown ops
    # are only rejected at dispatch — without a cap, a fuzzer sending unique
    # bogus op strings would grow the process registry (and /metrics output)
    # without bound. Real services have far fewer ops than this.
    _MAX_OPS = 64

    def _handles(self, op: str):
        handles = self._per_op.get(op)
        if handles is not None:
            return handles
        with self._per_op_lock:
            handles = self._per_op.get(op)
            if handles is not None:
                return handles
            if len(self._per_op) >= self._MAX_OPS:
                op = "_overflow"
                handles = self._per_op.get(op)
                if handles is not None:
                    return handles
            labels = {"component": self.component, "op": op}
            handles = self._per_op[op] = (
                METRICS.counter("rpc_requests_total", labels=labels),
                METRICS.counter("rpc_errors_total", labels=labels),
                METRICS.gauge("rpc_inflight", labels=labels),
                METRICS.histogram(
                    "rpc_request_duration_seconds", labels=labels
                ),
            )
            return handles

    def handle(self, req: dict):
        op = str(req.get("op"))
        ctx = wire.extract_trace(req)
        deadline = wire.extract_deadline(req)
        tenant = wire.extract_tenant(req)
        if tenant is not None:
            # normalize BEFORE any accounting: tenant ids come off the
            # wire, and junk/flood ids must collapse into the capped
            # overflow tenant, not mint ledger accounts or label values
            from ..query.tenants import normalize

            tenant = normalize(tenant)
        if op == "metrics" and not hasattr(self.service, "op_metrics"):
            # fmt="json" serves the structured Registry.collect() snapshot
            # (what the self-scrape collector pulls); default stays the
            # Prometheus text exposition for scrapers
            if req.get("fmt") == "json":
                return METRICS.collect()
            return METRICS.expose()
        requests, errors, inflight, hist = self._handles(op)
        requests.inc()
        # admission: shed past the in-flight cap before spending anything
        # else on the request ('metrics' stays admitted so the scrape that
        # would show the overload is never itself shed). The shared counter
        # (and its lock) is only maintained when a cap is configured — the
        # per-op gauges already cover observability in the default config.
        tracked = bool(self.max_inflight) and op != "metrics"
        if tracked:
            with self._load_lock:
                shed = self._inflight_total >= self.max_inflight
                if not shed:
                    self._inflight_total += 1
            if shed:
                self._shed.inc()
                errors.inc()
                if tenant is not None:
                    # the shed is attributed: per-tenant shed counters are
                    # what admission-control rules (tenant:shed:rate5m)
                    # key off
                    from ..query.tenants import LEDGER

                    LEDGER.charge(tenant, sheds=1)
                raise UnavailableError(
                    f"overloaded: {self.max_inflight} requests in flight, "
                    f"shedding {op!r}"
                )
        trace_hex = None
        if ctx is not None and op not in wire.UNTRACED_OPS:
            span = TRACER.span_from_context(
                f"rpc.server.{op}", ctx, component=self.component
            )
            if ctx.get("sampled", True):
                # exemplar for the latency histogram: a slow bucket links
                # to the stitched trace this request belongs to
                trace_hex = f"{int(ctx['trace_id']):016x}"
        else:
            span = NOOP_SPAN
        inflight.add(1)
        t0 = time.perf_counter()
        try:
            # m3lint: disable=M3L004 -- the propagated _deadline is wall-clock by protocol; peers are assumed clock-synced
            if deadline is not None and time.time() >= deadline:
                self._deadline_exceeded.inc()
                raise UnavailableError(
                    # m3lint: disable=M3L004 -- lateness report against the wall-clock wire deadline
                    f"deadline expired {time.time() - deadline:.3f}s before "
                    f"dispatch of {op!r}"
                )
            with span:
                if tenant is None:
                    return self.service.handle(req)
                # re-establish the caller's tenant context around dispatch
                # (a thread-local cannot cross the socket — the same seam
                # shape as the selfmon wire marker): storage/decode work
                # under this handler, including the KernelProfiler's
                # sampled device-seconds, is attributed to the tenant
                from ..query.tenants import LEDGER, tenant_context

                LEDGER.charge(tenant, rpcs=1)
                span.set_tag("tenant", tenant)
                with tenant_context(tenant):
                    return self.service.handle(req)
        except Exception:
            errors.inc()
            raise
        finally:
            hist.observe(time.perf_counter() - t0, trace_id=trace_hex)
            inflight.add(-1)
            if tracked:
                with self._load_lock:
                    self._inflight_total -= 1


class DebugService:
    """Minimal RPC surface for processes with no data-plane service of
    their own (the aggregator's rawtcp ingest is one-way): behind the
    middleware it answers `health` and the universal `metrics` scrape, so
    every daemon in the fleet exposes the same observability ops."""

    def __init__(self, info: dict | None = None) -> None:
        self.info = info or {}

    def handle(self, req: dict):
        op = req.get("op")
        if op == "health":
            return {"ok": True, **self.info}
        if op == "traces":
            return TRACER.dump(limit=req.get("limit") or 256)
        if op == "profile":
            # wall-clock folded-stack profile of this process (the
            # continuous profiler's wire face; m3_tpu/profiling/) — the
            # aggregator's --debug-port surface answers it too, so the
            # coordinator's fleet merge covers every role
            from ..profiling import process_profile

            return process_profile(seconds=req.get("seconds"))
        raise ValueError(f"unknown op {op!r}")


class NodeService:
    """Dispatch table over a storage Database + shard assignment state."""

    def __init__(self, db, node_id: str = "", assigned_shards=None) -> None:
        self.db = db
        self.node_id = node_id
        self.assigned_shards: set[int] = set(assigned_shards or ())

    def handle(self, req: dict):
        op = req.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(req)

    # -- rpc.thrift surface --

    def op_health(self, req):
        return {"id": self.node_id, "bootstrapped": self.db.bootstrapped}

    # write ops honor the wire `selfmon` marker: the coordinator's
    # self-scrape collector writes the reserved `_m3tpu` namespace through
    # the normal cluster write plane, and its thread-local writer context
    # cannot cross the socket — the marker re-establishes it around
    # dispatch (selfmon/guard.py invariant 1); unmarked reserved-namespace
    # writes still raise inside storage.Database

    # write ops also attribute ingested datapoint counts to the caller's
    # wire-carried tenant context (query/tenants.charge_writes — a no-op
    # for unattributed intra-fleet traffic)

    def op_write(self, req):
        from ..query.tenants import charge_writes
        from ..selfmon.guard import wire_writer

        with wire_writer(req.get("selfmon")):
            self.db.write(
                req["ns"], req["sid"], req["t"], req["v"], Unit(req.get("unit", 1))
            )
        charge_writes(1)
        return True

    def op_write_batch(self, req):
        from ..query.tenants import charge_writes
        from ..selfmon.guard import wire_writer

        with wire_writer(req.get("selfmon")):
            self.db.write_batch(req["ns"], [tuple(e) for e in req["entries"]])
        charge_writes(len(req["entries"]))
        return True

    def op_write_tagged(self, req):
        from ..query.tenants import charge_writes
        from ..selfmon.guard import wire_writer

        tags = tuple((n, v) for n, v in req["tags"])
        with wire_writer(req.get("selfmon")):
            result = self.db.write_tagged(
                req["ns"], tags, req["t"], req["v"], Unit(req.get("unit", 1))
            )
        charge_writes(1)
        return result

    def op_write_tagged_batch(self, req):
        """One RPC per host-queue flush (host_queue.go role); per-entry
        errors ride back so the session counts quorum per datapoint."""
        from ..query.tenants import charge_writes
        from ..selfmon.guard import wire_writer

        entries = [
            (tuple((n, v) for n, v in tags), t, val, unit)
            for tags, t, val, unit in req["entries"]
        ]
        with wire_writer(req.get("selfmon")):
            errs = self.db.write_tagged_batch(req["ns"], entries)
        charge_writes(sum(1 for e in errs if not e) if errs else len(entries))
        return errs

    def op_fetch(self, req):
        dps = self.db.read(req["ns"], req["sid"], req["start"], req["end"])
        return wire.dps_to_wire(dps)

    def op_fetch_blocks(self, req):
        # compressed read: raw encoded segments (rpc.thrift fetchBlocksRaw)
        return self.db.fetch_blocks(req["ns"], req["sid"], req["start"], req["end"])

    def op_fetch_tagged(self, req):
        q = wire.query_from_wire(req["query"])
        res = self.db.fetch_tagged(
            req["ns"], q, req["start"], req["end"], limit=req.get("limit")
        )
        return wire.series_to_wire(res)

    def op_query_ids(self, req):
        # force_host bypasses the device index tier (read-only knob):
        # the doc-id parity half of tools/check_index.py diffs a normal
        # resolve against a host-forced one on the same node
        q = wire.query_from_wire(req["query"])
        result = self.db.query_ids(
            req["ns"], q, req["start"], req["end"], limit=req.get("limit"),
            force_host=bool(req.get("force_host")),
        )
        return {
            "docs": [[d.id, [[k, v] for k, v in d.fields]] for d in result.docs],
            "exhaustive": result.exhaustive,
        }

    def op_aggregate_query(self, req):
        q = wire.query_from_wire(req["query"])
        ff = req.get("field_filter")
        agg = self.db.aggregate_query(
            req["ns"], q, req["start"], req["end"],
            field_filter=[bytes(f) for f in ff] if ff else None,
        )
        return [[k, sorted(vs)] for k, vs in agg.items()]

    def op_stream_shard(self, req):
        return wire.series_to_wire(
            self.db.stream_shard(
                req["ns"], req["shard"],
                exclude_blocks=req.get("exclude") or (),
            )
        )

    # -- shard-handoff migration source (warm residency streaming) --

    def op_migrate_manifest(self, req):
        """Streamable sealed-fileset inventory for one shard: per complete
        fileset, byte sizes of every file role (compressed data pages,
        packed side planes, index/bloom/summaries, digest) a receiver
        fetches before cutover."""
        from ..storage.fs import migration_manifest

        return migration_manifest(self.db.base, req["ns"], req["shard"])

    def op_migrate_fetch(self, req):
        """One resumable byte-range read of one fileset file role
        ({"data": bytes, "eof": bool}). Immutable source files make
        re-reads duplicate-safe; a fileset retention raced away surfaces
        as the error the receiver's fallback handles."""
        from ..storage.fs import FilesetID, read_fileset_chunk

        fid = FilesetID(
            req["ns"], req["shard"], req["block_start"], req["volume"]
        )
        data, eof = read_fileset_chunk(
            self.db.base, fid, req["suffix"], req["offset"], req["max_bytes"]
        )
        return {"data": data, "eof": eof}

    # -- repair endpoints (storage/repair.go metadata + block fetch) --

    def op_block_metadata(self, req):
        from ..storage.repair import block_metadata

        return block_metadata(self.db, req["ns"], req["shard"])

    def op_stream_series_blocks(self, req):
        from ..storage.repair import stream_series_blocks

        items = [(sid, bs) for sid, bs in req["items"]]
        out = stream_series_blocks(self.db, req["ns"], items, shard_id=req["shard"])
        return [[sid, bs, wire.dps_to_wire(dps)] for sid, bs, dps in out]

    def op_metrics(self, req):
        """Self-observability exposition (x/instrument): Prometheus text,
        or the structured Registry.collect() snapshot with fmt="json" (the
        form the self-scrape collector ingests)."""
        if req.get("fmt") == "json":
            return METRICS.collect()
        return METRICS.expose()

    def op_traces(self, req):
        """This process's recent finished spans (the dbnode half of a
        cross-process trace: merge with the coordinator's /debug/traces by
        traceId to see the full tree)."""
        return TRACER.dump(limit=req.get("limit") or 256)

    def op_cache_stats(self, req):
        """Decoded-block cache debug/status: hit/miss/eviction counters,
        resident bytes vs budget (m3_tpu/cache/)."""
        return self.db.cache_stats()

    def op_resident_stats(self, req):
        """HBM-resident compressed pool debug/status: admissions,
        pages/bytes/occupancy, eviction + invalidation counters, the
        upload/streamed byte counters warm-scan zero-transfer checks key
        on, and the per-shard heat split (m3_tpu/resident/)."""
        return self.db.resident_stats()

    def op_resident_clear(self, req):
        """Drop every resident-pool entry (operator/debug surface):
        lets tools/check_resident.py exercise eviction churn and the
        read-through re-admission path against a live node. Duplicate-
        safe — clearing an empty pool clears nothing."""
        return {"dropped": self.db.resident_clear()}

    def op_index_stats(self, req):
        """Device-index-tier debug/status (m3_tpu/index/device/):
        admissions/evictions/search routing counters, device bytes vs
        budget, per-namespace segment counts, postings-cache
        effectiveness. Also refreshes the device-memory split gauges so
        ``m3tpu_device_memory_bytes{kind="index"}`` is current in the
        next scrape (the profiling sampler refreshes them on its own
        slower cadence)."""
        from ..profiling import collect_device_memory

        collect_device_memory(self.db)
        return self.db.index_stats()

    def op_profile(self, req):
        """Continuous-profiling surface (m3_tpu/profiling/): this
        process's wall-clock folded-stack profile over the last
        ``seconds`` — what the coordinator's /debug/pprof/fleet merge
        pulls from every placement node."""
        from ..profiling import process_profile

        return process_profile(seconds=req.get("seconds"))

    def op_flush(self, req):
        """Operator/CI flush: seal buffered blocks before the cutoff
        (the mediator does this on its own cadence; tools/check_resident
        drives it explicitly to make seal-time admission observable)."""
        flushed = self.db.flush(req["ns"], req["flush_before"])
        return [[f.namespace, f.shard, f.block_start, f.volume] for f in flushed]

    def op_snapshot(self, req):
        """Operator/CI snapshot: capture un-flushed buffers so commit-log
        replay is bounded (the mediator snapshots on its own cadence;
        tools/check_crash.py drives it explicitly to reach the
        snapshot:pre-cleanup crash point deterministically)."""
        return {"records": self.db.snapshot(req["ns"])}

    def op_scrub(self, req):
        """Operator/CI scrub: one digest-verify pass over sealed filesets
        (the background Scrubber daemon runs the same verification on its
        own paced cadence). Corrupt/torn volumes quarantine — duplicate-
        safe: a re-run re-verifies what's left."""
        return self.db.scrub(req.get("ns"))

    def op_repair(self, req):
        """Operator/CI repair: checksum-diff the given shards against peer
        endpoints and merge only differing blocks (storage/repair.py).
        Duplicate-safe — a converged shard streams nothing on re-run."""
        from ..storage.repair import repair_database
        from .client import RemoteNode

        peers = [RemoteNode.connect(ep) for ep in req["peers"]]
        try:
            res = repair_database(
                self.db, req["ns"], peers, shard_ids=req.get("shards")
            )
        finally:
            for peer in peers:
                peer.close()
        return {
            "shards_repaired": res.shards_repaired,
            "blocks_compared": res.blocks_compared,
            "blocks_streamed": res.blocks_streamed,
            "points_merged": res.points_merged,
            "points_skipped_cold": res.points_skipped_cold,
            "peer_errors": res.peer_errors,
        }

    def op_scan_totals(self, req):
        """Raw-sample scan-and-aggregate over matched series (block
        granularity): routed to the decode-from-HBM path when every
        matched block is resident, streamed otherwise — the wire face of
        M3Storage.scan_totals. ``matchers``: [[name, op, value], ...].
        ``explain``: also record and return the per-(series, block)
        routing decisions (query/stats.py add_routing) so CI can assert
        WHICH decoder served the scan, not just the path."""
        import time as _time

        from ..query import stats
        from ..query.m3_storage import M3Storage
        from ..query.promql import Matcher

        matchers = [
            Matcher(str(n), str(op), str(v)) for n, op, v in req["matchers"]
        ]
        storage = M3Storage(self.db, req["ns"])
        if not req.get("explain"):
            return storage.scan_totals(matchers, req["start"], req["end"])
        st = stats.start("EXPLAIN scan_totals")
        if st is not None:
            st.record_routing = True
            st.namespace = str(req["ns"])
        t0 = _time.perf_counter()
        try:
            out = storage.scan_totals(matchers, req["start"], req["end"])
        finally:
            if st is not None:
                stats.finish(st, _time.perf_counter() - t0)
        if st is not None:
            out["routing"] = list(st.routing)
        return out

    def op_query_range(self, req):
        """Local PromQL evaluation over this node's database — the wire
        face of the one-dispatch fused query pipeline (query/plan.py).
        The per-namespace engine is CACHED so the plan cache warms across
        requests. ``force_staged`` runs the parity probe (device plans
        disabled for this evaluation); the response carries the full
        QueryStats record — deviceDispatches, plan hit/miss/fallback
        counts, and (with ``explain``) per-series routing reasons — so
        CI can assert a warm eligible query is exactly ONE dispatch and
        bit-identical to the staged path."""
        import time as _time

        from ..query import plan as query_plan
        from ..query import stats

        eng = self._query_engine(req["ns"])
        st = stats.start(f"wire:{req['query']}")
        if st is not None:
            st.namespace = str(req["ns"])
            if req.get("explain"):
                st.record_routing = True
        t0 = _time.perf_counter()
        err = None
        try:
            if req.get("force_staged"):
                with query_plan.force_staged():
                    r = eng.query_range(
                        req["query"], req["start"], req["end"], req["step"]
                    )
            else:
                r = eng.query_range(
                    req["query"], req["start"], req["end"], req["step"]
                )
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            if st is not None:
                stats.finish(st, _time.perf_counter() - t0, error=err)
        import numpy as np

        values = np.asarray(r.values, np.float64)
        return {
            "values": [list(map(float, row)) for row in values],
            "metas": [
                [[bytes(k), bytes(v)] for k, v in m.tags] for m in r.metas
            ],
            "stats": st.to_dict() if st is not None else {},
        }

    def _query_engine(self, ns: str):
        """Cached per-namespace Engine over the LOCAL database (bounded
        by the namespaces the database actually serves, so wire input
        can't grow the dict)."""
        engines = getattr(self, "_query_engines", None)
        if engines is None:
            engines = self._query_engines = {}
        eng = engines.get(ns)
        if eng is None:
            if ns not in self.db.namespaces:
                raise ValueError(f"unknown namespace {ns!r}")
            from ..query.engine import Engine
            from ..query.m3_storage import M3Storage

            eng = engines[ns] = Engine(M3Storage(self.db, ns))
        return eng

    def op_owned_shards(self, req):
        return sorted(self.assigned_shards)

    def op_assign_shards(self, req):
        """AssignShardSet (database.go:386): the control plane pushes shard
        ownership; peers bootstrap is driven by the caller via stream_shard."""
        self.assigned_shards = set(req["shards"])
        return True


class RpcServer:
    """Threaded TCP front end for any service exposing handle(req)->result.

    Serves the data plane (NodeService) and the control plane (cluster KV
    service) over the same framing."""

    def __init__(
        self, service, host: str = "127.0.0.1", port: int = 0,
        component: str = "rpc", max_inflight: int | None = None,
        fault_plan=None,
    ):
        self.service = service
        # every RPC server front end gets the observability middleware:
        # per-op metrics, trace adoption, and a universal `metrics` scrape op
        svc = RpcMiddleware(service, component=component,
                            max_inflight=max_inflight)
        self.middleware = svc
        # deterministic fault-injection seam: an explicit plan, or one from
        # the M3_TPU_FAULT_PLAN env var for spawned chaos processes; None
        # (the default) costs nothing per request
        fault_plan = fault_plan if fault_plan is not None else plan_from_env()
        self.fault_plan = fault_plan
        # live connections, force-closed on stop() so blocked long-polls and
        # pooled client sockets see a reset (SIGKILL semantics) instead of
        # silently talking to a stopped server
        conns: set = set()
        conns_lock = threading.Lock()
        self._conns, self._conns_lock = conns, conns_lock

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with conns_lock:
                    conns.add(self.request)
                try:
                    while True:
                        try:
                            req = wire.recv_frame(self.request)
                        except (ConnectionError, OSError):
                            return
                        if fault_plan is not None:
                            action, delay = fault_plan.decide(str(req.get("op")))
                            if delay > 0.0:
                                time.sleep(delay)
                            if action == "drop":
                                # the request vanishes: close the connection
                                # without a reply — the client sees the same
                                # reset a crashed/partitioned server produces
                                return
                            if action == "error":
                                try:
                                    wire.send_frame(self.request, {
                                        "ok": False,
                                        "error": "UnavailableError: injected",
                                        "etype": "UnavailableError",
                                    })
                                    continue
                                except (ConnectionError, OSError):
                                    return
                        try:
                            result = svc.handle(req)
                            resp = {"ok": True, "result": result}
                        except Exception as exc:  # per-request isolation
                            resp = {
                                "ok": False,
                                "error": f"{type(exc).__name__}: {exc}",
                                "etype": type(exc).__name__,
                            }
                        try:
                            wire.send_frame(self.request, resp)
                        except (ConnectionError, OSError):
                            return
                finally:
                    with conns_lock:
                        conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="m3tpu-node-server", daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            for sock in list(self._conns):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class NodeServer(RpcServer):
    """TCP front end for a NodeService."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 component: str = "dbnode", **kwargs):
        super().__init__(service, host=host, port=port, component=component,
                         **kwargs)
