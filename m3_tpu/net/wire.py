"""Node RPC wire format: length-prefixed frames of a compact binary value
codec, plus (de)serialization of the index query AST and datapoints.

Reference surface: /root/reference/src/dbnode/generated/thrift/rpc.thrift:44-87
(write / writeTagged / fetch / fetchTagged / query plus batch variants) —
the reference speaks TChannel+Thrift; this framework defines its own framing:

    frame   := u32 little-endian payload length | payload
    payload := value
    value   := 'N' | 'T' | 'F'
             | 'i' i64 | 'd' f64
             | 'b' u32 len bytes | 's' u32 len utf8
             | 'l' u32 count value* | 'm' u32 count (value value)*

Every RPC request is a map {"op": str, ...args}; every response is a map
{"ok": bool, "result": ... | "error": str}.
"""

from __future__ import annotations

import struct
from io import BytesIO

from ..codec.m3tsz import Datapoint
from ..index.query import (
    AllQuery,
    ConjunctionQuery,
    DisjunctionQuery,
    FieldQuery,
    NegationQuery,
    Query,
    RegexpQuery,
    TermQuery,
)
from ..utils.xtime import Unit

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

MAX_FRAME = 256 * 1024 * 1024


def encode_value(v, out: BytesIO) -> None:
    if v is None:
        out.write(b"N")
    elif v is True:
        out.write(b"T")
    elif v is False:
        out.write(b"F")
    elif isinstance(v, int):
        out.write(b"i")
        out.write(_I64.pack(v))
    elif isinstance(v, float):
        out.write(b"d")
        out.write(_F64.pack(v))
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.write(b"b")
        out.write(_U32.pack(len(b)))
        out.write(b)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.write(b"s")
        out.write(_U32.pack(len(b)))
        out.write(b)
    elif isinstance(v, (list, tuple)):
        out.write(b"l")
        out.write(_U32.pack(len(v)))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, dict):
        out.write(b"m")
        out.write(_U32.pack(len(v)))
        for k, val in v.items():
            encode_value(k, out)
            encode_value(val, out)
    else:
        raise TypeError(f"unencodable type {type(v)!r}")


def decode_value(buf: bytes, pos: int = 0):
    tag = buf[pos : pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"i":
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == b"d":
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag in (b"b", b"s"):
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        raw = buf[pos : pos + n]
        if len(raw) != n:
            raise ValueError("truncated value")
        return (raw if tag == b"b" else raw.decode("utf-8")), pos + n
    if tag == b"l":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = decode_value(buf, pos)
            items.append(item)
        return items, pos
    if tag == b"m":
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = decode_value(buf, pos)
            v, pos = decode_value(buf, pos)
            d[k] = v
        return d, pos
    raise ValueError(f"bad value tag {tag!r} at {pos - 1}")


def dumps(v) -> bytes:
    out = BytesIO()
    encode_value(v, out)
    return out.getvalue()


def loads(b: bytes):
    v, pos = decode_value(b, 0)
    if pos != len(b):
        raise ValueError(f"trailing bytes after value ({pos} != {len(b)})")
    return v


# --- framing over a socket/file-like ---


def pack_frame(payload: bytes) -> bytes:
    return _U32.pack(len(payload)) + payload


class FrameDecoder:
    """Incremental length-prefixed frame parser for streaming receivers
    (the one framing implementation; request/response paths use
    send_frame/recv_frame below)."""

    def __init__(self, max_frame: int = MAX_FRAME) -> None:
        self.max_frame = max_frame
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> list[bytes]:
        self._buf.extend(chunk)
        out = []
        while len(self._buf) >= 4:
            (n,) = _U32.unpack_from(self._buf, 0)
            if n > self.max_frame:
                raise ValueError(f"frame too large: {n}")
            if len(self._buf) < 4 + n:
                break
            out.append(bytes(self._buf[4 : 4 + n]))
            del self._buf[: 4 + n]
        return out


def send_frame(sock, v) -> None:
    sock.sendall(pack_frame(dumps(v)))


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock):
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return loads(_recv_exact(sock, n))


# --- trace context propagation (Dapper-style; x/context StartSampledTraceSpan
# carried this through TChannel headers in the reference) ---

# reserved request-map key: [trace_id i64, parent span_id i64, sampled bool]
TRACE_KEY = "_trace"

# ops that pollers hammer (health checks, scrapes, shard-ownership probes):
# spans for them would be all noise. ONE list shared by client injection and
# server adoption — the exclusion must stay symmetric or traces end up
# half-stitched (server spans with no client parent, or vice versa).
UNTRACED_OPS = frozenset(
    {"health", "metrics", "traces", "cache_stats", "resident_stats",
     "index_stats", "owned_shards"}
)

# ops the RPC client may TRANSPARENTLY retry on a transport failure or a
# typed retryable rejection: re-executing them server-side changes nothing.
# Everything else (writes, KV mutations, lease ops) reaches the server at
# most once per caller-visible attempt — a broken connection mid-write is
# ambiguous (the op may have applied), so only a layer that understands the
# op's semantics (the Session's idempotent-upsert fan-out retry, the KV
# store's documented at-least-once contract) may send it again. The raft
# RPCs are idempotent by protocol — term/index consistency checks make a
# duplicate append/vote a no-op — and keep their pre-registry stale-socket
# retry behavior.
IDEMPOTENT_OPS = frozenset(
    {
        # data-plane reads + probes
        "health", "fetch", "fetch_blocks", "fetch_tagged", "query_ids",
        "aggregate_query", "stream_shard", "block_metadata",
        "stream_series_blocks", "scan_totals", "query_range", "owned_shards",
        # shard-handoff migration reads: the manifest lists immutable
        # sealed filesets, and a fetch is a byte-range read of one
        # fileset file — re-reading the same range is duplicate-safe, so
        # transfers survive transport failures via the normal budgeted
        # retry machinery
        "migrate_manifest", "migrate_fetch",
        # debug / observability ('profile' reads the process's folded
        # stack table — sampling continues regardless, duplicate-safe)
        "metrics", "traces", "cache_stats", "resident_stats", "index_stats",
        "lg_poll", "profile",
        # operator ops that re-apply to the same state
        "flush", "assign_shards", "resident_clear", "scrub", "repair",
        "snapshot",
        # raft protocol (duplicate-safe by design)
        "raft_vote", "raft_append", "raft_snapshot", "raft_status",
        # KV reads (mutations ride RemoteKVStore's own failover contract);
        # kv_watch is a long-poll read — re-asking "anything newer than
        # version V?" is duplicate-safe by construction
        "kv_get", "kv_keys", "kv_get_prefix", "kv_lease_get", "kv_watch",
    }
)

# RemoteError etypes that are safe to retry for idempotent ops: the server
# REFUSED the request (deadline already expired, load shed, injected fault)
# without touching state. Raised as net.resilience.UnavailableError
# server-side; RetryableError is the raft KV service's pre-existing
# no-leader-yet rejection. DiskFullError (storage/faults.py) is the
# commit-log ENOSPC shed: the write was rejected before any WAL append, so
# the client may retry it elsewhere (or later, once space frees) — the SLO
# plane sees it as unavailability, not data loss.
RETRYABLE_ETYPES = frozenset(
    {"UnavailableError", "RetryableError", "DiskFullError"}
)


def inject_trace(req: dict, ctx: dict | None) -> dict:
    """Attach a tracer context (utils.trace.Tracer.current_context()) to an
    RPC request map; no-op when there is no active sampled span."""
    if ctx is not None:
        req[TRACE_KEY] = [int(ctx["trace_id"]), int(ctx["span_id"]),
                          bool(ctx.get("sampled", True))]
    return req


def extract_trace(req: dict) -> dict | None:
    """Pop the trace context off an incoming request map (popped so op
    handlers never see the reserved key). Malformed fields → None: a bad
    peer must not break the request."""
    raw = req.pop(TRACE_KEY, None)
    if not isinstance(raw, list) or len(raw) != 3:
        return None
    tid, sid, sampled = raw
    if not isinstance(tid, int) or not isinstance(sid, int):
        return None
    return {"trace_id": tid, "span_id": sid, "sampled": bool(sampled)}


# --- tenant propagation (the identity half of per-tenant cost
# attribution, query/tenants.py: the coordinator's HTTP layer sets a
# thread-local tenant context, and it must survive the socket hop so
# dbnode-side decode work is attributed to the same caller) ---

# reserved request-map key: the caller's tenant id (str)
TENANT_KEY = "_tenant"


def inject_tenant(req: dict, tenant: str | None) -> dict:
    """Attach the active tenant identity to an RPC request map; no-op
    when no tenant context is active (intra-fleet traffic stays
    unattributed rather than paying a frame field per call)."""
    if tenant is not None:
        req[TENANT_KEY] = str(tenant)
    return req


def extract_tenant(req: dict) -> str | None:
    """Pop the tenant off an incoming request map (popped so op handlers
    never see the reserved key). Malformed → None, like extract_trace;
    VALIDATION (charset/length/cardinality) is the receiver's job —
    query/tenants.normalize collapses junk into the capped overflow
    tenant."""
    raw = req.pop(TENANT_KEY, None)
    if not isinstance(raw, str) or not raw:
        return None
    return raw


# --- deadline propagation (x/context deadlines over TChannel in the
# reference; "The Tail at Scale" cancellation discipline: a server must not
# burn cycles on a request whose caller already gave up) ---

# reserved request-map key: absolute wall-clock deadline, seconds since the
# unix epoch (wall clock, not monotonic — it must mean the same thing in
# another process; peers are assumed clock-synced to well under typical
# timeouts, as in the reference)
DEADLINE_KEY = "_deadline"


def inject_deadline(req: dict, deadline: float | None) -> dict:
    """Attach an absolute wall-clock deadline to an RPC request map."""
    if deadline is not None:
        req[DEADLINE_KEY] = float(deadline)
    return req


def extract_deadline(req: dict) -> float | None:
    """Pop the deadline off an incoming request map (popped so op handlers
    never see the reserved key). Malformed → None, like extract_trace."""
    raw = req.pop(DEADLINE_KEY, None)
    if isinstance(raw, bool) or not isinstance(raw, (int, float)):
        return None
    return float(raw)


# --- query AST <-> wire values ---


def query_to_wire(q: Query):
    if isinstance(q, TermQuery):
        return {"t": "term", "f": q.field, "v": q.value}
    if isinstance(q, RegexpQuery):
        return {"t": "regexp", "f": q.field, "p": q.pattern}
    if isinstance(q, FieldQuery):
        return {"t": "field", "f": q.field}
    if isinstance(q, AllQuery):
        return {"t": "all"}
    if isinstance(q, ConjunctionQuery):
        return {"t": "conj", "q": [query_to_wire(s) for s in q.queries]}
    if isinstance(q, DisjunctionQuery):
        return {"t": "disj", "q": [query_to_wire(s) for s in q.queries]}
    if isinstance(q, NegationQuery):
        return {"t": "neg", "q": query_to_wire(q.query)}
    raise TypeError(f"unknown query type {type(q)!r}")


def query_from_wire(w) -> Query:
    t = w["t"]
    if t == "term":
        return TermQuery(w["f"], w["v"])
    if t == "regexp":
        return RegexpQuery(w["f"], w["p"])
    if t == "field":
        return FieldQuery(w["f"])
    if t == "all":
        return AllQuery()
    if t == "conj":
        return ConjunctionQuery(tuple(query_from_wire(s) for s in w["q"]))
    if t == "disj":
        return DisjunctionQuery(tuple(query_from_wire(s) for s in w["q"]))
    if t == "neg":
        return NegationQuery(query_from_wire(w["q"]))
    raise ValueError(f"unknown query tag {t!r}")


# --- datapoints / series results ---


def dps_to_wire(dps) -> list:
    return [
        [dp.timestamp, dp.value, int(dp.unit), dp.annotation or b""] for dp in dps
    ]


def dps_from_wire(w) -> list[Datapoint]:
    return [
        Datapoint(t, v, Unit(u), bytes(a) if a else None) for t, v, u, a in w
    ]


def series_to_wire(result) -> list:
    """[(sid, tags, dps)] -> wire (tags as [[name, value], ...])."""
    return [
        [sid, [[n, v] for n, v in tags], dps_to_wire(dps)]
        for sid, tags, dps in result
    ]


def series_from_wire(w) -> list:
    return [
        (sid, tuple((n, v) for n, v in tags), dps_from_wire(dps))
        for sid, tags, dps in w
    ]
