"""Resilience primitives for the RPC plane: retry policies with budgets,
per-host circuit breakers, deadline bookkeeping, and background health
probing.

Reference: the reference M3 ships x/retry (exponential backoff + jitter +
retry *budgets* so a brown-out cannot amplify itself into a retry storm)
and per-host connection health checking in the dbnode client
(connection_pool.go health checks gating host queues). "The Tail at Scale"
(Dean & Barroso, CACM 2013) and the Hystrix circuit-breaker literature
motivate the rest of the toolkit: propagated deadlines so work is never
done for a caller that stopped waiting, and fast-fail ejection of hosts
that keep timing out so fan-outs stop paying the worst replica's tail.

Everything here emits through utils/instrument's process registry:

    m3tpu_rpc_retries_total{op}           transparent RPC-layer retries
    m3tpu_rpc_retry_budget_exhausted_total retries suppressed by the budget
    m3tpu_session_hedge_budget_exhausted_total hedges suppressed by the budget
    m3tpu_breaker_state{peer}             0 closed / 1 half-open / 2 open
    m3tpu_breaker_transitions_total{peer,to}
"""

from __future__ import annotations

import random
import threading
import time

from ..utils.instrument import DEFAULT as METRICS


class UnavailableError(RuntimeError):
    """Typed RETRYABLE server-side rejection: the request was refused
    before any state changed (expired deadline, load shed, injected
    fault), so even a non-idempotent op is safe to send again."""


class BreakerOpenError(ConnectionError):
    """Fast-fail raised client-side while a peer's circuit is open; a
    ConnectionError so callers' transport-failure handling (session
    replica accounting, KV failover rotation) treats it like any other
    unreachable-peer outcome — without paying a socket timeout."""


class DeadlineExceededError(RuntimeError):
    """The caller's deadline expired before (or while) the call ran."""


_DEADLINE_LOCAL = threading.local()


def current_deadline() -> float | None:
    """This thread's ambient deadline as a MONOTONIC instant, or None.

    Established by :func:`deadline_scope` at an entry point that knows
    how long its caller is willing to wait (the coordinator's HTTP
    ``timeout`` param / ``M3-Timeout`` header); consumed wherever work
    queues or fans out (``QueryScheduler.admit(deadline=)``, the RPC
    client's per-call wall-clock budget) so nothing keeps working for a
    caller that already hung up."""
    return getattr(_DEADLINE_LOCAL, "deadline", None)


def remaining_time() -> float | None:
    """Seconds until the ambient deadline (may be <= 0 when already
    expired), or None when no deadline scope is active."""
    deadline = current_deadline()
    if deadline is None:
        return None
    return deadline - time.monotonic()


class deadline_scope:
    """Establish (or tighten) the thread's ambient deadline for a block.

    Scopes only ever TIGHTEN: nesting under an earlier scope keeps the
    earlier deadline when it is sooner, so an inner library cannot grant
    itself more time than the caller offered. ``None`` is a no-op scope
    (keeps whatever is ambient), which lets entry points write
    ``with deadline_scope(parsed_or_none):`` unconditionally. Re-enter
    with a captured :func:`current_deadline` value to carry the budget
    onto a worker thread (thread-locals don't cross threads)."""

    def __init__(self, deadline: float | None) -> None:
        self.deadline = deadline
        self._prev: float | None = None

    def __enter__(self) -> float | None:
        self._prev = getattr(_DEADLINE_LOCAL, "deadline", None)
        if self.deadline is None:
            effective = self._prev
        elif self._prev is None:
            effective = self.deadline
        else:
            effective = min(self._prev, self.deadline)
        _DEADLINE_LOCAL.deadline = effective
        return effective

    def __exit__(self, *exc) -> None:
        _DEADLINE_LOCAL.deadline = self._prev


class RetryBudget:
    """Token bucket bounding the *ratio* of retries to requests
    (x/retry's budget role, gRPC retry-throttling shape): every success
    deposits ``token_ratio`` tokens, every retry spends one, and retries
    are allowed only while the bucket is above half — so a total outage
    degrades to ~token_ratio extra load instead of multiplying traffic
    by the retry count."""

    def __init__(
        self,
        max_tokens: float = 32.0,
        token_ratio: float = 0.2,
        exhausted_counter: str = "rpc_retry_budget_exhausted_total",
        exhausted_help: str = "retries suppressed because the retry budget ran dry",
    ) -> None:
        self.max_tokens = float(max_tokens)
        self.token_ratio = float(token_ratio)
        self._tokens = float(max_tokens)
        self._lock = threading.Lock()
        # m3lint: disable=M3L005 -- every constructor call site passes a static literal (rpc_retry_budget / session_hedge_budget): a closed two-name set
        self._exhausted = METRICS.counter(exhausted_counter, exhausted_help)

    @property
    def tokens(self) -> float:
        return self._tokens

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens, self._tokens + self.token_ratio)

    def try_spend(self) -> bool:
        """Spend one token for a retry; False (and a metric tick) when the
        budget is below half — the caller must fail instead of retrying."""
        with self._lock:
            if self._tokens <= self.max_tokens / 2:
                allowed = False
            else:
                self._tokens -= 1.0
                allowed = True
        if not allowed:
            self._exhausted.inc()
        return allowed


class RetryPolicy:
    """Exponential backoff with DECORRELATED jitter plus a retry budget.

    ``backoff(attempt, prev)`` follows the "decorrelated jitter" scheme
    (sleep = min(cap, uniform(base, prev * 3))) except that the FIRST
    retry sleeps 0 — the overwhelmingly common transport failure is a
    stale pooled socket whose peer restarted, and an immediate retry on a
    fresh connection both preserves the pre-budget behavior of this
    client and keeps the happy path fast.

    ``seed`` pins the jitter RNG for deterministic tests; production
    callers leave it None.
    """

    def __init__(
        self,
        max_retries: int = 3,
        initial_backoff: float = 0.02,
        max_backoff: float = 1.0,
        budget: RetryBudget | None = None,
        seed: int | None = None,
    ) -> None:
        self.max_retries = int(max_retries)
        self.initial_backoff = float(initial_backoff)
        self.max_backoff = float(max_backoff)
        self.budget = RetryBudget() if budget is None else budget
        self._rng = random.Random(seed)

    def backoff(self, attempt: int, prev: float = 0.0) -> float:
        """Sleep before retry number ``attempt`` (1-based), given the
        previous sleep; bounded by [0, max_backoff]."""
        if attempt <= 1:
            return 0.0
        lo = self.initial_backoff
        hi = max(lo, min(self.max_backoff, max(prev, lo) * 3.0))
        return min(self.max_backoff, self._rng.uniform(lo, hi))

    def allow_retry(self, attempt: int) -> bool:
        """Whether retry number ``attempt`` (1-based) may happen: bounded
        by max_retries AND by the shared budget."""
        if attempt > self.max_retries:
            return False
        return self.budget.try_spend()

    def on_success(self) -> None:
        self.budget.on_success()


class HedgeBudget(RetryBudget):
    """Token bucket bounding hedged (backup) replica requests to a small
    ratio of served traffic — "The Tail at Scale"'s 'a few percent extra
    load' bound. Every successful primary response deposits
    ``token_ratio`` (default 5%) tokens; every hedge spends one and is
    allowed only while the bucket is above half, so a cluster-wide
    brown-out cannot turn hedging into a traffic doubler."""

    def __init__(self, max_tokens: float = 8.0, token_ratio: float = 0.05) -> None:
        super().__init__(
            max_tokens=max_tokens,
            token_ratio=token_ratio,
            exhausted_counter="session_hedge_budget_exhausted_total",
            exhausted_help="hedged backup requests suppressed because the "
                           "hedge budget ran dry",
        )


class LatencyEstimator:
    """Per-(peer, op) response-latency p95 estimate over a sliding sample
    window (old samples fall out, so the estimate decays toward current
    behavior after a regime change). The hedging layer compares a pending
    replica's elapsed time against ITS OWN p95 to decide the request is a
    straggler, and ranks candidate peers by p95 to pick the next-best
    replica for the backup ("Tail at Scale" hedged requests keyed off the
    class's expected latency, not a fixed grace)."""

    def __init__(self, window: int = 64, min_samples: int = 8) -> None:
        self.window = int(window)
        self.min_samples = int(min_samples)
        self._samples: dict[tuple[str, str], list[float]] = {}
        self._lock = threading.Lock()

    def record(self, peer: str, op: str, seconds: float) -> None:
        key = (peer, op)
        with self._lock:
            buf = self._samples.get(key)
            if buf is None:
                buf = self._samples[key] = []
            buf.append(float(seconds))
            if len(buf) > self.window:
                del buf[: len(buf) - self.window]

    def p95(self, peer: str, op: str) -> float | None:
        """The current p95 estimate, or None until ``min_samples`` have
        been observed (an unmeasured peer must not be hedged against a
        made-up threshold)."""
        with self._lock:
            buf = self._samples.get((peer, op))
            if buf is None or len(buf) < self.min_samples:
                return None
            ordered = sorted(buf)
        return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]

    def rank(self, peers, op: str) -> list[str]:
        """Peers ordered fastest-first by p95 estimate; unmeasured peers
        sort last (a peer we know nothing about is a worse hedge target
        than one we know to be fast)."""
        est = {p: self.p95(p, op) for p in peers}
        return sorted(peers, key=lambda p: (est[p] is None, est[p] or 0.0))


_BREAKER_STATE_VALUES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class CircuitBreaker:
    """Per-host circuit breaker: closed → open after
    ``failure_threshold`` CONSECUTIVE transport failures; open → half-open
    after ``recovery_timeout``; the single half-open probe closes it on
    success or re-opens it on failure (Hystrix state machine).

    Only transport failures count — an application error from a living
    server is evidence the host is UP. ``clock`` is injectable for tests.
    """

    def __init__(
        self,
        peer: str = "",
        failure_threshold: int = 5,
        recovery_timeout: float = 2.0,
        clock=time.monotonic,
    ) -> None:
        self.peer = peer
        self.failure_threshold = int(failure_threshold)
        self.recovery_timeout = float(recovery_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._gauge = METRICS.gauge(
            "breaker_state",
            "per-peer circuit state: 0 closed, 1 half-open, 2 open",
            labels={"peer": peer or "?"},
        )
        self._gauge.set(0.0)

    @property
    def state(self) -> str:
        return self._state

    def _set_state(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        self._gauge.set(_BREAKER_STATE_VALUES[state])
        METRICS.counter(
            "breaker_transitions_total",
            "circuit breaker state transitions",
            labels={"peer": self.peer or "?", "to": state},
        ).inc()

    def available(self) -> bool:
        """Side-effect-free 'worth talking to' check (RemoteNode.is_up):
        False only while open with the recovery window still running."""
        with self._lock:
            if self._state != "open":
                return True
            return self._clock() - self._opened_at >= self.recovery_timeout

    def allow(self) -> bool:
        """Gate one call attempt. Open→half-open transition happens here
        once the recovery window elapses; in half-open exactly ONE probe
        is in flight at a time."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at < self.recovery_timeout:
                    return False
                self._set_state("half_open")
                self._probing = True
                return True
            # half-open: single probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def release(self) -> None:
        """Release a probe slot claimed by :meth:`allow` when the attempt
        aborted WITHOUT learning anything about the peer (e.g. the
        caller's deadline expired before anything was sent) — otherwise a
        half-open breaker whose probe aborted would stay probing forever
        and never admit another attempt."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != "closed":
                self._set_state("closed")

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == "half_open":
                self._opened_at = self._clock()
                self._set_state("open")
                return
            self._failures += 1
            if self._state == "closed" and self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._set_state("open")


class HealthProber:
    """Cheap background health probe driving per-host breakers back
    closed (the reference client's connection health check role): probes
    only hosts whose breaker is NOT closed, so a healthy fleet costs
    nothing and a recovered host is readmitted within ~interval instead
    of waiting for live traffic to half-open probe it."""

    def __init__(self, nodes: dict, interval: float = 0.25,
                 probe_timeout: float = 1.0) -> None:
        self.nodes = nodes
        self.interval = interval
        self.probe_timeout = probe_timeout
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._probe_failures = METRICS.counter(
            "health_probe_failures_total",
            "background health probes that failed (expected while a peer "
            "is down; the breaker outcome is what matters)",
        )

    def start(self) -> "HealthProber":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="m3tpu-health-prober"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            for node in list(self.nodes.values()):
                breaker = getattr(node, "breaker", None)
                if breaker is None or breaker.state == "closed":
                    continue
                try:
                    # success/failure lands on the breaker inside _call
                    node._call("health", _retry=False,
                               _timeout=self.probe_timeout)
                except Exception:
                    # swallow-by-design (probing a down host), but counted
                    # so a prober that NEVER succeeds is visible (M3L007)
                    self._probe_failures.inc()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
