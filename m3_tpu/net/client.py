"""RemoteNode: socket-backed node stub with a connection pool, budgeted
retries, and a per-host circuit breaker.

Reference: /root/reference/src/dbnode/client/ — host queues and connection
pools (session.go:505 Open, host_queue.go) plus x/retry (backoff + jitter +
retry budgets) and per-host connection health checking. Each RemoteNode
keeps a small pool of persistent connections; transport failures are
retried (with decorrelated-jitter backoff and a per-client retry budget)
ONLY for ops in wire.IDEMPOTENT_OPS, every call carries a propagated
deadline, and consecutive transport failures open a circuit breaker that
backs ``is_up`` — so the Session's down-replica accounting fires for remote
nodes instead of paying a timeout per fan-out. Remote errors surface as
exceptions so consistency accounting treats them like any replica failure.

RemoteNode implements the same surface as testing/cluster.Node, so a Session
works identically over in-process nodes and sockets.
"""

from __future__ import annotations

import socket
import threading
import time

from ..utils.instrument import DEFAULT as METRICS
from ..utils.trace import NOOP_SPAN, TRACER
from ..utils.xtime import Unit
from . import wire
from .resilience import (
    BreakerOpenError,
    CircuitBreaker,
    DeadlineExceededError,
    RetryPolicy,
)

# failures of the transport itself (vs typed errors from a living server):
# these count against the peer's circuit breaker. ValueError covers a
# corrupt frame — the connection is unusable either way.
TRANSPORT_ERRORS = (ConnectionError, OSError, ValueError)


class RemoteError(RuntimeError):
    def __init__(self, etype: str, message: str) -> None:
        super().__init__(message)
        self.etype = etype


class RpcClient:
    """Generic pooled request/response client over the wire framing; the
    base for RemoteNode (data plane) and RemoteKVStore (control plane)."""

    def __init__(
        self,
        host: str,
        port: int,
        pool_size: int = 4,
        timeout: float = 10.0,
        retry_policy: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
    ) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self.retry_policy = (
            retry_policy if retry_policy is not None else RetryPolicy()
        )
        self.breaker = (
            breaker if breaker is not None else CircuitBreaker(peer=f"{host}:{port}")
        )
        self._pool: list[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_size = pool_size

    @classmethod
    def connect(cls, endpoint: str, **kwargs):
        """Build a client from a 'host:port' endpoint string (the one
        parser for placement/discovery endpoints)."""
        host, port = endpoint.rsplit(":", 1)
        return cls(host, int(port), **kwargs)

    # -- connection pool --

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _acquire(self) -> socket.socket:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._connect()

    def _release(self, sock: socket.socket) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(sock)
                return
        sock.close()

    def close(self) -> None:
        with self._pool_lock:
            for sock in self._pool:
                sock.close()
            self._pool.clear()

    def _call(self, op: str, _retry: bool = True, _timeout: float | None = None, **args):
        # trace propagation: when this RPC happens inside a traced request
        # (a span is active on this thread), it gets its own client span and
        # the context rides the wire so the server joins the same trace —
        # the per-process spans stitch into one tree (Dapper propagation).
        # Untraced calls (no active span) pay nothing. Retries happen INSIDE
        # this one span (tagged retried=N) — a retry is one logical call,
        # not a second nested rpc.client span.
        if TRACER.active() and op not in wire.UNTRACED_OPS:
            span = TRACER.span(f"rpc.client.{op}", peer=f"{self.host}:{self.port}")
        else:
            span = NOOP_SPAN
        with span:
            return self._call_attempts(op, _retry, _timeout, args, span)

    def _call_attempts(self, op: str, _retry: bool, _timeout: float | None,
                       args: dict, span):
        """Attempt loop: budgeted transparent retries for IDEMPOTENT ops
        only (transport failures and typed retryable rejections); every
        attempt is gated by the peer's circuit breaker and bounded by one
        shared per-call deadline that also rides the wire."""
        budget = _timeout if _timeout is not None else self.timeout
        # ambient-deadline propagation (resilience.deadline_scope): a call
        # made under a caller-supplied deadline (coordinator HTTP timeout)
        # never budgets past what the caller will wait for — the tightened
        # budget also rides the wire as the _deadline frame, so the server
        # refuses work the client has already abandoned.
        from .resilience import remaining_time

        ambient = remaining_time()
        if ambient is not None:
            budget = min(budget, max(ambient, 0.0))
        # m3lint: disable=M3L004 -- the wire _deadline frame is wall-clock by protocol (must mean the same instant in another process)
        deadline = time.time() + budget
        retryable = _retry and op in wire.IDEMPOTENT_OPS
        attempt = 0
        prev_backoff = 0.0
        while True:
            if not self.breaker.allow():
                raise BreakerOpenError(
                    f"circuit open for {self.host}:{self.port} ({op})"
                )
            try:
                result = self._call_once(op, args, deadline)
            except TRANSPORT_ERRORS as exc:
                self.breaker.record_failure()
                err: Exception = exc
            except RemoteError as exc:
                # the server is alive and answered — that is breaker-success
                self.breaker.record_success()
                if exc.etype not in wire.RETRYABLE_ETYPES:
                    raise
                err = exc
            except BaseException:
                # an abort that says nothing about the peer (deadline
                # expired before sending, KeyboardInterrupt): release any
                # half-open probe slot allow() claimed, or the breaker
                # would stay probing forever and never admit another call
                self.breaker.release()
                raise
            else:
                self.breaker.record_success()
                self.retry_policy.on_success()
                return result
            attempt += 1
            if (
                not retryable
                or time.time() >= deadline  # m3lint: disable=M3L004 -- compares against the wall-clock wire deadline
                or not self.retry_policy.allow_retry(attempt)
            ):
                raise err
            METRICS.counter(
                "rpc_retries_total",
                "transparent RPC-layer retries of idempotent ops",
                labels={"op": op},
            ).inc()
            span.set_tag("retried", attempt)
            prev_backoff = self.retry_policy.backoff(attempt, prev_backoff)
            if prev_backoff > 0.0:
                remaining = deadline - time.time()  # m3lint: disable=M3L004 -- remaining budget against the wall-clock wire deadline
                if remaining <= 0:
                    raise err
                time.sleep(min(prev_backoff, remaining))

    def _call_once(self, op: str, args: dict, deadline: float):
        """One wire round trip; the deadline bounds the socket wait and is
        propagated in the frame so the server can refuse expired work."""
        remaining = deadline - time.time()  # m3lint: disable=M3L004 -- remaining budget against the wall-clock wire deadline
        if remaining <= 0:
            raise DeadlineExceededError(
                f"deadline expired before sending {op!r} to {self.host}:{self.port}"
            )
        req = wire.inject_trace({"op": op, **args}, TRACER.current_context())
        wire.inject_deadline(req, deadline)
        # tenant propagation (query/tenants.py): a call made under a
        # tenant context carries the identity so the server attributes
        # its work (decode device-seconds, per-tenant rpc counters) to
        # the same caller. query/__init__ is empty, so this import pulls
        # no jax-adjacent weight into the net layer.
        from ..query.tenants import current as current_tenant

        wire.inject_tenant(req, current_tenant())
        sock = self._acquire()
        try:
            sock.settimeout(remaining)
            wire.send_frame(sock, req)
            resp = wire.recv_frame(sock)
            sock.settimeout(self.timeout)
        except BaseException:
            sock.close()
            raise
        self._release(sock)
        if not resp.get("ok"):
            raise RemoteError(resp.get("etype", ""), resp.get("error", "remote error"))
        return resp.get("result")


class RemoteNode(RpcClient):
    def __init__(
        self,
        host: str,
        port: int,
        node_id: str | None = None,
        pool_size: int = 4,
        timeout: float = 10.0,
        **kwargs,
    ) -> None:
        super().__init__(host, port, pool_size=pool_size, timeout=timeout,
                         **kwargs)
        self.id = node_id or f"{host}:{port}"
        self._shards_cache: tuple[float, set[int]] | None = None

    # -- node surface (mirrors testing/cluster.Node) --

    @property
    def is_up(self) -> bool:
        # backed by the per-host circuit breaker: False only while the
        # breaker is open with its recovery window still running, so the
        # Session's down-replica accounting skips a dead host instead of
        # paying its connect/read timeout on every fan-out. Once the
        # window elapses (or a background HealthProber closes the breaker)
        # traffic resumes via the half-open probe.
        return self.breaker.available()

    def health(self) -> dict:
        return self._call("health")

    @staticmethod
    def _selfmon_args(ns) -> dict:
        """Reserved-namespace writes carry the wire `selfmon` marker: the
        server re-establishes the collector's writer context around
        dispatch (a thread-local cannot cross the socket — and the
        session's host-queue flusher threads aren't even the collector's
        thread client-side). Only the self-scrape pipeline addresses these
        namespaces; in-process accidental paths (downsampler output,
        remote-write relabels) hit the bare Database surface and raise."""
        from ..selfmon.guard import is_reserved

        return {"selfmon": True} if is_reserved(ns) else {}

    def write(self, ns, sid, t, v, unit=Unit.SECOND):
        return self._call("write", ns=ns, sid=sid, t=t, v=v, unit=int(unit),
                          **self._selfmon_args(ns))

    def write_batch(self, ns, entries):
        return self._call(
            "write_batch", ns=ns, entries=[list(e) for e in entries],
            **self._selfmon_args(ns),
        )

    def write_tagged(self, ns, tags, t, v, unit=Unit.SECOND):
        return self._call(
            "write_tagged",
            ns=ns,
            tags=[[n, v2] for n, v2 in tags],
            t=t,
            v=v,
            unit=int(unit),
            **self._selfmon_args(ns),
        )

    def write_tagged_batch(self, ns, entries):
        """entries: (tags, t, v, unit) — one framed RPC, per-entry errors."""
        return self._call(
            "write_tagged_batch",
            ns=ns,
            entries=[
                [[[n, v2] for n, v2 in tags], t, v, int(unit)]
                for tags, t, v, unit in entries
            ],
            **self._selfmon_args(ns),
        )

    def read(self, ns, sid, start, end):
        return wire.dps_from_wire(
            self._call("fetch", ns=ns, sid=sid, start=start, end=end)
        )

    def fetch_blocks(self, ns, sid, start, end):
        return self._call("fetch_blocks", ns=ns, sid=sid, start=start, end=end)

    def fetch_tagged(self, ns, query, start, end, limit=None):
        return wire.series_from_wire(
            self._call(
                "fetch_tagged",
                ns=ns,
                query=wire.query_to_wire(query),
                start=start,
                end=end,
                limit=limit,
            )
        )

    def query_ids(self, ns, query, start, end, limit=None, force_host=False):
        extra = {"force_host": True} if force_host else {}
        return self._call(
            "query_ids",
            ns=ns,
            query=wire.query_to_wire(query),
            start=start,
            end=end,
            limit=limit,
            **extra,
        )

    def aggregate_query(self, ns, query, start, end, field_filter=None):
        out = self._call(
            "aggregate_query",
            ns=ns,
            query=wire.query_to_wire(query),
            start=start,
            end=end,
            field_filter=[bytes(f) for f in field_filter] if field_filter else None,
        )
        return {bytes(k): {bytes(v) for v in vs} for k, vs in out}

    def stream_shard(self, ns, shard, exclude_blocks=None):
        """Decoded peer stream of one shard; ``exclude_blocks`` skips
        sealed blocks the caller already imported via migration (their
        buffered overlays still stream — only fileset content dedupes)."""
        args = {"ns": ns, "shard": shard}
        if exclude_blocks:
            args["exclude"] = sorted(exclude_blocks)
        return wire.series_from_wire(self._call("stream_shard", **args))

    def migrate_manifest(self, ns, shard) -> list:
        """Sealed-fileset inventory of a shard on this peer (the
        migration source's streamable file roles + byte sizes)."""
        return self._call("migrate_manifest", ns=ns, shard=shard)

    def migrate_fetch(
        self, ns, shard, block_start, volume, suffix, offset, max_bytes,
        _timeout=None,
    ) -> dict:
        """One resumable byte-range read of one fileset file role on this
        peer — deadline-bounded per chunk (``_timeout``) and transparently
        retried under the idempotent-op budget, so a partial transfer
        resumes at the byte offset rather than restarting the file."""
        return self._call(
            "migrate_fetch", _timeout=_timeout, ns=ns, shard=shard,
            block_start=block_start, volume=volume, suffix=suffix,
            offset=offset, max_bytes=max_bytes,
        )

    def block_metadata(self, ns, shard):
        return self._call("block_metadata", ns=ns, shard=shard)

    def stream_series_blocks(self, ns, shard, items):
        out = self._call(
            "stream_series_blocks",
            ns=ns,
            shard=shard,
            items=[[sid, bs] for sid, bs in items],
        )
        return [(sid, bs, wire.dps_from_wire(dps)) for sid, bs, dps in out]

    def cache_stats(self) -> dict:
        return self._call("cache_stats")

    def resident_stats(self) -> dict:
        """HBM-resident compressed pool stats (m3_tpu/resident/)."""
        return self._call("resident_stats")

    def resident_clear(self) -> dict:
        """Drop every resident-pool entry (operator/CI surface)."""
        return self._call("resident_clear")

    def index_stats(self) -> dict:
        """Device index tier + postings cache stats (m3_tpu/index/)."""
        return self._call("index_stats")

    def flush(self, ns, flush_before) -> list:
        """Seal buffered blocks before the cutoff (operator/CI surface)."""
        return self._call("flush", ns=ns, flush_before=flush_before)

    def snapshot(self, ns) -> dict:
        """Capture un-flushed buffers to a snapshot file (operator/CI
        surface; bounds commit-log replay)."""
        return self._call("snapshot", ns=ns)

    def scrub(self, ns=None) -> dict:
        """One digest-verify pass over sealed filesets; corrupt/torn
        volumes quarantine. {"scanned","quarantined","bytes"}."""
        return self._call("scrub", ns=ns)

    def repair(self, ns, peers, shards=None) -> dict:
        """Checksum-diff ``shards`` (all when None) against peer
        endpoint strings and merge differing blocks (operator/CI
        surface; the repair daemon runs the same path on a cadence)."""
        return self._call("repair", ns=ns, peers=list(peers), shards=shards)

    def scan_totals(self, ns, matchers, start, end, explain: bool = False) -> dict:
        """Raw-sample scan-and-aggregate; ``matchers``:
        [[name, op, value], ...] (see NodeService.op_scan_totals).
        ``explain`` adds the per-(series, block) routing record."""
        return self._call(
            "scan_totals", ns=ns, matchers=list(matchers), start=start,
            end=end, explain=explain,
        )

    def query_range(self, ns, query: str, start: int, end: int, step: int,
                    force_staged: bool = False, explain: bool = False) -> dict:
        """PromQL range evaluation on the node's LOCAL engine — the wire
        face of the fused device query pipeline. Returns {"values",
        "metas", "stats"}; ``force_staged`` is the bit-identity parity
        probe, ``explain`` adds per-series routing to the stats record."""
        return self._call(
            "query_range", ns=ns, query=query, start=start, end=end,
            step=step, force_staged=force_staged, explain=explain,
        )

    def metrics(self) -> str:
        """Prometheus text exposition of the remote process (the universal
        scrape op every RpcServer answers via the middleware)."""
        return self._call("metrics")

    def metrics_snapshot(self) -> dict:
        """Structured Registry.collect() snapshot of the remote process —
        what the self-scrape collector converts into stored series (same
        universal op, fmt="json")."""
        return self._call("metrics", fmt="json")

    def traces(self, limit: int = 256) -> list[dict]:
        """The remote process's recent spans — merge with other processes'
        dumps by traceId to reassemble a cross-process trace."""
        return self._call("traces", limit=limit)

    def profile(self, seconds: float | None = None) -> dict:
        """The remote process's wall-clock folded-stack profile over the
        last ``seconds`` (m3_tpu/profiling/): {"folded": {stack: count},
        "samples", "hz", ...} — the fleet profile merge pulls this from
        every peer."""
        return self._call("profile", seconds=seconds)

    def owned_shards(self, cache_secs: float = 1.0) -> set[int]:
        cached = self._shards_cache
        now = time.monotonic()
        if cached is not None and now - cached[0] < cache_secs:
            return cached[1]
        shards = set(self._call("owned_shards"))
        self._shards_cache = (now, shards)
        return shards

    def assign_shards(self, shards) -> None:
        self._shards_cache = None
        self._call("assign_shards", shards=sorted(shards))
