"""RemoteNode: socket-backed node stub with a connection pool + retries.

Reference: /root/reference/src/dbnode/client/ — host queues and connection
pools (session.go:505 Open, host_queue.go); here each RemoteNode keeps a
small pool of persistent connections, retries once on a broken connection
(idempotent ops), and surfaces remote errors as exceptions so the Session's
consistency accounting treats them like any replica failure.

RemoteNode implements the same surface as testing/cluster.Node, so a Session
works identically over in-process nodes and sockets.
"""

from __future__ import annotations

import socket
import threading
import time

from ..utils.trace import NOOP_SPAN, TRACER
from ..utils.xtime import Unit
from . import wire


class RemoteError(RuntimeError):
    def __init__(self, etype: str, message: str) -> None:
        super().__init__(message)
        self.etype = etype


class RpcClient:
    """Generic pooled request/response client over the wire framing; the
    base for RemoteNode (data plane) and RemoteKVStore (control plane)."""

    def __init__(
        self,
        host: str,
        port: int,
        pool_size: int = 4,
        timeout: float = 10.0,
    ) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self._pool: list[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_size = pool_size

    @classmethod
    def connect(cls, endpoint: str, **kwargs):
        """Build a client from a 'host:port' endpoint string (the one
        parser for placement/discovery endpoints)."""
        host, port = endpoint.rsplit(":", 1)
        return cls(host, int(port), **kwargs)

    # -- connection pool --

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _acquire(self) -> socket.socket:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._connect()

    def _release(self, sock: socket.socket) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(sock)
                return
        sock.close()

    def close(self) -> None:
        with self._pool_lock:
            for sock in self._pool:
                sock.close()
            self._pool.clear()

    def _call(self, op: str, _retry: bool = True, _timeout: float | None = None, **args):
        # trace propagation: when this RPC happens inside a traced request
        # (a span is active on this thread), it gets its own client span and
        # the context rides the wire so the server joins the same trace —
        # the per-process spans stitch into one tree (Dapper propagation).
        # Untraced calls (no active span) pay nothing.
        if TRACER.active() and op not in wire.UNTRACED_OPS:
            span = TRACER.span(f"rpc.client.{op}", peer=f"{self.host}:{self.port}")
        else:
            span = NOOP_SPAN
        with span:
            return self._call_traced(op, _retry, _timeout, args)

    def _call_traced(self, op: str, _retry: bool, _timeout: float | None, args: dict):
        req = wire.inject_trace({"op": op, **args}, TRACER.current_context())
        sock = self._acquire()
        try:
            if _timeout is not None:
                sock.settimeout(_timeout)
            wire.send_frame(sock, req)
            resp = wire.recv_frame(sock)
            if _timeout is not None:
                sock.settimeout(self.timeout)
        except (ConnectionError, OSError, ValueError):
            sock.close()
            if _retry:
                # one retry on a fresh connection (stale pooled socket)
                return self._call(op, _retry=False, _timeout=_timeout, **args)
            raise
        self._release(sock)
        if not resp.get("ok"):
            raise RemoteError(resp.get("etype", ""), resp.get("error", "remote error"))
        return resp.get("result")


class RemoteNode(RpcClient):
    def __init__(
        self,
        host: str,
        port: int,
        node_id: str | None = None,
        pool_size: int = 4,
        timeout: float = 10.0,
    ) -> None:
        super().__init__(host, port, pool_size=pool_size, timeout=timeout)
        self.id = node_id or f"{host}:{port}"
        self._shards_cache: tuple[float, set[int]] | None = None

    # -- node surface (mirrors testing/cluster.Node) --

    @property
    def is_up(self) -> bool:
        # optimistic: failures surface as exceptions the session counts
        return True

    def health(self) -> dict:
        return self._call("health")

    def write(self, ns, sid, t, v, unit=Unit.SECOND):
        return self._call("write", ns=ns, sid=sid, t=t, v=v, unit=int(unit))

    def write_batch(self, ns, entries):
        return self._call(
            "write_batch", ns=ns, entries=[list(e) for e in entries]
        )

    def write_tagged(self, ns, tags, t, v, unit=Unit.SECOND):
        return self._call(
            "write_tagged",
            ns=ns,
            tags=[[n, v2] for n, v2 in tags],
            t=t,
            v=v,
            unit=int(unit),
        )

    def write_tagged_batch(self, ns, entries):
        """entries: (tags, t, v, unit) — one framed RPC, per-entry errors."""
        return self._call(
            "write_tagged_batch",
            ns=ns,
            entries=[
                [[[n, v2] for n, v2 in tags], t, v, int(unit)]
                for tags, t, v, unit in entries
            ],
        )

    def read(self, ns, sid, start, end):
        return wire.dps_from_wire(
            self._call("fetch", ns=ns, sid=sid, start=start, end=end)
        )

    def fetch_blocks(self, ns, sid, start, end):
        return self._call("fetch_blocks", ns=ns, sid=sid, start=start, end=end)

    def fetch_tagged(self, ns, query, start, end, limit=None):
        return wire.series_from_wire(
            self._call(
                "fetch_tagged",
                ns=ns,
                query=wire.query_to_wire(query),
                start=start,
                end=end,
                limit=limit,
            )
        )

    def query_ids(self, ns, query, start, end, limit=None):
        return self._call(
            "query_ids",
            ns=ns,
            query=wire.query_to_wire(query),
            start=start,
            end=end,
            limit=limit,
        )

    def aggregate_query(self, ns, query, start, end, field_filter=None):
        out = self._call(
            "aggregate_query",
            ns=ns,
            query=wire.query_to_wire(query),
            start=start,
            end=end,
            field_filter=[bytes(f) for f in field_filter] if field_filter else None,
        )
        return {bytes(k): {bytes(v) for v in vs} for k, vs in out}

    def stream_shard(self, ns, shard):
        return wire.series_from_wire(self._call("stream_shard", ns=ns, shard=shard))

    def block_metadata(self, ns, shard):
        return self._call("block_metadata", ns=ns, shard=shard)

    def stream_series_blocks(self, ns, shard, items):
        out = self._call(
            "stream_series_blocks",
            ns=ns,
            shard=shard,
            items=[[sid, bs] for sid, bs in items],
        )
        return [(sid, bs, wire.dps_from_wire(dps)) for sid, bs, dps in out]

    def cache_stats(self) -> dict:
        return self._call("cache_stats")

    def resident_stats(self) -> dict:
        """HBM-resident compressed pool stats (m3_tpu/resident/)."""
        return self._call("resident_stats")

    def flush(self, ns, flush_before) -> list:
        """Seal buffered blocks before the cutoff (operator/CI surface)."""
        return self._call("flush", ns=ns, flush_before=flush_before)

    def scan_totals(self, ns, matchers, start, end) -> dict:
        """Raw-sample scan-and-aggregate; ``matchers``:
        [[name, op, value], ...] (see NodeService.op_scan_totals)."""
        return self._call(
            "scan_totals", ns=ns, matchers=list(matchers), start=start, end=end
        )

    def metrics(self) -> str:
        """Prometheus text exposition of the remote process (the universal
        scrape op every RpcServer answers via the middleware)."""
        return self._call("metrics")

    def traces(self, limit: int = 256) -> list[dict]:
        """The remote process's recent spans — merge with other processes'
        dumps by traceId to reassemble a cross-process trace."""
        return self._call("traces", limit=limit)

    def owned_shards(self, cache_secs: float = 1.0) -> set[int]:
        cached = self._shards_cache
        now = time.monotonic()
        if cached is not None and now - cached[0] < cache_secs:
            return cached[1]
        shards = set(self._call("owned_shards"))
        self._shards_cache = (now, shards)
        return shards

    def assign_shards(self, shards) -> None:
        self._shards_cache = None
        self._call("assign_shards", shards=sorted(shards))
