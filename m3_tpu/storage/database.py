"""Database → namespaces → shards → series: write/read routing + lifecycle.

Reference: /root/reference/src/dbnode/storage/ — storage.Database
(database.go: Write :573, ReadEncoded :842, Bootstrap :925, AssignShardSet
:386), dbNamespace (namespace.go, per-namespace retention/blockSize), dbShard
(shard.go: writeAndIndex :869, ReadEncoded :1060, Tick :663, WarmFlush :2146),
bootstrap chain (bootstrap/process.go:147: filesystem → commitlog → peers →
uninitialized).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from ..cache import BlockCache, BlockKey, CacheInvalidator, CacheOptions, DecodedBlock
from ..codec.m3tsz import Datapoint, decode
from ..resident import ResidentOptions, ResidentPool
from ..query import stats as query_stats
from ..utils.hash import shard_for
from ..utils.instrument import DEFAULT as METRICS
from ..utils.serialize import decode_tags, is_tag_id
from ..utils.trace import NOOP_SPAN, TRACER
from ..utils.xtime import Unit

# decoded bytes off the compressed-stream hot path (BENCH attribution:
# how much M3TSZ input each round actually decoded, cache hits excluded)
_M_DECODED_BYTES = METRICS.counter(
    "decoded_bytes_total", "compressed stream bytes decoded into arrays"
)
# a cold-flush volume bump makes every lower volume of the block
# unservable (the reader cache checks volume; caches/pool invalidate on
# the flush notification), so they are deleted eagerly instead of
# lingering on disk until retention expiry
_M_SUPERSEDED_DELETED = METRICS.counter(
    "db_superseded_volumes_deleted_total",
    "superseded fileset volumes deleted eagerly at cold-flush volume bump",
)
_M_ENCODE_LANES = METRICS.counter(
    "encode_device_lanes_total",
    "lanes sealed through the batched device m3tsz encode kernel",
)
_M_ENCODE_FALLBACK = METRICS.counter(
    "encode_host_fallback_lanes_total",
    "sealing lanes the kernel cannot take (annotated values, sub-second "
    "timestamps, mixed int/float, delta overflows) — encoded by the host "
    "codec, riding the same fileset and admission batch",
)
_M_ENCODE_BYTES = METRICS.counter(
    "encode_device_bytes_total",
    "compressed stream bytes produced by the device encode kernel",
)
from .commitlog import CommitLog, CommitLogEntry
from .faults import DiskFullError
from .fs import (
    CHUNK_K,
    CorruptFilesetError,
    FilesetID,
    FilesetReader,
    delete_fileset,
    fileset_complete,
    list_fileset_volumes,
    list_filesets,
    quarantine_fileset,
    read_index_ids,
    verify_fileset,
    write_fileset,
)

# --commitlog-sync mapping onto the CommitLog knobs: the acked-write loss
# bound per mode on a hard process kill (pinned by
# tests/test_storage_faults.py::test_commitlog_sync_loss_bounds):
#   every    acked => appended AND fsynced; zero acked-write loss
#   interval write-behind; loss bounded by flush_every/flush_interval
#   none     fsync only at explicit barriers (flush/rotate/close); loss
#            bounded by the OS+python buffers — fastest, replay gaps OK
COMMITLOG_SYNC_MODES: dict[str, dict] = {
    "every": {"write_behind": False, "flush_every": 1},
    "interval": {},
    "none": {"write_behind": True, "flush_every": 1 << 30, "flush_interval": 1e9},
}
from .series import NANOS, SeriesBuffer
from .snapshot import read_latest_snapshot, remove_snapshots, write_snapshot


class ColdWriteError(ValueError):
    """Write into a flushed block while cold writes are disabled
    (dbnode m3dberrors.ErrColdWritesNotEnabled)."""


class NewSeriesLimitError(RuntimeError):
    """New-series insert rate limit hit (kvconfig insert limit)."""


@dataclass
class NamespaceOptions:
    """namespace metadata (src/dbnode/namespace/options.go)."""

    retention_nanos: int = 2 * 24 * 3600 * NANOS
    block_size_nanos: int = 2 * 3600 * NANOS
    index_enabled: bool = True
    cold_writes_enabled: bool = True


class Shard:
    """dbShard: series map for one virtual shard.

    Reads go through a per-(block) FilesetReader cache (the role of
    persist/fs/seek_manager.go seeker cache + the wired list): a fileset is
    materialized once and reused until a newer volume replaces it or the
    block expires, instead of re-reading data+index+side files per read."""

    def __init__(
        self,
        shard_id: int,
        ns: str,
        opts: NamespaceOptions,
        base: str,
        cache: BlockCache | None = None,
        invalidator: CacheInvalidator | None = None,
        pool: ResidentPool | None = None,
        ingest_options=None,
    ) -> None:
        self.id = shard_id
        self.namespace = ns
        self.opts = opts
        self.base = base
        # device column write buffer (m3_tpu/ingest/): write batches
        # accumulate into (series_lane, slot) planes, sealed blocks
        # device-encode (ops/encode.py) and are born resident — opt-in
        # via Database(ingest_options=...) / dbnode --device-ingest
        self.ingest = None
        if ingest_options is not None and ingest_options.enabled:
            from ..ingest import ColumnWriteBuffer

            self.ingest = ColumnWriteBuffer(
                ingest_options, opts.block_size_nanos
            )
        # decoded-block cache (m3_tpu/cache/): sealed fileset blocks decode
        # once; the invalidator hooks write/flush/tick so nothing stale or
        # superseded stays resident
        self.cache = cache
        # HBM-resident compressed pool (m3_tpu/resident/): sealed blocks'
        # m3tsz bytes stay device-resident, admitted at flush/seal below
        self.pool = pool
        self.invalidator = invalidator or CacheInvalidator(cache, pool)
        # per-shard lock (shard.go RWMutex role): hot-path reads/writes
        # contend only within a shard; lifecycle ops (flush/tick) take the
        # database lock FIRST then shard locks, writers take only this one,
        # so the lock order is always db -> shard
        self.lock = threading.RLock()
        self.series: dict[bytes, SeriesBuffer] = {}
        self._flushed_blocks: set[int] = set()
        # block_start -> live bucket count across ALL series buffers: the
        # O(distinct buffered blocks) summary behind has_buffered_overlap.
        # Buckets exist only while they hold points (created on first
        # write, removed whole by flush/tick eviction), so a nonzero
        # count is exactly "some series has buffered data in this block".
        self._buffered_blocks: dict[int, int] = {}
        self._filesets: list[FilesetID] | None = None  # listdir cache
        self.fileset_epoch = 0  # bumps whenever the fileset set changes
        # block_start -> reader, LRU-bounded (wired_list.go:77 role: a cap on
        # resident block resources with least-recently-used eviction)
        self._readers: "OrderedDict[int, FilesetReader]" = OrderedDict()
        self.max_cached_readers = 128
        self.reader_materializations = 0  # observability: fileset loads

    def filesets(self) -> list[FilesetID]:
        with self.lock:
            if self._filesets is None:
                self._filesets = list_filesets(self.base, self.namespace, self.id)
            return self._filesets

    def _invalidate_filesets(self) -> None:
        self._filesets = None
        # monotone stamp of the shard's sealed-fileset topology: bumps on
        # every flush/retention/repair that changes the fileset set, so
        # the device query planner (query/plan.py) can revalidate a
        # cached plan's block set with one integer compare instead of a
        # per-query fileset listing
        self.fileset_epoch += 1

    def reader(self, fid: FilesetID) -> FilesetReader:
        with self.lock:
            return self._reader_locked(fid)

    def _reader_locked(self, fid: FilesetID) -> FilesetReader:
        cached = self._readers.get(fid.block_start)
        if cached is not None and cached.fid.volume == fid.volume:
            self._readers.move_to_end(fid.block_start)
            return cached
        try:
            reader = FilesetReader(self.base, fid)
        except CorruptFilesetError as exc:
            # verify-on-first-read tripped: the volume rotted on disk
            # after commit. Quarantine it and report the fileset missing —
            # every caller already survives a retention race deleting a
            # fileset mid-read, and subsequent listings exclude it, so the
            # shard degrades to peers/repair instead of erroring reads.
            self._quarantine_locked(fid, exc.problems)
            raise FileNotFoundError(f"fileset {fid} quarantined") from exc
        self.reader_materializations += 1
        self._readers[fid.block_start] = reader
        self._readers.move_to_end(fid.block_start)
        while len(self._readers) > self.max_cached_readers:
            self._readers.popitem(last=False)
        return reader

    def _reader_or_none_locked(self, fid: FilesetID) -> FilesetReader | None:
        """Reader, or None when the fileset vanished (retention race) or
        was just quarantined — the graceful-read spelling call sites use
        so corruption never surfaces as a client-visible error."""
        try:
            return self._reader_locked(fid)
        except FileNotFoundError:
            return None

    def reader_or_none(self, fid: FilesetID) -> FilesetReader | None:
        with self.lock:
            return self._reader_or_none_locked(fid)

    def _quarantine_locked(self, fid: FilesetID, problems: list) -> None:
        """Rename a corrupt volume aside and invalidate everything that
        could still serve its bytes: the reader LRU entry, the fileset
        listing cache + epoch (device query plans revalidate), the decoded
        cache and resident pool for the block. If no complete volume
        remains for the block it is no longer 'flushed', so bootstrap's
        peers source / the repair plane re-replicate it."""
        quarantine_fileset(self.base, fid, problems)
        self._readers.pop(fid.block_start, None)
        self._invalidate_filesets()
        remaining = [
            f
            for f in list_fileset_volumes(self.base, self.namespace, self.id)
            if f.block_start == fid.block_start
        ]
        if not remaining:
            self._flushed_blocks.discard(fid.block_start)
        self.invalidator.on_tick_expire(
            self.namespace, self.id, {fid.block_start}
        )

    def scrub(self) -> dict:
        """One verify pass over this shard's sealed filesets: every
        complete volume is digest-verified; mismatches quarantine. Returns
        {"scanned", "quarantined", "bytes"} for the scrubber's pacing."""
        from .fs import fileset_bytes

        scanned = quarantined = scrubbed_bytes = 0
        for fid in list_fileset_volumes(self.base, self.namespace, self.id):
            scrubbed_bytes += fileset_bytes(self.base, fid)
            problems = verify_fileset(self.base, fid)
            scanned += 1
            if problems:
                with self.lock:
                    # retention/supersede deletes run under the shard lock;
                    # re-verify under it so a fileset deleted mid-verify
                    # doesn't count as corruption
                    if fileset_complete(self.base, fid):
                        problems = verify_fileset(self.base, fid)
                        if problems:
                            self._quarantine_locked(fid, problems)
                            quarantined += 1
        return {
            "scanned": scanned,
            "quarantined": quarantined,
            "bytes": scrubbed_bytes,
        }

    def check_write(self, t_nanos: int) -> None:
        """Raise if a write at ``t_nanos`` would be rejected (shard.go:
        writes into flushed blocks need cold writes enabled)."""
        bs = (t_nanos // self.opts.block_size_nanos) * self.opts.block_size_nanos
        if bs in self._flushed_blocks and not self.opts.cold_writes_enabled:
            raise ColdWriteError(
                f"write at {t_nanos} targets flushed block {bs} and namespace "
                f"{self.namespace} has cold writes disabled"
            )

    def write(self, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        with self.lock:
            self.check_write(t_nanos)
            buf = self.series.get(sid)
            if buf is None:
                buf = SeriesBuffer(sid, self.opts.block_size_nanos)
                self.series[sid] = buf
            bs = (t_nanos // self.opts.block_size_nanos) * self.opts.block_size_nanos
            if bs not in buf.buckets:
                self._buffered_blocks[bs] = self._buffered_blocks.get(bs, 0) + 1
            buf.write(t_nanos, value, unit)
            if self.ingest is not None:
                self.ingest.append(sid, t_nanos, value, int(unit))
            self.invalidator.on_write(self.namespace, self.id, sid, bs)

    def _buffered_dec(self, block_start: int, n: int = 1) -> None:
        """Retire ``n`` evicted buckets from the buffered-block summary."""
        left = self._buffered_blocks.get(block_start)
        if left is None:
            return
        if left <= n:
            del self._buffered_blocks[block_start]
        else:
            self._buffered_blocks[block_start] = left - n

    def read(
        self, sid: bytes, start: int, end: int, populate_cache: bool = True
    ) -> list[Datapoint]:
        """``populate_cache=False`` serves lifecycle scans (repair digests,
        peer streaming): they read every series once and would otherwise
        flush the hot query working set out of the byte-budget LRU —
        cached entries are still used, but misses don't insert."""
        with self.lock:
            return self._read_locked(sid, start, end, populate_cache)

    def _read_locked(
        self, sid: bytes, start: int, end: int, populate_cache: bool = True
    ) -> list[Datapoint]:
        # flushed filesets first (older), then buffer segments: the
        # MultiReaderIterator's latest-segment-wins dedupe gives buffer
        # precedence over filesets (shard.go:1060 ReadEncoded ordering)
        from ..codec.iterator import MultiReaderIterator
        from ..codec.native_read import read_segments

        arrs = self._read_arrays_locked(sid, start, end, populate_cache)
        if arrs is not None:  # decoded-block cache path
            t, v, u = arrs
            return [
                Datapoint(tt, vv, Unit(uu))
                for tt, vv, uu in zip(t.tolist(), v.tolist(), u.tolist())
            ]
        segments = self._segments_locked(sid, start, end)
        fast = read_segments(segments, start, end)  # native decoder; None
        if fast is not None:  # when annotations must survive
            return fast
        it = MultiReaderIterator(segments)
        return [dp for dp in it if start <= dp.timestamp < end]

    def _read_arrays_locked(
        self, sid: bytes, start: int, end: int, populate_cache: bool = True
    ):
        """(times, values, units) for [start, end) via the decoded-block
        cache: sealed fileset blocks come from (or populate) the cache,
        live buffer buckets overlay on top (newest wins — the same
        precedence as the segment path). None → caller falls back (cache
        disabled, or an annotated stream that must keep Datapoint
        fidelity). ``populate_cache=False``: hits are served, misses
        decode without inserting (lifecycle scans must not evict the hot
        working set)."""
        cache = self.cache
        if cache is None:
            return None
        from ..codec.native_read import decode_stream_arrays, merge_segment_arrays

        bsz = self.opts.block_size_nanos
        triples = []
        for fid in self.filesets():
            if fid.block_start + bsz <= start or fid.block_start >= end:
                continue
            key = BlockKey(self.namespace, self.id, sid, fid.block_start, fid.volume)

            def _decode(fid=fid):
                reader = self._reader_or_none_locked(fid)
                stream = reader.stream(sid) if reader is not None else None
                _M_DECODED_BYTES.inc(len(stream) if stream else 0)
                arrs = decode_stream_arrays(stream or b"")
                return None if arrs is None else DecodedBlock(*arrs)

            if populate_cache:
                entry = cache.get_or_decode(key, _decode)
            else:
                entry = cache.get(key)
                if entry is None:
                    entry = _decode()
            if entry is None:
                return None  # annotated stream: segment-path fallback
            if len(entry):
                triples.append(entry.triple())
        buf = self.series.get(sid)
        if buf is not None:
            # buffer overlay: per-bucket decoded arrays, memoized on the
            # bucket until its next write (series.py merged_arrays keeps
            # codec-roundtrip parity with the segment path)
            for bs in sorted(buf.buckets):
                if bs + bsz <= start or bs >= end:
                    continue
                arrs = buf.buckets[bs].merged_arrays()
                if arrs is None:
                    return None  # annotated: segment-path fallback
                if len(arrs[0]):
                    triples.append(arrs)
        t, v, u = merge_segment_arrays(triples)
        lo = int(np.searchsorted(t, start, side="left"))
        hi = int(np.searchsorted(t, end, side="left"))
        return t[lo:hi], v[lo:hi], u[lo:hi]

    def read_arrays(self, sid: bytes, start: int, end: int):
        """Array read surface: (times i64, values f64, units) decoded
        arrays for [start, end) — cache-aware, always succeeds (annotated
        streams decode through the iterator path and re-materialize;
        straight to the iterator, not via _read_locked, which would retry
        the arrays path and re-decode everything)."""
        with self.lock:
            arrs = self._read_arrays_locked(sid, start, end)
            if arrs is not None:
                return arrs
            from ..codec.iterator import MultiReaderIterator
            from ..codec.native_read import read_segments_arrays

            segments = self._segments_locked(sid, start, end)
            _M_DECODED_BYTES.inc(sum(len(s) for s in segments))
            arrs = read_segments_arrays(segments, start, end)
            if arrs is not None:
                return arrs
            dps = [
                dp
                for dp in MultiReaderIterator(segments)
                if start <= dp.timestamp < end
            ]
        return (
            np.asarray([dp.timestamp for dp in dps], np.int64),
            np.asarray([dp.value for dp in dps], np.float64),
            np.asarray([int(dp.unit) for dp in dps], np.uint8),
        )

    def _segments_locked(self, sid: bytes, start: int, end: int) -> list[bytes]:
        """Raw encoded segments overlapping [start, end), oldest-first —
        the compressed-read surface (rpc.thrift fetchBlocksRaw role)."""
        segments: list[bytes] = []
        for fid in self.filesets():
            if fid.block_start + self.opts.block_size_nanos <= start or fid.block_start >= end:
                continue
            reader = self._reader_or_none_locked(fid)
            stream = reader.stream(sid) if reader is not None else None
            if stream:
                segments.append(stream)
        buf = self.series.get(sid)
        if buf is not None:
            segments.extend(buf.streams(start, end))
        return segments

    def fetch_blocks(self, sid: bytes, start: int, end: int) -> list[bytes]:
        with self.lock:
            return self._segments_locked(sid, start, end)

    def read_excluding(self, sid: bytes, exclude_blocks: set[int]) -> list[Datapoint]:
        """Full-range lifecycle read SKIPPING the given sealed blocks'
        fileset content; buffered overlays (including ones inside excluded
        blocks — cold writes not yet flushed there) still return. The
        peer-stream dedupe surface for migration: the receiver already
        holds those blocks' filesets byte-identically."""
        from ..codec.iterator import MultiReaderIterator

        with self.lock:
            segments: list[bytes] = []
            for fid in self.filesets():
                if fid.block_start in exclude_blocks:
                    continue
                reader = self._reader_or_none_locked(fid)
                stream = reader.stream(sid) if reader is not None else None
                if stream:
                    segments.append(stream)
            buf = self.series.get(sid)
            if buf is not None:
                segments.extend(buf.streams(0, 2**62))
        return [dp for dp in MultiReaderIterator(segments)]

    # --- resident-scan routing surface (m3_tpu/resident/) ---

    def scan_block_keys(self, sid: bytes, start: int, end: int):
        """(fileset BlockKeys overlapping [start, end), buffered) — the
        residency check input: the resident path may serve this series iff
        every key is resident (or its fileset is complete-admitted and the
        series is simply absent) AND no live buffer overlaps the range
        (buffer data overlays sealed blocks at read time; a resident-only
        scan would miss it)."""
        with self.lock:
            bsz = self.opts.block_size_nanos
            keys = [
                BlockKey(self.namespace, self.id, sid, fid.block_start, fid.volume)
                for fid in self.filesets()
                if not (fid.block_start + bsz <= start or fid.block_start >= end)
            ]
            buf = self.series.get(sid)
            buffered = buf is not None and buf.has_points(start, end)
            return keys, buffered

    def has_buffered_overlap(self, start: int, end: int) -> bool:
        """True when ANY live series buffer holds points in [start, end)
        — the shard-level buffer-overlay gate the device query planner
        checks per execution (a fused plan reads sealed residency only,
        so one buffered point in range degrades the whole query to the
        staged path, which applies the per-series overlay rule). Served
        from the maintained block-start summary: O(distinct buffered
        blocks) regardless of how many series are ingesting, so a
        heavily ingesting shard answering historical queries pays a few
        integer compares, not a walk of every live buffer."""
        bsz = self.opts.block_size_nanos
        with self.lock:
            return any(
                bs + bsz > start and bs < end for bs in self._buffered_blocks
            )

    def scan_segments(self, sid: bytes, start: int, end: int) -> list[tuple]:
        """[(stream, datapoint_bound, chunk_k)] for the STREAMED scan
        path, in the same lane order the resident path uses (filesets by
        block start, then buffer buckets). Bounds come from fileset index
        entries (n_chunks * chunk_k) / buffer write counts — an upper
        bound is enough: extra decode steps land on done lanes and drop
        out of every reduction. chunk_k is the fileset's persisted chunkK
        (the resident path decodes with it via the admitted side planes,
        so the streamed twin must prescan with the SAME chunk size for
        the bit-for-bit parity contract to hold); buffer buckets have no
        fileset and report the default."""
        with self.lock:
            out: list[tuple] = []
            bsz = self.opts.block_size_nanos
            for fid in self.filesets():
                if fid.block_start + bsz <= start or fid.block_start >= end:
                    continue
                reader = self._reader_or_none_locked(fid)
                if reader is None:
                    continue
                entry = reader._lookup(sid) if reader.bloom.test(sid) else None
                if entry is None:
                    continue
                stream = reader.stream(sid)
                if not stream:
                    continue
                chunk_k = int(reader.info.get("chunkK", CHUNK_K))
                out.append((stream, entry[3] * chunk_k, chunk_k))
            buf = self.series.get(sid)
            if buf is not None:
                for bs in sorted(buf.buckets):
                    if bs + bsz <= start or bs >= end:
                        continue
                    bucket = buf.buckets[bs]
                    stream = bucket.merged_stream()
                    if stream:
                        out.append((stream, len(bucket.times), CHUNK_K))
            return out

    def warm_flush(self, flush_before_nanos: int) -> list[FilesetID]:
        """shard.go:2146 — write filesets for complete blocks, then evict.

        With device ingest on, sealed blocks encode through the batched
        m3tsz kernel (ops/encode.py) and are BORN resident: the fileset
        persists from the device-encoded bytes and admission gathers the
        pages device->device (pool.admit_block_device) instead of
        re-reading and re-uploading the fileset."""
        with self.lock:
            flushed, device_payload = self._warm_flush_locked(flush_before_nanos)
            device_blocks = {(p[0], p[1]) for p in device_payload}
            payload = self._collect_admission_locked(
                [
                    f
                    for f in flushed
                    if (f.block_start, f.volume) not in device_blocks
                ]
            )
        self._admit_payload(payload)
        self._admit_device_payload(device_payload)
        return flushed

    def _seal_encode_locked(self, bs: int, buckets: list):
        """Device-encode one sealing block: ``buckets`` is
        ``[(sid, BufferBucket)]``. Returns ``(series_streams,
        fileset_side_rows, device_payload | None)`` where device_payload
        is ``(block_start, volume_placeholder, words, dev_items,
        host_items, chunk_k)`` admission input — volume is patched by
        the caller. Ineligible lanes (annotated values, sub-second
        timestamps, mixed int/float, overflows) fall back to the host
        codec and ride the SAME admission batch as host items."""
        from ..ops import encode as dev

        if self.ingest is not None:
            # release the sealed window's frame + clean/dirty accounting
            # (the columns themselves are read off the canonical merged
            # buckets; a clean lane's merge is a no-op)
            self.ingest.seal_window(bs)
        series: dict[bytes, bytes] = {}
        side_rows: dict[bytes, object] = {}
        host_items: list[tuple] = []
        eligible: list[tuple] = []
        for sid, bucket in buckets:
            t, v, u = bucket.merged_points()
            kind = dev.classify_lane(t, v, u).kind
            if kind == dev.KIND_NONE:
                stream = bucket.merged_stream()
                if stream:
                    series[sid] = stream
                    host_items.append((sid, stream, len(t)))
            else:
                eligible.append((sid, t, v, kind))
        _M_ENCODE_FALLBACK.inc(len(host_items))
        if not eligible:
            return series, side_rows, None
        pw = (
            self.pool.options.page_words
            if self.pool is not None and self.pool.enabled
            else 1
        )
        lanes = [(c[1], c[2]) for c in eligible]
        res = dev.encode_lanes(
            lanes, [c[3] for c in eligible], k=CHUNK_K, round_words_to=pw
        )
        rows = dev.side_rows_for(res, lanes, bs)
        streams = res.streams()
        _M_ENCODE_LANES.inc(len(eligible))
        _M_ENCODE_BYTES.inc(int(res.nbytes.sum()))
        dev_items = []
        for m, (sid, t, v, kind) in enumerate(eligible):
            series[sid] = streams[m]
            side_rows[sid] = rows[m]
            dev_items.append(
                (
                    sid,
                    m,
                    int(res.nbytes[m]),
                    int(res.n_chunks[m]),
                    dev.lane_max_span(res, m),
                    rows[m],
                )
            )
        return series, side_rows, (bs, 0, res.words, dev_items, host_items, CHUNK_K)

    def _admit_device_payload(self, payload: list) -> int:
        """Stage-2 admission of device-encoded seals (outside the shard
        lock, like :meth:`_admit_payload`): pages gather device->device,
        zero stream-byte upload; host-fallback lanes of the same block
        ride the same batch and pay the normal upload."""
        if self.pool is None or not self.pool.enabled:
            return 0
        admitted = 0
        for block_start, volume, words, items, host_items, chunk_k in payload:
            res = self.pool.admit_block_device(
                self.namespace, self.id, block_start, volume, words, items,
                chunk_k=chunk_k, host_items=host_items,
            )
            admitted += res.admitted
        return admitted

    def _warm_flush_locked(self, flush_before_nanos: int):
        blocks: dict[int, list] = {}
        for sid, buf in self.series.items():
            for bs, bucket in buf.buckets.items():
                if (
                    bs + buf.block_size <= flush_before_nanos
                    and bucket.times
                    and bs not in self._flushed_blocks
                ):
                    blocks.setdefault(bs, []).append((sid, bucket))
        flushed = []
        device_payload = []
        for bs, buckets in sorted(blocks.items()):
            if self.ingest is not None:
                series, side_rows, dev_payload = self._seal_encode_locked(
                    bs, buckets
                )
            else:
                series = {
                    sid: stream
                    for sid, bucket in buckets
                    for stream in [bucket.merged_stream()]
                    if stream
                }
                side_rows, dev_payload = {}, None
            if not series:
                continue
            fid = FilesetID(self.namespace, self.id, bs, volume=0)
            write_fileset(
                self.base, fid, series, self.opts.block_size_nanos, CHUNK_K,
                side_rows=side_rows or None,
            )
            self._flushed_blocks.add(bs)
            flushed.append(fid)
            if dev_payload is not None:
                device_payload.append(dev_payload)
        if flushed:
            self._invalidate_filesets()
            self.invalidator.on_flush(self.namespace, self.id, flushed)
        # evict only what this flush made durable — cold writes into
        # previously-flushed blocks stay buffered for cold_flush
        for buf in self.series.values():
            for fid in flushed:
                if buf.evict_block(fid.block_start):
                    self._buffered_dec(fid.block_start)
        # drop buffers the flush emptied (tick would anyway): keeps the
        # sealed-only fast path O(1) for has_buffered_overlap instead of
        # walking thousands of empty buckets per query
        for sid in [s for s, buf in self.series.items() if not buf.buckets]:
            del self.series[sid]
        return flushed, device_payload

    def cold_flush(self, flush_before_nanos: int) -> list[FilesetID]:
        """shard.go:2212 + persist/fs/merger.go — out-of-order writes into
        already-flushed blocks merge with the existing fileset ONCE PER BLOCK
        (all cold series together) and go out as one new volume."""
        with self.lock:
            flushed = self._cold_flush_locked(flush_before_nanos)
            payload = self._collect_admission_locked(flushed)
        self._admit_payload(payload)
        return flushed

    def _cold_flush_locked(self, flush_before_nanos: int) -> list[FilesetID]:
        # gather every cold stream per block first, so each block merges once
        cold: dict[int, dict[bytes, bytes]] = {}
        for sid, buf in list(self.series.items()):
            for bs, stream in buf.streams_before(flush_before_nanos).items():
                if bs in self._flushed_blocks and stream:
                    cold.setdefault(bs, {})[sid] = stream
        flushed = []
        for bs, updates in sorted(cold.items()):
            prev = next((f for f in self.filesets() if f.block_start == bs), None)
            series: dict[bytes, bytes] = {}
            reader = self._reader_or_none_locked(prev) if prev is not None else None
            if reader is not None:
                for other in reader.series_ids:
                    series[other] = reader.stream(other) or b""
            from ..codec.m3tsz import Encoder

            for sid, stream in updates.items():
                merged: dict[int, Datapoint] = {}
                if sid in series:
                    for dp in decode(series[sid]):
                        merged[dp.timestamp] = dp
                for dp in decode(stream):
                    merged[dp.timestamp] = dp
                enc = Encoder(min(merged))
                for t in sorted(merged):
                    dp = merged[t]
                    enc.encode(dp.timestamp, dp.value, unit=dp.unit)
                series[sid] = enc.stream()
            vol = (prev.volume + 1) if prev is not None else 0
            fid = FilesetID(self.namespace, self.id, bs, volume=vol)
            write_fileset(self.base, fid, series, self.opts.block_size_nanos, CHUNK_K)
            flushed.append(fid)
            # eager superseded-volume cleanup: every lower volume of this
            # block can never serve a read again (the reader cache checks
            # volume; caches/pool invalidate on the flush notification
            # below), so delete it NOW instead of letting it linger on
            # disk until retention expiry
            for old in list_fileset_volumes(self.base, self.namespace, self.id):
                if old.block_start == bs and old.volume < vol:
                    delete_fileset(self.base, old)
                    _M_SUPERSEDED_DELETED.inc()
            for sid in updates:
                if self.series[sid].evict_block(bs):
                    self._buffered_dec(bs)
        if flushed:
            self._invalidate_filesets()
            # a cold flush writes a NEW volume per block: every cached
            # entry of a lower volume is superseded and can never hit
            self.invalidator.on_flush(self.namespace, self.id, flushed)
        return flushed

    def _collect_admission_locked(self, fids: list[FilesetID]) -> list[tuple]:
        """Seal-time residency admission, stage 1 (under the shard lock):
        resolve each flushed fileset's reader and FORCE its full index
        parse — the only mutable state the off-lock stage touches.
        Everything else (bloom probes, index lookups against the parsed
        table, mmap'd data slices) is read-only on an immutable fileset,
        so the O(fileset bytes) stream read-back runs lock-free in
        stage 2."""
        if self.pool is None or not self.pool.enabled:
            return []
        payload = []
        for fid in fids:
            reader = self._reader_locked(fid)
            chunk_k = int(reader.info.get("chunkK", CHUNK_K))
            payload.append(
                (fid.block_start, fid.volume, reader, dict(reader.index), chunk_k)
            )
        return payload

    def _admit_payload(self, payload: list[tuple], readmission: bool = False) -> int:
        """Seal-time residency admission, stage 2 (OUTSIDE the shard
        lock): the fileset read-back, staging-array build, host->device
        upload, and any first-shape XLA scatter compile must not stall
        the shard's hot read/write path. Each lane rides with the
        fileset's PERSISTED per-chunk side table (fs.side_table) so the
        pool pages the chunk metadata into its device side planes without
        re-running the prescan — the chunk-parallel resident decoder's
        shapes then match the streamed path's exactly (same snapshots,
        same chunk_k), which keeps the two paths' decode programs (and
        f32 reduction trees) identical. Racing mutations stay correct
        without the lock: a write landing between collect and admit
        leaves buffered points that force the query router's streamed
        fallback (buffer-overlay check), and a superseding flush admits a
        HIGHER volume the router prefers; a retention expiry racing in
        leaves only an unreachable entry that ages out of the LRU.
        Returns the number of admitted lanes."""
        admitted = 0
        for block_start, volume, reader, index, chunk_k in payload:
            items = []
            for sid, (_, _, _, n_chunks) in index.items():
                stream = reader.stream(sid)
                if stream:
                    items.append(
                        (sid, stream, n_chunks * chunk_k, reader.side_table(sid))
                    )
            res = self.pool.admit_block(
                self.namespace, self.id, block_start, volume, items,
                chunk_k=chunk_k, readmission=readmission,
            )
            admitted += res.admitted
        return admitted

    def readmit_fileset(self, fid: FilesetID) -> int:
        """Read-through re-admission: re-read one sealed fileset and
        admit it into the resident pool, keeping the two-phase admission
        discipline (collect under the shard lock, admit outside it) in
        THIS layer — callers (query routing) never touch the shard's
        lock or admission internals. Returns admitted lanes; 0 when
        retention raced the fileset away (in EITHER phase: the admit
        phase re-reads stream/side bytes off the fileset too)."""
        try:
            with self.lock:
                payload = self._collect_admission_locked([fid])
            return self._admit_payload(payload, readmission=True)
        except FileNotFoundError:
            return 0

    def tick(self, now_nanos: int) -> None:
        """shard.go:663 tickAndExpire: drop series/blocks past retention,
        expired filesets off disk, and stale cached readers."""
        with self.lock:
            self._tick_locked(now_nanos)

    def _tick_locked(self, now_nanos: int) -> None:
        expire_before = now_nanos - self.opts.retention_nanos
        for sid in list(self.series):
            buf = self.series[sid]
            for bs in buf.evict_before(expire_before):
                self._buffered_dec(bs)
            if not buf.buckets:
                del self.series[sid]
        if self.ingest is not None:
            for bs in self.ingest.open_windows():
                if bs + self.opts.block_size_nanos <= expire_before:
                    self.ingest.drop_window(bs)
        bsz = self.opts.block_size_nanos
        expired = [
            fid
            for fid in list_fileset_volumes(self.base, self.namespace, self.id)
            if fid.block_start + bsz <= expire_before
        ]
        for fid in expired:
            delete_fileset(self.base, fid)
            self._flushed_blocks.discard(fid.block_start)
            self._readers.pop(fid.block_start, None)
        if expired:
            self._invalidate_filesets()
            self.invalidator.on_tick_expire(
                self.namespace, self.id, {fid.block_start for fid in expired}
            )


class Namespace:
    def __init__(
        self,
        name: str,
        opts: NamespaceOptions,
        num_shards: int,
        base: str,
        cache: BlockCache | None = None,
        invalidator: CacheInvalidator | None = None,
        pool: ResidentPool | None = None,
        index_store=None,
        ingest_options=None,
    ) -> None:
        self.name = name
        self.opts = opts
        self.num_shards = num_shards
        self.shards = [
            Shard(
                i, name, opts, base, cache=cache, invalidator=invalidator,
                pool=pool, ingest_options=ingest_options,
            )
            for i in range(num_shards)
        ]
        self.index = None
        if opts.index_enabled:
            from ..index.ns_index import NamespaceIndex

            self.index = NamespaceIndex(
                opts.block_size_nanos, opts.retention_nanos,
                device_store=index_store,
            )

    def shard_for(self, sid: bytes) -> Shard:
        return self.shards[shard_for(sid, self.num_shards)]


class Database:
    """Top-level storage node object (database.go)."""

    def __init__(
        self,
        base_dir: str,
        num_shards: int = 8,
        commitlog_enabled: bool = True,
        cache_options: CacheOptions | None = None,
        resident_options: ResidentOptions | None = None,
        index_device_options=None,
        ingest_options=None,
        commitlog_sync: str = "interval",
    ) -> None:
        self.base = base_dir
        self.num_shards = num_shards
        self.namespaces: dict[str, Namespace] = {}
        self.commitlog_enabled = commitlog_enabled
        if commitlog_sync not in COMMITLOG_SYNC_MODES:
            raise ValueError(
                f"commitlog_sync must be one of {sorted(COMMITLOG_SYNC_MODES)}, "
                f"got {commitlog_sync!r}"
            )
        self.commitlog_sync = commitlog_sync
        # decoded-block cache, shared across namespaces/shards (one byte
        # budget per node, like the reference's process-wide wired list)
        self.cache_options = cache_options or CacheOptions()
        self.block_cache = (
            BlockCache(self.cache_options)
            if self.cache_options.enabled and self.cache_options.max_bytes > 0
            else None
        )
        # HBM-resident compressed pool, one device byte budget per node
        # (m3_tpu/resident/): sealed blocks admit at flush, warm scans
        # decode from HBM. Off by default — an opt-in mode via
        # resident_options / dbnode --resident-bytes.
        self.resident_options = resident_options or ResidentOptions(enabled=False)
        self.resident_pool = (
            ResidentPool(self.resident_options)
            if self.resident_options.enabled and self.resident_options.max_bytes > 0
            else None
        )
        # device-resident inverted index (m3_tpu/index/device/): one
        # byte budget per node like the pool above; sealed index
        # segments admit at seal and queries plan onto batched kernels.
        # Off by default — opt-in via dbnode --index-device-bytes.
        from ..index.device import IndexDeviceOptions

        self.index_device_options = index_device_options or IndexDeviceOptions(
            enabled=False
        )
        self.index_device_store = None
        if (
            self.index_device_options.enabled
            and self.index_device_options.max_bytes > 0
        ):
            from ..index.device import DeviceIndexStore

            self.index_device_store = DeviceIndexStore(self.index_device_options)
        # device-side ingest (m3_tpu/ingest/): write batches mirror into
        # per-shard column planes so seal device-encodes and admits
        # born-resident. Off by default — opt-in via dbnode --device-ingest.
        self.ingest_options = ingest_options
        self.cache_invalidator = CacheInvalidator(self.block_cache, self.resident_pool)
        self._commitlogs: dict[str, CommitLog] = {}
        self.bootstrapped = False
        # self-observability (x/instrument role). Write/read counters are
        # labeled {ns=...} (cardinality = operator-bounded namespace count)
        # so the self-scrape pipeline can SKIP the reserved `_m3tpu`
        # namespace's children when snapshotting — the collector's own
        # storage writes never re-enter the telemetry it stores
        # (selfmon/guard.py invariant 2). Children resolve once per
        # namespace; after that a write costs one dict lookup.
        self._m_writes: dict[str, object] = {}
        self._m_reads: dict[str, object] = {}
        self._m_write_errors: dict[str, object] = {}
        # new-series insert rate limit (runtime options; 0 = unlimited)
        self._new_series_limit = 0
        self._new_series_window = (0, 0)  # (second, count)
        self._limit_lock = threading.Lock()
        # Lifecycle lock: create_namespace / flush / snapshot / tick /
        # bootstrap / stream_shard. Hot-path reads and writes take ONLY the
        # per-shard locks (shard.go RWMutex granularity); lifecycle ops take
        # this lock first, then shard locks, so the order is always
        # db -> shard and a flush of one shard never blocks reads of others.
        self.lock = threading.RLock()

    def create_namespace(self, name: str, opts: NamespaceOptions | None = None) -> Namespace:
        # resolve the namespace's write/read counter children eagerly so
        # the families exist in the exposition from boot (scrape targets
        # and tools/check_metrics.py expect them before the first write)
        self._writes_counter(name)
        self._reads_counter(name)
        self._write_errors_counter(name)
        with self.lock:
            ns = Namespace(
                name,
                opts or NamespaceOptions(),
                self.num_shards,
                self.base,
                cache=self.block_cache,
                invalidator=self.cache_invalidator,
                pool=self.resident_pool,
                index_store=self.index_device_store,
                ingest_options=self.ingest_options,
            )
            self.namespaces[name] = ns
            if self.commitlog_enabled:
                self._commitlogs[name] = CommitLog(
                    self._commitlog_dir(name),
                    **COMMITLOG_SYNC_MODES[self.commitlog_sync],
                )
            return ns

    def _commitlog_dir(self, ns: str) -> str:
        return os.path.join(self.base, "commitlogs", ns)

    # per-namespace counter children resolve once; a benign race hands both
    # writers the SAME registry child, so the dict update is lock-free

    def _writes_counter(self, ns: str):
        c = self._m_writes.get(ns)
        if c is None:
            c = self._m_writes[ns] = METRICS.counter(
                "db_writes_total", "datapoint writes", labels={"ns": ns}
            )
        return c

    def _reads_counter(self, ns: str):
        c = self._m_reads.get(ns)
        if c is None:
            c = self._m_reads[ns] = METRICS.counter(
                "db_reads_total", "series reads", labels={"ns": ns}
            )
        return c

    def _write_errors_counter(self, ns: str):
        c = self._m_write_errors.get(ns)
        if c is None:
            c = self._m_write_errors[ns] = METRICS.counter(
                "db_write_errors_total", "rejected datapoint writes",
                labels={"ns": ns},
            )
        return c

    def write(
        self, ns: str, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND
    ) -> None:
        # reserved-namespace rule (selfmon/guard.py): only the tagged
        # self-scrape pipeline may write `_m3tpu*` telemetry namespaces
        from ..selfmon.guard import check_write

        check_write(ns)
        namespace = self.namespaces[ns]
        shard = namespace.shard_for(sid)
        cl = self._commitlogs.get(ns)
        if cl is not None and cl.disk_full:
            # shed before buffering: an accepted point the WAL cannot land
            # would be unreplayable after a crash. Typed retryable — the
            # client backs off and the write succeeds once space frees.
            raise DiskFullError(f"commit log disk full: {ns}")
        with shard.lock:
            with self._limit_lock:
                is_new = self._check_new_series(shard, sid)
            # buffer first so rejected writes (ColdWriteError) never reach the
            # WAL — a logged-but-unacceptable entry would poison replay
            try:
                shard.write(sid, t_nanos, value, unit)
            except Exception:
                self._write_errors_counter(ns).inc()
                raise
            if is_new and self._new_series_limit > 0:
                with self._limit_lock:
                    self._consume_new_series()
            # WAL append under the shard lock: buffer apply and log entry
            # are one atomic unit per series, so replay order can't diverge
            # from the order reads observed (the WAL lock nests inside
            # shard locks everywhere)
            cl = self._commitlogs.get(ns)
            if cl is not None:
                cl.write(CommitLogEntry(sid, t_nanos, value, unit))
        self._writes_counter(ns).inc()

    def write_batch(self, ns: str, entries: list[tuple[bytes, int, float]]) -> None:
        """Batched ingest, flattened to one tight loop per shard: entries
        group by shard (one lock acquisition each), then append directly
        into the raw-column buffer buckets — the per-entry method chain
        (Shard.write → SeriesBuffer.write → BufferBucket.write) cost ~12µs
        per datapoint and capped node ingest at ~80k writes/s/core. If an
        entry is rejected midway (a flush can seal a block between
        entries), everything ALREADY applied is still WAL-logged before
        the error propagates, so no applied write is ever unlogged."""
        from .series import BufferBucket, SeriesBuffer

        from ..selfmon.guard import check_write

        check_write(ns)
        namespace = self.namespaces[ns]
        cl = self._commitlogs.get(ns)
        if cl is not None and cl.disk_full:
            # shed the whole batch before buffering (see write())
            raise DiskFullError(f"commit log disk full: {ns}")
        limit_on = self._new_series_limit > 0
        unit_s = int(Unit.SECOND)
        # shard routing for the whole batch in ONE native murmur3 call
        # (the pure-python hash costs ~4µs/id; exact parity tested) —
        # python per-id fallback without the lib
        from .. import native

        shard_ids = native.shard_batch([e[0] for e in entries], namespace.num_shards)
        by_shard: dict[int, tuple] = {}
        if shard_ids is None:
            ns_shard_for = namespace.shard_for
            for e in entries:
                sh = ns_shard_for(e[0])
                rec = by_shard.get(sh.id)
                if rec is None:
                    rec = by_shard[sh.id] = (sh, [])
                rec[1].append(e)
        else:
            shards = namespace.shards
            for e, si in zip(entries, shard_ids.tolist()):
                rec = by_shard.get(si)
                if rec is None:
                    rec = by_shard[si] = (shards[si], [])
                rec[1].append(e)
        applied: list[CommitLogEntry] = []
        cache = self.block_cache
        pool = self.resident_pool
        touched: set = set()
        try:
            for sh, items in by_shard.values():
                bsz = sh.opts.block_size_nanos
                cold_ok = sh.opts.cold_writes_enabled
                flushed = sh._flushed_blocks
                with sh.lock:
                    # decided UNDER the shard lock: cache entries for this
                    # shard's keys are only created by readers holding this
                    # lock (pool entries by flushes, which also hold it), so
                    # an empty cache AND pool here (the common case during
                    # ingest-heavy phases) safely skips the per-item set
                    # insert
                    collect = (cache is not None and len(cache) > 0) or (
                        pool is not None and len(pool) > 0
                    )
                    series = sh.series
                    for sid, t, v in items:
                        bs = (t // bsz) * bsz
                        if bs in flushed and not cold_ok:
                            raise ColdWriteError(
                                f"write at {t} targets flushed block {bs} and "
                                f"namespace {sh.namespace} has cold writes disabled"
                            )
                        if collect:
                            touched.add((sh.id, sid, bs))
                        buf = series.get(sid)
                        if buf is None:
                            if limit_on:
                                with self._limit_lock:
                                    self._check_new_series(sh, sid)
                                    self._consume_new_series()
                            buf = series[sid] = SeriesBuffer(sid, bsz)
                        bucket = buf.buckets.get(bs)
                        if bucket is None:
                            bucket = buf.buckets[bs] = BufferBucket(block_start=bs)
                            buffered = sh._buffered_blocks
                            buffered[bs] = buffered.get(bs, 0) + 1
                        bucket.times.append(t)
                        bucket.values.append(v)
                        bucket.units.append(unit_s)
                        if t > bucket.last_write_nanos:
                            bucket.last_write_nanos = t
                        bucket.num_writes += 1
                        bucket._stream_cache = None
                        bucket._arrays_cache = None
                        applied.append(CommitLogEntry(sid, t, v))
                    if sh.ingest is not None and items:
                        # mirror the batch into the device column planes
                        # (one vectorized append per shard, not per point);
                        # spilled rows just lose the device-seal shortcut —
                        # the bucket append above stays the source of truth
                        sh.ingest.append_batch(
                            [e[0] for e in items],
                            [e[1] for e in items],
                            [e[2] for e in items],
                            [unit_s] * len(items),
                        )
            self._writes_counter(ns).inc(len(applied))
        finally:
            if touched:
                for shard_id, sid, bs in touched:
                    self.cache_invalidator.on_write(ns, shard_id, sid, bs)
            if cl is not None and applied:
                cl.write_batch(applied)

    def apply_runtime_options(self, ro) -> None:
        """storage/runtime.py listener target: live-tunable node knobs."""
        with self.lock:
            self._new_series_limit = int(ro.write_new_series_limit_per_sec)

    def _check_new_series(self, shard: Shard, sid: bytes) -> bool:
        """ClusterNewSeriesInsertLimit (kvconfig): cap NEW series creations
        per second across the node; existing-series writes are unaffected.
        Returns whether the write WOULD create a series; the token is only
        consumed after the write succeeds (_consume_new_series), so rejected
        writes don't burn quota."""
        is_new = sid not in shard.series
        if self._new_series_limit <= 0 or not is_new:
            return is_new
        import time as _time

        now_s = int(_time.monotonic())
        sec, count = self._new_series_window
        if sec != now_s:
            sec, count = now_s, 0
            self._new_series_window = (sec, count)
        if count >= self._new_series_limit:
            raise NewSeriesLimitError(
                f"new series insert limit {self._new_series_limit}/s exceeded"
            )
        return True

    def _consume_new_series(self) -> None:
        sec, count = self._new_series_window
        self._new_series_window = (sec, count + 1)

    def read(self, ns: str, sid: bytes, start: int, end: int) -> list[Datapoint]:
        self._reads_counter(ns).inc()
        # per-shard locking (inside Shard.read): reads don't serialize
        # against other shards or the database lifecycle lock
        return self.namespaces[ns].shard_for(sid).read(sid, start, end)

    def read_arrays(self, ns: str, sid: bytes, start: int, end: int):
        """Decoded (times i64, values f64, units) arrays for one series —
        the cache-aware array read surface query engines consume without
        materializing per-point Datapoint objects."""
        self._reads_counter(ns).inc()
        return self.namespaces[ns].shard_for(sid).read_arrays(sid, start, end)

    def fetch_blocks(self, ns: str, sid: bytes, start: int, end: int) -> list[bytes]:
        """Compressed read surface: raw encoded segments overlapping the
        range, oldest-first (rpc.thrift fetchBlocksRaw; the client session
        merges replicas' segments with the SeriesIterator stack instead of
        shipping decoded datapoints)."""
        self._reads_counter(ns).inc()
        return self.namespaces[ns].shard_for(sid).fetch_blocks(sid, start, end)

    # --- tagged write / index query path (database.go:606 WriteTagged,
    # :785 QueryIDs; network FetchTagged mirrors this) ---

    def write_tagged(
        self, ns: str, tags, t_nanos: int, value: float, unit: Unit = Unit.SECOND
    ) -> bytes:
        from ..rules.rules import encode_tags_id

        sid = encode_tags_id(tags)
        namespace = self.namespaces[ns]
        # data first: a rejected write (ColdWriteError) must not leave a
        # phantom entry in the reverse index
        self.write(ns, sid, t_nanos, value, unit)
        if namespace.index is not None:
            namespace.index.write(sid, tags, t_nanos)
        return sid

    def write_tagged_batch(self, ns: str, entries) -> list[str | None]:
        """Batched tagged writes with PER-ENTRY error isolation (the node
        side of the client's host queue, rpc.thrift writeTaggedBatchRaw +
        per-element error semantics). ``entries``: (tags, t_nanos, value,
        unit). Returns one error string or None per entry, in order."""
        errs: list[str | None] = []
        for tags, t, v, unit in entries:
            try:
                self.write_tagged(
                    ns,
                    tuple((bytes(a), bytes(b)) for a, b in tags),
                    t,
                    v,
                    Unit(unit),
                )
                errs.append(None)
            except Exception as exc:
                errs.append(f"{type(exc).__name__}: {exc}")
        return errs

    def query_ids(self, ns: str, query, start: int, end: int, limit: int | None = None,
                  force_host: bool = False):
        """Index resolution (QueryIDs). ``force_host`` bypasses the
        device index tier — the parity surface check_index and the
        property suite diff the device executor against."""
        namespace = self.namespaces[ns]
        if namespace.index is None:
            raise RuntimeError(f"namespace {ns} has no index")
        with query_stats.stage("index_resolve"):
            return namespace.index.query(
                query, start, end, limit=limit, force_host=force_host
            )

    def aggregate_query(
        self, ns: str, query, start: int, end: int, field_filter=None
    ):
        """AggregateQuery (storage/index.go:1218): distinct field names →
        values over matched docs (labels / label-values endpoints)."""
        namespace = self.namespaces[ns]
        if namespace.index is None:
            raise RuntimeError(f"namespace {ns} has no index")
        return namespace.index.aggregate_query(
            query, start, end, field_filter=field_filter
        )

    def fetch_tagged(
        self, ns: str, query, start: int, end: int, limit: int | None = None
    ) -> list[tuple[bytes, tuple, list[Datapoint]]]:
        """Index query + per-series read (the FetchTagged server path,
        tchannelthrift/node/service.go:626). Inside a traced request (e.g.
        a server-side RPC span) the index-resolve + decode work gets a
        storage span so stitched traces show where node time went."""
        span = (
            TRACER.span("storage.fetch_tagged", namespace=ns)
            if TRACER.active()
            else NOOP_SPAN
        )
        with span:
            result = self.query_ids(ns, query, start, end, limit=limit)
            out = []
            with query_stats.stage("decode"):
                for doc in result.docs:
                    out.append(
                        (doc.id, doc.fields, self.read(ns, doc.id, start, end))
                    )
            span.set_tag("series", len(out))
        return out

    def fetch_tagged_arrays(
        self, ns: str, query, start: int, end: int, limit: int | None = None,
        docs=None,
    ) -> list[tuple[bytes, tuple, tuple]]:
        """FetchTagged on the array surface: (sid, tags, (times, values))
        per matched series, served through the decoded-block cache.
        ``docs``: pre-resolved index docs — callers that already ran
        query_ids (the residency router) skip the second resolution."""
        span = (
            TRACER.span("storage.fetch_tagged", namespace=ns)
            if TRACER.active()
            else NOOP_SPAN
        )
        with span:
            if docs is None:
                docs = self.query_ids(ns, query, start, end, limit=limit).docs
            out = []
            with query_stats.stage("decode"):
                for doc in docs:
                    t, v, _u = self.read_arrays(ns, doc.id, start, end)
                    out.append((doc.id, doc.fields, (t, v)))
            span.set_tag("series", len(out))
        return out

    def cache_stats(self) -> dict:
        """Decoded-block cache stats for debug/status endpoints."""
        if self.block_cache is None:
            return {"enabled": False}
        return {"enabled": True, **self.block_cache.stats()}

    def resident_stats(self) -> dict:
        """Resident-pool stats for debug/status endpoints, plus the
        streamed-fallback byte counter so one call answers 'are warm scans
        moving block bytes?' (tools/check_resident.py asserts the deltas
        are zero across a warm resident scan)."""
        if self.resident_pool is None:
            return {"enabled": False}
        from ..resident.scan import _M_STREAMED_BYTES

        return {
            **self.resident_pool.stats(),
            "streamed_bytes": _M_STREAMED_BYTES.value,
        }

    def resident_clear(self) -> int:
        """Drop every resident entry (operator/debug surface — the wire
        face lets tools/check_resident.py exercise eviction churn + the
        read-through re-admission path against a live node). Returns the
        number of entries dropped; duplicate-safe (clearing an empty pool
        clears nothing)."""
        if self.resident_pool is None:
            return 0
        return self.resident_pool.clear()

    def index_stats(self) -> dict:
        """Device-index-tier + postings-cache stats for debug/status
        endpoints (the `index_stats` wire op and /debug/dump's
        index.json): store budget/occupancy/eviction counters plus
        per-namespace block/segment counts and cache effectiveness."""
        out: dict = {
            "enabled": self.index_device_store is not None,
            "namespaces": {},
        }
        if self.index_device_store is not None:
            out.update(self.index_device_store.stats())
        with self.lock:
            namespaces = list(self.namespaces.items())
        for name, ns in namespaces:
            ix = ns.index
            if ix is None:
                continue
            with ix.lock:
                blocks = list(ix.blocks.values())
            sealed = sum(len(b.sealed) for b in blocks)
            device_resident = sum(
                1
                for b in blocks
                for s in b.sealed
                if getattr(s, "resident", False)
            )
            out["namespaces"][name] = {
                "blocks": len(blocks),
                "sealed_segments": sealed,
                "device_resident_segments": device_resident,
                "postings_cache": ix.postings_cache.stats(),
            }
        return out

    def stream_shard(self, ns: str, shard_id: int, exclude_blocks=()) -> list:
        """Peer streaming (FetchBootstrapBlocksFromPeers / repair source):
        every (sid, tags, datapoints) owned by one shard; tags come from the
        reverse index when available. ``exclude_blocks`` skips sealed
        blocks whose fileset content the receiver already imported via
        migration — their data would otherwise re-enter the receiver's
        write path, re-buffer, and wreck the warm-before-cutover contract
        (a buffered overlay forces the streamed scan path). Buffered
        overlays in excluded blocks still stream: they are NOT in the
        migrated fileset."""
        excl = set(exclude_blocks)
        with self.lock:
            namespace = self.namespaces[ns]
            sh = namespace.shards[shard_id]
            with sh.lock:
                sids = set(sh.series)
                for fid in sh.filesets():
                    if fid.block_start in excl:
                        continue
                    reader = sh._reader_or_none_locked(fid)
                    if reader is not None:
                        sids.update(reader.series_ids)
            docs: dict[bytes, tuple] = {}
            if namespace.index is not None and sids:
                with namespace.index.lock:
                    blocks = list(namespace.index.blocks.values())
                for blk in blocks:
                    for seg in blk.segments:
                        for d in seg.docs:
                            if d.id in sids:
                                docs.setdefault(d.id, d.fields)
            out = []
            for sid in sorted(sids):
                # a peer-streaming sweep reads every series once — don't
                # let it evict the hot query working set
                if excl:
                    dps = sh.read_excluding(sid, excl)
                else:
                    dps = sh.read(sid, 0, 2**62, populate_cache=False)
                if dps:
                    out.append((sid, docs.get(sid, ()), dps))
            return out

    def admit_imported_fileset(self, ns: str, shard_id: int, fid: FilesetID) -> int:
        """Post-commit bookkeeping for a migration-imported sealed
        fileset: mark the block flushed, bump the shard's fileset epoch
        (cached query plans revalidate their block set), invalidate any
        superseded decoded/pool entries of lower volumes (on_flush — the
        receiver may have served this block from an older fileset before
        the handoff), re-index the imported series, then warm the
        resident pool by re-admitting the fileset's compressed pages +
        packed side planes. The pool's three-phase publish means a query
        NEVER observes a partially-admitted block — it streams from the
        (already committed) fileset until the group completes. Returns
        admitted lanes (0 when the budget pushed back; the fileset still
        serves streamed reads)."""
        namespace = self.namespaces[ns]
        sh = namespace.shards[shard_id]
        with sh.lock:
            sh._flushed_blocks.add(fid.block_start)
            sh._invalidate_filesets()
        sh.invalidator.on_flush(ns, shard_id, [fid])
        try:
            for sid in read_index_ids(self.base, fid):
                self._reindex(namespace, sid, fid.block_start)
        except FileNotFoundError:
            return 0  # retention raced the import away
        return sh.readmit_fileset(fid)

    def flush(self, ns: str, flush_before_nanos: int) -> list[FilesetID]:
        with TRACER.span("db.flush", namespace=ns):
            with self.lock:
                namespace = self.namespaces[ns]
                out = []
                for shard in namespace.shards:
                    out.extend(shard.warm_flush(flush_before_nanos))
                    if namespace.opts.cold_writes_enabled:
                        out.extend(shard.cold_flush(flush_before_nanos))
                # Rotate the WAL, then drop only sealed segments whose every entry
                # is now durable in a flushed fileset. Coverage is BLOCK-aligned:
                # only entries whose whole block is before the cutoff were
                # flushed (streams_before), so an entry in a partial block at the
                # cutoff edge keeps its segment alive. With cold writes enabled,
                # warm+cold flush together make every such point durable; with
                # cold writes disabled, writes into flushed blocks are rejected
                # at write time (never logged), so the same coverage rule holds
                # (the reference removes commit logs only once covered by
                # snapshot/fileset data — storage/cleanup.go).
                cl = self._commitlogs.get(ns)
                bsz = namespace.opts.block_size_nanos
                if cl is not None:
                    cl.rotate()
                    cl.cleanup(
                        lambda e: (e.time_nanos // bsz) * bsz + bsz
                        <= flush_before_nanos
                    )
                # Snapshots whose every record now lives in a flushed block are
                # covered by filesets; drop them so bootstrap doesn't re-buffer
                # flushed points (storage/cleanup.go snapshot cleanup).
                for shard in namespace.shards:
                    snap = read_latest_snapshot(self.base, ns, shard.id)
                    if snap and all(
                        bs + bsz <= flush_before_nanos and bs in shard._flushed_blocks
                        for _, bs, _, _ in snap
                    ):
                        remove_snapshots(self.base, ns, shard.id)
                # WarmFlush of index blocks (storage/index.go:868): seal + persist
                if namespace.index is not None:
                    namespace.index.persist_before(self.base, ns, flush_before_nanos)
                return out

    def snapshot(self, ns: str) -> int:
        """shard.go:2335 Snapshot: capture every un-flushed buffer stream so
        commit-log replay is bounded. Returns the number of records written.
        All sealed WAL segments become removable afterwards: their entries are
        either in flushed filesets or in this snapshot."""
        with TRACER.span("db.snapshot", namespace=ns):
            with self.lock:
                namespace = self.namespaces[ns]
                total = 0
                for shard in namespace.shards:
                    with shard.lock:  # consistent buffer capture vs writers
                        vol_now = {f.block_start: f.volume for f in shard.filesets()}
                        records = []
                        for sid, buf in shard.series.items():
                            for bs, bucket in buf.buckets.items():
                                stream = bucket.merged_stream()
                                if stream:
                                    records.append(
                                        (sid, bs, stream, vol_now.get(bs, -1))
                                    )
                    if records:
                        write_snapshot(self.base, ns, shard.id, records)
                    else:
                        # nothing buffered: an absent snapshot says the same
                        # thing as an empty one without the file churn
                        remove_snapshots(self.base, ns, shard.id)
                    total += len(records)
                cl = self._commitlogs.get(ns)
                if cl is not None:
                    cl.rotate()
                    cl.remove_inactive()
                return total

    def scrub(self, ns: str | None = None) -> dict:
        """One verify pass over sealed filesets (op_scrub lands here; the
        background Scrubber daemon does its own per-volume walk so it can
        pace to a byte budget): every complete volume
        is digest-verified; mismatched/torn volumes quarantine with full
        cache/pool/index invalidation and the shard falls back to the
        peer/repair machinery. Returns {"scanned","quarantined","bytes"}."""
        totals = {"scanned": 0, "quarantined": 0, "bytes": 0}
        names = [ns] if ns is not None else list(self.namespaces)
        for name in names:
            namespace = self.namespaces[name]
            for shard in namespace.shards:
                r = shard.scrub()
                for k in totals:
                    totals[k] += r[k]
        return totals

    def tick(self, now_nanos: int) -> None:
        """storage/mediator.go tick: expire buffers, filesets, and index
        blocks past retention (including their persisted segment files)."""
        with self.lock:
            for name, ns in list(self.namespaces.items()):
                for shard in ns.shards:
                    shard.tick(now_nanos)
                if ns.index is not None:
                    ns.index.evict_before(
                        now_nanos - ns.opts.retention_nanos, self.base, name
                    )

    # --- bootstrap chain (bootstrap/process.go:147) ---

    def _reindex(self, namespace: Namespace, sid: bytes, t_nanos: int) -> None:
        """Rebuild reverse-index state for a recovered series. Series IDs are
        the canonical tag wire format (utils/serialize.py), so tags are
        recoverable from the ID alone."""
        if namespace.index is not None and is_tag_id(sid):
            try:
                tags = tuple(sorted(decode_tags(sid)))
            except ValueError:
                return
            namespace.index.write(sid, tags, t_nanos)

    def bootstrap(
        self,
        peers_source=None,
        shard_filter: set[int] | None = None,
        now_nanos: int | None = None,
        has_peer_with_shard=None,
    ) -> dict:
        """Run the bootstrapper chain with shard-time-range accounting:
        filesystem → commitlog+snapshot → peers → uninitialized
        (bootstrap/process.go:147). Each source claims the block ranges it
        fulfilled; the remainder passes down the chain.

        - filesystem marks flushed blocks (fileset data reads lazily) and
          re-indexes flushed series;
        - commitlog+snapshot restores buffered streams and replays WAL
          segments — replay never skips entries: a replayed point that also
          exists in a flushed fileset dedupes at read/merge time, whereas
          skipping loses cold writes not yet cold-flushed;
        - peers (``peers_source(ns, shard) -> series|None``) streams shards
          with no local provenance from replicas
          (bootstrapper/peers/source.go:117) — wired by ClusterDatabase for
          shards gained via placement change (AssignShardSet,
          database.go:386);
        - uninitialized claims what no replica can serve.

        ``shard_filter`` restricts the pass to gained shards on a live node.
        """
        with TRACER.span("db.bootstrap"):
            result = {
                "commitlog_entries": 0,
                "filesets": 0,
                "snapshot_records": 0,
                "quarantined": 0,
                "sources": {},
            }
            for name, ns in list(self.namespaces.items()):
                r = self._bootstrap_namespace(
                    name, ns, peers_source, shard_filter, now_nanos, result,
                    has_peer_with_shard,
                )
                result["sources"][name] = {
                    "target_blocks": r.target_blocks,
                    "fulfilled": dict(r.fulfilled_by_source),
                    "unfulfilled": r.unfulfilled,
                }
            if shard_filter is None:
                # full (re)start: warm the resident pool from discovered
                # filesets — gained-shard passes skip this (their data
                # arrives through the write path and admits at flush)
                self._readmit_resident()
            self.bootstrapped = True
            return result

    def _readmit_resident(self) -> None:
        """Restart warm-up for the residency mode: admission is a
        flush-time event, so blocks sealed by a PREVIOUS process would
        otherwise never re-admit and every historical query would stream
        forever. Admit discovered filesets NEWEST-first until the pool's
        budget pushes back (recency is the best eviction-order prior we
        have at boot; later flushes keep rotating newer blocks in via
        LRU); read-through re-admission (query/m3_storage.py) pulls back
        anything demand proves hot after that."""
        pool = self.resident_pool
        if pool is None or not pool.enabled:
            return
        work = []
        for ns in self.namespaces.values():
            for shard in ns.shards:
                for fid in shard.filesets():
                    work.append((fid.block_start, shard, fid))
        work.sort(key=lambda t: -t[0])
        for _, shard, fid in work:
            with shard.lock:
                payload = shard._collect_admission_locked([fid])
            for block_start, volume, reader, index, chunk_k in payload:
                items = []
                for sid, (_, _, _, n_chunks) in index.items():
                    stream = reader.stream(sid)
                    if stream:
                        items.append(
                            (sid, stream, n_chunks * chunk_k,
                             reader.side_table(sid))
                        )
                res = pool.admit_block(
                    shard.namespace, shard.id, block_start, volume, items,
                    chunk_k=chunk_k,
                )
                if res.rejected_budget:
                    return  # budget full: the newest blocks are resident

    def bootstrap_shards(
        self, shard_ids, peers_source=None, has_peer_with_shard=None
    ) -> dict:
        """Bootstrap only the given (newly gained) shards through the full
        chain — the AssignShardSet → queued-bootstrap path (database.go:386,
        :442)."""
        result = self.bootstrap(
            peers_source=peers_source,
            shard_filter=set(shard_ids),
            has_peer_with_shard=has_peer_with_shard,
        )
        # durability barrier BEFORE the caller CASes the shards AVAILABLE:
        # once the source's LEAVING copy is dropped, this replica's WAL may
        # be the only record of the streamed data
        self.flush_wals()
        return result

    def flush_wals(self) -> None:
        """Barrier-fsync every namespace's commit log (write-behind WALs
        ack before fsync; callers needing a durability point use this)."""
        for cl in list(self._commitlogs.values()):
            cl.flush()

    def _bootstrap_namespace(
        self, name: str, ns: Namespace, peers_source, shard_filter, now_nanos,
        result, has_peer_with_shard=None,
    ):
        from .bootstrap import BootstrapProcess, ShardTimeRanges, uninitialized_source

        bsz = ns.opts.block_size_nanos
        shards = [
            sh for sh in ns.shards if shard_filter is None or sh.id in shard_filter
        ]
        shard_ids = [sh.id for sh in shards]
        by_id = {sh.id: sh for sh in shards}

        # Re-buffering a point that already sits in a flushed fileset would
        # make the next cold_flush rewrite an identical volume, so snapshot
        # records and commitlog entries for flushed blocks are checked
        # against the fileset first (decoded lazily, cached per
        # (shard, block, series)). Points NOT in the fileset are genuine
        # un-flushed cold writes and must replay.
        pts: dict[tuple[int, int, bytes], dict[int, float]] = {}

        def _covered(sh: Shard, sid: bytes, t_nanos: int, value: float) -> bool:
            bs = (t_nanos // bsz) * bsz
            if bs not in sh._flushed_blocks:
                return False
            fid = next((f for f in sh.filesets() if f.block_start == bs), None)
            if fid is None:
                return False
            pk = (sh.id, bs, sid)
            if pk not in pts:
                reader = sh.reader_or_none(fid)
                stream = reader.stream(sid) if reader is not None else None
                pts[pk] = (
                    {dp.timestamp: dp.value for dp in decode(stream)}
                    if stream
                    else {}
                )
            return pts[pk].get(t_nanos) == value

        def _restore(sh: Shard, sid: bytes, t: int, v: float, unit) -> bool:
            if _covered(sh, sid, t, v):
                return False
            try:
                sh.write(sid, t, v, unit)
            except ColdWriteError:
                # pre-crash WAL/snapshot entry in a flushed block of a
                # cold-disabled namespace whose value changed: drop it
                return False
            return True

        # --- chain sources (each claims block ranges it fulfilled) ---

        def fs_source(ns_name: str, remaining: ShardTimeRanges) -> ShardTimeRanges:
            fulfilled = ShardTimeRanges()
            with self.lock:
                persisted: set[int] = set()
                if ns.index is not None:
                    persisted = ns.index.load_persisted(self.base, ns_name)
                for shard in shards:
                    # bootstrap-open verification: digest-check every
                    # discovered volume BEFORE trusting it as provenance.
                    # A corrupt winner quarantines and the re-listing may
                    # surface an older complete volume; blocks left with
                    # no clean volume stay unfulfilled here and fall
                    # through the chain to peers.
                    with shard.lock:
                        while True:
                            fids = shard.filesets()
                            bad = next(
                                (
                                    (fid, problems)
                                    for fid in fids
                                    if (problems := verify_fileset(self.base, fid))
                                ),
                                None,
                            )
                            if bad is None:
                                break
                            shard._quarantine_locked(bad[0], bad[1])
                            result["quarantined"] += 1
                    result["filesets"] += len(fids)
                    for fid in fids:
                        shard._flushed_blocks.add(fid.block_start)
                        fulfilled.add(shard.id, fid.block_start)
                        if fid.block_start in persisted:
                            continue
                        for sid in read_index_ids(self.base, fid):
                            self._reindex(ns, sid, fid.block_start)
            return fulfilled

        def commitlog_snapshot_source(
            ns_name: str, remaining: ShardTimeRanges
        ) -> ShardTimeRanges:
            fulfilled = ShardTimeRanges()
            with self.lock:
                for shard in shards:
                    snap = snapshots.get(shard.id)
                    if not snap:
                        continue
                    vol_now = {f.block_start: f.volume for f in shard.filesets()}
                    for sid, bs, stream, rec_vol in snap:
                        # Ordering vs filesets (the recorded volume is the
                        # arbiter): every warm/cold flush bumps the block's
                        # fileset volume, so a volume that has advanced since
                        # the snapshot means the fileset superseded this
                        # record — restoring it would shadow newer flushed
                        # values (buffer wins on read dedupe). An unchanged
                        # volume means the record is a cold-write overlay
                        # NEWER than the fileset.
                        if vol_now.get(bs, -1) > rec_vol:
                            continue
                        for dp in decode(stream):
                            _restore(shard, sid, dp.timestamp, dp.value, dp.unit)
                        fulfilled.add(shard.id, bs)
                        self._reindex(ns, sid, bs)
                    result["snapshot_records"] += len(snap)
                # The WAL is totally ordered, so for duplicate (sid, t) the
                # LAST entry is the live value (an earlier entry may be a
                # stale overwrite whose newer value now lives only in a
                # fileset — replaying it would shadow the fileset).
                final: dict[tuple[bytes, int], CommitLogEntry] = {}
                replayed = 0
                for e in wal_entries:
                    sh = shard_of[e.series_id]
                    if sh.id not in by_id:
                        continue  # outside this pass's shard filter
                    final[(e.series_id, e.time_nanos)] = e
                    replayed += 1
                for e in final.values():
                    sh = shard_of[e.series_id]
                    fulfilled.add(sh.id, (e.time_nanos // bsz) * bsz)
                    if _covered(sh, e.series_id, e.time_nanos, e.value):
                        continue
                    # value differs from (or is absent in) the fileset: with
                    # last-wins dedupe the only such survivors are post-flush
                    # cold writes, so replay them
                    if _restore(sh, e.series_id, e.time_nanos, e.value, e.unit):
                        self._reindex(ns, e.series_id, e.time_nanos)
                result["commitlog_entries"] += replayed
            return fulfilled

        def peers_src(ns_name: str, remaining: ShardTimeRanges) -> ShardTimeRanges:
            fulfilled = ShardTimeRanges()
            if peers_source is None:
                return fulfilled
            # replication context: peer-streamed reserved-namespace
            # telemetry was admitted by a sanctioned writer on the source
            # replica — moving it here must not trip the selfmon guard
            # (and its ReservedNamespaceError is a ValueError, which the
            # skip below would otherwise silently eat)
            from ..selfmon.guard import selfmon_writer

            for shard_id in remaining.shards():
                series = peers_source(ns_name, shard_id)
                if series is None:
                    continue  # no reachable replica holds this shard
                with selfmon_writer():
                    for sid, tags, dps in series:
                        for dp in dps:
                            # full write path: WAL-logged (a restart before
                            # the next flush must be able to replay this
                            # replica's copy) and indexed per point (series
                            # spanning several index blocks stay queryable
                            # in each)
                            try:
                                if tags:
                                    self.write_tagged(
                                        ns_name, tags, dp.timestamp, dp.value, dp.unit
                                    )
                                else:
                                    self.write(
                                        ns_name, sid, dp.timestamp, dp.value, dp.unit
                                    )
                                    self._reindex(ns, sid, dp.timestamp)
                            except (ColdWriteError, ValueError):
                                continue
                # a reachable peer hands over everything it has for the
                # shard: the remaining ranges are fulfilled (blocks with no
                # data are legitimately empty on the replica too)
                fulfilled.add_shard_blocks(shard_id, remaining.ranges[shard_id])
            return fulfilled

        # target = retention window (live operation) ∪ locally discovered
        # blocks (restarts with data older than the window still replay);
        # the WAL and each shard's snapshot are read ONCE here and reused
        # by the commitlog+snapshot source
        import time as _time

        now = int(_time.time() * NANOS) if now_nanos is None else now_nanos
        target = ShardTimeRanges.for_window(
            shard_ids, now - ns.opts.retention_nanos, now + bsz, bsz
        )
        snapshots: dict[int, list] = {}
        with self.lock:
            wal_entries = CommitLog.replay(self._commitlog_dir(name))
            # replay hashes every entry's sid up to three times across the
            # bootstrap passes: route all UNIQUE sids in one native murmur3
            # call (python per-id fallback), then the passes dict-lookup
            from .. import native as _native

            _uniq = list({e.series_id for e in wal_entries})
            _sb = _native.shard_batch(_uniq, ns.num_shards)
            if _sb is not None:
                shard_of = {
                    sid: ns.shards[si] for sid, si in zip(_uniq, _sb.tolist())
                }
            else:
                shard_of = {sid: ns.shard_for(sid) for sid in _uniq}
            for shard in shards:
                for fid in shard.filesets():
                    target.add(shard.id, fid.block_start)
                snap = read_latest_snapshot(self.base, name, shard.id)
                snapshots[shard.id] = snap or []
                for _, bs, _, _ in snap or ():
                    target.add(shard.id, bs)
            for e in wal_entries:
                sh = shard_of[e.series_id]
                if sh.id in by_id:
                    target.add(sh.id, (e.time_nanos // bsz) * bsz)

        process = BootstrapProcess(
            [
                ("filesystem", fs_source),
                ("commitlog_snapshot", commitlog_snapshot_source),
                ("peers", peers_src),
                # uninitialized claims ranges only when topology says NO
                # replica holds the shard (fresh cluster) — an unreachable
                # replica leaves them unfulfilled so the caller retries
                ("uninitialized", uninitialized_source(has_peer_with_shard)),
            ]
        )
        return process.run(name, target)

    def close(self) -> None:
        with self.lock:
            for cl in list(self._commitlogs.values()):
                cl.close()
