"""Database → namespaces → shards → series: write/read routing + lifecycle.

Reference: /root/reference/src/dbnode/storage/ — storage.Database
(database.go: Write :573, ReadEncoded :842, Bootstrap :925, AssignShardSet
:386), dbNamespace (namespace.go, per-namespace retention/blockSize), dbShard
(shard.go: writeAndIndex :869, ReadEncoded :1060, Tick :663, WarmFlush :2146),
bootstrap chain (bootstrap/process.go:147: filesystem → commitlog → peers →
uninitialized).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..codec.m3tsz import Datapoint, decode
from ..utils.hash import shard_for
from ..utils.xtime import Unit
from .commitlog import CommitLog, CommitLogEntry
from .fs import CHUNK_K, FilesetID, FilesetReader, list_filesets, write_fileset
from .series import NANOS, SeriesBuffer


@dataclass
class NamespaceOptions:
    """namespace metadata (src/dbnode/namespace/options.go)."""

    retention_nanos: int = 2 * 24 * 3600 * NANOS
    block_size_nanos: int = 2 * 3600 * NANOS
    index_enabled: bool = True
    cold_writes_enabled: bool = True


class Shard:
    """dbShard: series map for one virtual shard."""

    def __init__(self, shard_id: int, ns: str, opts: NamespaceOptions, base: str) -> None:
        self.id = shard_id
        self.namespace = ns
        self.opts = opts
        self.base = base
        self.series: dict[bytes, SeriesBuffer] = {}
        self._flushed_blocks: set[int] = set()

    def write(self, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        buf = self.series.get(sid)
        if buf is None:
            buf = SeriesBuffer(sid, self.opts.block_size_nanos)
            self.series[sid] = buf
        buf.write(t_nanos, value, unit)

    def read(self, sid: bytes, start: int, end: int) -> list[Datapoint]:
        out: list[Datapoint] = []
        # flushed filesets first (older), then buffer (newer wins on dupes)
        for fid in list_filesets(self.base, self.namespace, self.id):
            if fid.block_start + self.opts.block_size_nanos <= start or fid.block_start >= end:
                continue
            reader = FilesetReader(self.base, fid)
            stream = reader.stream(sid)
            if stream:
                out.extend(dp for dp in decode(stream) if start <= dp.timestamp < end)
        buf = self.series.get(sid)
        if buf is not None:
            out.extend(buf.read(start, end))
        dedup: dict[int, Datapoint] = {}
        for dp in out:
            dedup[dp.timestamp] = dp
        return [dedup[t] for t in sorted(dedup)]

    def warm_flush(self, flush_before_nanos: int) -> list[FilesetID]:
        """shard.go:2146 — write filesets for complete blocks, then evict."""
        blocks: dict[int, dict[bytes, bytes]] = {}
        for sid, buf in self.series.items():
            for bs, stream in buf.streams_before(flush_before_nanos).items():
                if stream and bs not in self._flushed_blocks:
                    blocks.setdefault(bs, {})[sid] = stream
        flushed = []
        for bs, series in sorted(blocks.items()):
            fid = FilesetID(self.namespace, self.id, bs, volume=0)
            write_fileset(self.base, fid, series, self.opts.block_size_nanos, CHUNK_K)
            self._flushed_blocks.add(bs)
            flushed.append(fid)
        # evict only what this flush made durable — cold writes into
        # previously-flushed blocks stay buffered for cold_flush
        for buf in self.series.values():
            for fid in flushed:
                buf.evict_block(fid.block_start)
        return flushed

    def cold_flush(self, flush_before_nanos: int) -> list[FilesetID]:
        """shard.go:2212 — out-of-order writes into already-flushed blocks go
        out as a new volume merged with the existing fileset."""
        flushed = []
        for sid, buf in list(self.series.items()):
            for bs, stream in buf.streams_before(flush_before_nanos).items():
                if bs not in self._flushed_blocks or not stream:
                    continue
                existing = list_filesets(self.base, self.namespace, self.id)
                prev = next((f for f in existing if f.block_start == bs), None)
                series: dict[bytes, bytes] = {}
                if prev is not None:
                    reader = FilesetReader(self.base, prev)
                    for other in reader.series_ids:
                        series[other] = reader.stream(other) or b""
                # merge this series' new points with any flushed ones
                merged: dict[int, Datapoint] = {}
                if sid in series:
                    for dp in decode(series[sid]):
                        merged[dp.timestamp] = dp
                for dp in decode(stream):
                    merged[dp.timestamp] = dp
                from ..codec.m3tsz import Encoder

                enc = Encoder(min(merged))
                for t in sorted(merged):
                    dp = merged[t]
                    enc.encode(dp.timestamp, dp.value, unit=dp.unit)
                series[sid] = enc.stream()
                vol = (prev.volume + 1) if prev is not None else 0
                fid = FilesetID(self.namespace, self.id, bs, volume=vol)
                write_fileset(self.base, fid, series, self.opts.block_size_nanos, CHUNK_K)
                flushed.append(fid)
                buf.evict_block(bs)
        return flushed

    def tick(self, now_nanos: int) -> None:
        """shard.go:663 tickAndExpire: drop series/blocks past retention."""
        expire_before = now_nanos - self.opts.retention_nanos
        for sid in list(self.series):
            buf = self.series[sid]
            buf.evict_before(expire_before)
            if not buf.buckets:
                del self.series[sid]


class Namespace:
    def __init__(self, name: str, opts: NamespaceOptions, num_shards: int, base: str) -> None:
        self.name = name
        self.opts = opts
        self.num_shards = num_shards
        self.shards = [Shard(i, name, opts, base) for i in range(num_shards)]
        self.index = None
        if opts.index_enabled:
            from ..index.ns_index import NamespaceIndex

            self.index = NamespaceIndex(opts.block_size_nanos, opts.retention_nanos)

    def shard_for(self, sid: bytes) -> Shard:
        return self.shards[shard_for(sid, self.num_shards)]


class Database:
    """Top-level storage node object (database.go)."""

    def __init__(self, base_dir: str, num_shards: int = 8, commitlog_enabled: bool = True) -> None:
        self.base = base_dir
        self.num_shards = num_shards
        self.namespaces: dict[str, Namespace] = {}
        self.commitlog_enabled = commitlog_enabled
        self._commitlogs: dict[str, CommitLog] = {}
        self.bootstrapped = False

    def create_namespace(self, name: str, opts: NamespaceOptions | None = None) -> Namespace:
        ns = Namespace(name, opts or NamespaceOptions(), self.num_shards, self.base)
        self.namespaces[name] = ns
        if self.commitlog_enabled:
            self._commitlogs[name] = CommitLog(self._commitlog_path(name))
        return ns

    def _commitlog_path(self, ns: str) -> str:
        return os.path.join(self.base, "commitlogs", f"{ns}.wal")

    def write(
        self, ns: str, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND
    ) -> None:
        namespace = self.namespaces[ns]
        cl = self._commitlogs.get(ns)
        if cl is not None:
            cl.write(CommitLogEntry(sid, t_nanos, value, unit))
        namespace.shard_for(sid).write(sid, t_nanos, value, unit)

    def write_batch(self, ns: str, entries: list[tuple[bytes, int, float]]) -> None:
        namespace = self.namespaces[ns]
        cl = self._commitlogs.get(ns)
        if cl is not None:
            cl.write_batch(
                [CommitLogEntry(sid, t, v) for sid, t, v in entries]
            )
        for sid, t, v in entries:
            namespace.shard_for(sid).write(sid, t, v)

    def read(self, ns: str, sid: bytes, start: int, end: int) -> list[Datapoint]:
        return self.namespaces[ns].shard_for(sid).read(sid, start, end)

    # --- tagged write / index query path (database.go:606 WriteTagged,
    # :785 QueryIDs; network FetchTagged mirrors this) ---

    def write_tagged(
        self, ns: str, tags, t_nanos: int, value: float, unit: Unit = Unit.SECOND
    ) -> bytes:
        from ..rules.rules import encode_tags_id

        sid = encode_tags_id(tags)
        namespace = self.namespaces[ns]
        if namespace.index is not None:
            namespace.index.write(sid, tags, t_nanos)
        self.write(ns, sid, t_nanos, value, unit)
        return sid

    def query_ids(self, ns: str, query, start: int, end: int, limit: int | None = None):
        namespace = self.namespaces[ns]
        if namespace.index is None:
            raise RuntimeError(f"namespace {ns} has no index")
        return namespace.index.query(query, start, end, limit=limit)

    def fetch_tagged(
        self, ns: str, query, start: int, end: int, limit: int | None = None
    ) -> list[tuple[bytes, tuple, list[Datapoint]]]:
        """Index query + per-series read (the FetchTagged server path,
        tchannelthrift/node/service.go:626)."""
        result = self.query_ids(ns, query, start, end, limit=limit)
        out = []
        for doc in result.docs:
            out.append((doc.id, doc.fields, self.read(ns, doc.id, start, end)))
        return out

    def flush(self, ns: str, flush_before_nanos: int) -> list[FilesetID]:
        out = []
        for shard in self.namespaces[ns].shards:
            out.extend(shard.warm_flush(flush_before_nanos))
            if self.namespaces[ns].opts.cold_writes_enabled:
                out.extend(shard.cold_flush(flush_before_nanos))
        # flushed data is durable: rotate the WAL (snapshot+truncate role)
        cl = self._commitlogs.get(ns)
        if cl is not None:
            old = cl.rotate(self._commitlog_path(ns) + ".new")
            os.replace(cl.path, old)
            cl.path = old
        return out

    def tick(self, now_nanos: int) -> None:
        for ns in self.namespaces.values():
            for shard in ns.shards:
                shard.tick(now_nanos)

    # --- bootstrap chain (bootstrap/process.go:147) ---

    def bootstrap(self) -> dict:
        """filesystem → commitlog → (peers, uninitialized) — the fs source is
        implicit (filesets are read lazily at query time once complete); the
        commitlog source replays WAL entries into buffers."""
        result = {"commitlog_entries": 0, "filesets": 0}
        for name, ns in self.namespaces.items():
            for shard in ns.shards:
                fids = list_filesets(self.base, name, shard.id)
                result["filesets"] += len(fids)
                for fid in fids:
                    shard._flushed_blocks.add(fid.block_start)
            entries = CommitLog.replay(self._commitlog_path(name))
            for e in entries:
                sh = ns.shard_for(e.series_id)
                # skip points already covered by a complete flushed block
                bs = (e.time_nanos // ns.opts.block_size_nanos) * ns.opts.block_size_nanos
                if bs in sh._flushed_blocks:
                    continue
                sh.write(e.series_id, e.time_nanos, e.value, e.unit)
            result["commitlog_entries"] += len(entries)
        self.bootstrapped = True
        return result

    def close(self) -> None:
        for cl in self._commitlogs.values():
            cl.close()
