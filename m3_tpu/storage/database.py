"""Database → namespaces → shards → series: write/read routing + lifecycle.

Reference: /root/reference/src/dbnode/storage/ — storage.Database
(database.go: Write :573, ReadEncoded :842, Bootstrap :925, AssignShardSet
:386), dbNamespace (namespace.go, per-namespace retention/blockSize), dbShard
(shard.go: writeAndIndex :869, ReadEncoded :1060, Tick :663, WarmFlush :2146),
bootstrap chain (bootstrap/process.go:147: filesystem → commitlog → peers →
uninitialized).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from ..codec.m3tsz import Datapoint, decode
from ..utils.hash import shard_for
from ..utils.serialize import decode_tags, is_tag_id
from ..utils.xtime import Unit
from .commitlog import CommitLog, CommitLogEntry
from .fs import (
    CHUNK_K,
    FilesetID,
    FilesetReader,
    list_filesets,
    read_index_ids,
    write_fileset,
)
from .series import NANOS, SeriesBuffer
from .snapshot import read_latest_snapshot, write_snapshot


@dataclass
class NamespaceOptions:
    """namespace metadata (src/dbnode/namespace/options.go)."""

    retention_nanos: int = 2 * 24 * 3600 * NANOS
    block_size_nanos: int = 2 * 3600 * NANOS
    index_enabled: bool = True
    cold_writes_enabled: bool = True


class Shard:
    """dbShard: series map for one virtual shard."""

    def __init__(self, shard_id: int, ns: str, opts: NamespaceOptions, base: str) -> None:
        self.id = shard_id
        self.namespace = ns
        self.opts = opts
        self.base = base
        self.series: dict[bytes, SeriesBuffer] = {}
        self._flushed_blocks: set[int] = set()

    def write(self, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        buf = self.series.get(sid)
        if buf is None:
            buf = SeriesBuffer(sid, self.opts.block_size_nanos)
            self.series[sid] = buf
        buf.write(t_nanos, value, unit)

    def read(self, sid: bytes, start: int, end: int) -> list[Datapoint]:
        out: list[Datapoint] = []
        # flushed filesets first (older), then buffer (newer wins on dupes)
        for fid in list_filesets(self.base, self.namespace, self.id):
            if fid.block_start + self.opts.block_size_nanos <= start or fid.block_start >= end:
                continue
            reader = FilesetReader(self.base, fid)
            stream = reader.stream(sid)
            if stream:
                out.extend(dp for dp in decode(stream) if start <= dp.timestamp < end)
        buf = self.series.get(sid)
        if buf is not None:
            out.extend(buf.read(start, end))
        dedup: dict[int, Datapoint] = {}
        for dp in out:
            dedup[dp.timestamp] = dp
        return [dedup[t] for t in sorted(dedup)]

    def warm_flush(self, flush_before_nanos: int) -> list[FilesetID]:
        """shard.go:2146 — write filesets for complete blocks, then evict."""
        blocks: dict[int, dict[bytes, bytes]] = {}
        for sid, buf in self.series.items():
            for bs, stream in buf.streams_before(flush_before_nanos).items():
                if stream and bs not in self._flushed_blocks:
                    blocks.setdefault(bs, {})[sid] = stream
        flushed = []
        for bs, series in sorted(blocks.items()):
            fid = FilesetID(self.namespace, self.id, bs, volume=0)
            write_fileset(self.base, fid, series, self.opts.block_size_nanos, CHUNK_K)
            self._flushed_blocks.add(bs)
            flushed.append(fid)
        # evict only what this flush made durable — cold writes into
        # previously-flushed blocks stay buffered for cold_flush
        for buf in self.series.values():
            for fid in flushed:
                buf.evict_block(fid.block_start)
        return flushed

    def cold_flush(self, flush_before_nanos: int) -> list[FilesetID]:
        """shard.go:2212 — out-of-order writes into already-flushed blocks go
        out as a new volume merged with the existing fileset."""
        flushed = []
        for sid, buf in list(self.series.items()):
            for bs, stream in buf.streams_before(flush_before_nanos).items():
                if bs not in self._flushed_blocks or not stream:
                    continue
                existing = list_filesets(self.base, self.namespace, self.id)
                prev = next((f for f in existing if f.block_start == bs), None)
                series: dict[bytes, bytes] = {}
                if prev is not None:
                    reader = FilesetReader(self.base, prev)
                    for other in reader.series_ids:
                        series[other] = reader.stream(other) or b""
                # merge this series' new points with any flushed ones
                merged: dict[int, Datapoint] = {}
                if sid in series:
                    for dp in decode(series[sid]):
                        merged[dp.timestamp] = dp
                for dp in decode(stream):
                    merged[dp.timestamp] = dp
                from ..codec.m3tsz import Encoder

                enc = Encoder(min(merged))
                for t in sorted(merged):
                    dp = merged[t]
                    enc.encode(dp.timestamp, dp.value, unit=dp.unit)
                series[sid] = enc.stream()
                vol = (prev.volume + 1) if prev is not None else 0
                fid = FilesetID(self.namespace, self.id, bs, volume=vol)
                write_fileset(self.base, fid, series, self.opts.block_size_nanos, CHUNK_K)
                flushed.append(fid)
                buf.evict_block(bs)
        return flushed

    def tick(self, now_nanos: int) -> None:
        """shard.go:663 tickAndExpire: drop series/blocks past retention."""
        expire_before = now_nanos - self.opts.retention_nanos
        for sid in list(self.series):
            buf = self.series[sid]
            buf.evict_before(expire_before)
            if not buf.buckets:
                del self.series[sid]


class Namespace:
    def __init__(self, name: str, opts: NamespaceOptions, num_shards: int, base: str) -> None:
        self.name = name
        self.opts = opts
        self.num_shards = num_shards
        self.shards = [Shard(i, name, opts, base) for i in range(num_shards)]
        self.index = None
        if opts.index_enabled:
            from ..index.ns_index import NamespaceIndex

            self.index = NamespaceIndex(opts.block_size_nanos, opts.retention_nanos)

    def shard_for(self, sid: bytes) -> Shard:
        return self.shards[shard_for(sid, self.num_shards)]


class Database:
    """Top-level storage node object (database.go)."""

    def __init__(self, base_dir: str, num_shards: int = 8, commitlog_enabled: bool = True) -> None:
        self.base = base_dir
        self.num_shards = num_shards
        self.namespaces: dict[str, Namespace] = {}
        self.commitlog_enabled = commitlog_enabled
        self._commitlogs: dict[str, CommitLog] = {}
        self.bootstrapped = False
        # Serializes write/read/flush across request threads — the reference
        # guards these paths with per-shard locks (shard.go RLock/Lock); a
        # single re-entrant lock is the current granularity.
        self.lock = threading.RLock()

    def create_namespace(self, name: str, opts: NamespaceOptions | None = None) -> Namespace:
        with self.lock:
            ns = Namespace(name, opts or NamespaceOptions(), self.num_shards, self.base)
            self.namespaces[name] = ns
            if self.commitlog_enabled:
                self._commitlogs[name] = CommitLog(self._commitlog_dir(name))
            return ns

    def _commitlog_dir(self, ns: str) -> str:
        return os.path.join(self.base, "commitlogs", ns)

    def write(
        self, ns: str, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND
    ) -> None:
        with self.lock:
            namespace = self.namespaces[ns]
            cl = self._commitlogs.get(ns)
            if cl is not None:
                cl.write(CommitLogEntry(sid, t_nanos, value, unit))
            namespace.shard_for(sid).write(sid, t_nanos, value, unit)

    def write_batch(self, ns: str, entries: list[tuple[bytes, int, float]]) -> None:
        with self.lock:
            namespace = self.namespaces[ns]
            cl = self._commitlogs.get(ns)
            if cl is not None:
                cl.write_batch(
                    [CommitLogEntry(sid, t, v) for sid, t, v in entries]
                )
            for sid, t, v in entries:
                namespace.shard_for(sid).write(sid, t, v)

    def read(self, ns: str, sid: bytes, start: int, end: int) -> list[Datapoint]:
        with self.lock:
            return self.namespaces[ns].shard_for(sid).read(sid, start, end)

    # --- tagged write / index query path (database.go:606 WriteTagged,
    # :785 QueryIDs; network FetchTagged mirrors this) ---

    def write_tagged(
        self, ns: str, tags, t_nanos: int, value: float, unit: Unit = Unit.SECOND
    ) -> bytes:
        from ..rules.rules import encode_tags_id

        sid = encode_tags_id(tags)
        with self.lock:
            namespace = self.namespaces[ns]
            if namespace.index is not None:
                namespace.index.write(sid, tags, t_nanos)
            self.write(ns, sid, t_nanos, value, unit)
        return sid

    def query_ids(self, ns: str, query, start: int, end: int, limit: int | None = None):
        with self.lock:
            namespace = self.namespaces[ns]
            if namespace.index is None:
                raise RuntimeError(f"namespace {ns} has no index")
            return namespace.index.query(query, start, end, limit=limit)

    def fetch_tagged(
        self, ns: str, query, start: int, end: int, limit: int | None = None
    ) -> list[tuple[bytes, tuple, list[Datapoint]]]:
        """Index query + per-series read (the FetchTagged server path,
        tchannelthrift/node/service.go:626)."""
        with self.lock:
            result = self.query_ids(ns, query, start, end, limit=limit)
            out = []
            for doc in result.docs:
                out.append((doc.id, doc.fields, self.read(ns, doc.id, start, end)))
            return out

    def flush(self, ns: str, flush_before_nanos: int) -> list[FilesetID]:
        with self.lock:
            namespace = self.namespaces[ns]
            out = []
            for shard in namespace.shards:
                out.extend(shard.warm_flush(flush_before_nanos))
                if namespace.opts.cold_writes_enabled:
                    out.extend(shard.cold_flush(flush_before_nanos))
            # Rotate the WAL, then drop only sealed segments whose every entry
            # is now durable in a flushed fileset. Coverage is BLOCK-aligned:
            # only entries whose whole block is before the cutoff were
            # flushed (streams_before), so an entry in a partial block at the
            # cutoff edge keeps its segment alive. With cold writes enabled,
            # warm+cold flush together make every such point durable; with
            # cold writes disabled, late points in already-flushed blocks are
            # never durable, so segments are kept (the reference removes
            # commit logs only once covered by snapshot/fileset data —
            # storage/cleanup.go).
            cl = self._commitlogs.get(ns)
            if cl is not None:
                cl.rotate()
                if namespace.opts.cold_writes_enabled:
                    bsz = namespace.opts.block_size_nanos
                    cl.cleanup(
                        lambda e: (e.time_nanos // bsz) * bsz + bsz
                        <= flush_before_nanos
                    )
            # WarmFlush of index blocks (storage/index.go:868): seal + persist
            if namespace.index is not None:
                namespace.index.persist_before(self.base, ns, flush_before_nanos)
            return out

    def snapshot(self, ns: str) -> int:
        """shard.go:2335 Snapshot: capture every un-flushed buffer stream so
        commit-log replay is bounded. Returns the number of records written.
        All sealed WAL segments become removable afterwards: their entries are
        either in flushed filesets or in this snapshot."""
        with self.lock:
            namespace = self.namespaces[ns]
            total = 0
            for shard in namespace.shards:
                records = []
                for sid, buf in shard.series.items():
                    for bs, bucket in buf.buckets.items():
                        stream = bucket.merged_stream()
                        if stream:
                            records.append((sid, bs, stream))
                write_snapshot(self.base, ns, shard.id, records)
                total += len(records)
            cl = self._commitlogs.get(ns)
            if cl is not None:
                cl.rotate()
                cl.remove_inactive()
            return total

    def tick(self, now_nanos: int) -> None:
        with self.lock:
            for ns in self.namespaces.values():
                for shard in ns.shards:
                    shard.tick(now_nanos)

    # --- bootstrap chain (bootstrap/process.go:147) ---

    def _reindex(self, namespace: Namespace, sid: bytes, t_nanos: int) -> None:
        """Rebuild reverse-index state for a recovered series. Series IDs are
        the canonical tag wire format (utils/serialize.py), so tags are
        recoverable from the ID alone."""
        if namespace.index is not None and is_tag_id(sid):
            try:
                tags = tuple(sorted(decode_tags(sid)))
            except ValueError:
                return
            namespace.index.write(sid, tags, t_nanos)

    def bootstrap(self) -> dict:
        """filesystem → snapshot → commitlog — the fs source marks flushed
        blocks (fileset data is read lazily at query time) and re-indexes
        flushed series; the snapshot source restores buffered streams; the
        commitlog source replays remaining WAL segments into buffers.

        Replay never skips entries: a replayed point that also exists in a
        flushed fileset dedupes at read/merge time, whereas skipping loses
        cold writes that were logged but not yet cold-flushed."""
        with self.lock:
            result = {"commitlog_entries": 0, "filesets": 0, "snapshot_records": 0}
            for name, ns in self.namespaces.items():
                # persisted index blocks load wholesale; blocks without one
                # are rebuilt below from fileset IDs (tag wire format)
                persisted: set[int] = set()
                if ns.index is not None:
                    persisted = ns.index.load_persisted(self.base, name)
                for shard in ns.shards:
                    fids = list_filesets(self.base, name, shard.id)
                    result["filesets"] += len(fids)
                    for fid in fids:
                        shard._flushed_blocks.add(fid.block_start)
                        if fid.block_start in persisted:
                            continue
                        for sid in read_index_ids(self.base, fid):
                            self._reindex(ns, sid, fid.block_start)
                    snap = read_latest_snapshot(self.base, name, shard.id)
                    if snap:
                        for sid, bs, stream in snap:
                            for dp in decode(stream):
                                shard.write(sid, dp.timestamp, dp.value, dp.unit)
                            self._reindex(ns, sid, bs)
                        result["snapshot_records"] += len(snap)
                entries = CommitLog.replay(self._commitlog_dir(name))
                # Re-buffering a point that already sits in a flushed fileset
                # would make the next cold_flush rewrite an identical volume,
                # so entries for flushed blocks are checked against the
                # fileset first (decoded lazily, cached per (shard, block,
                # series)). Points NOT in the fileset are genuine un-flushed
                # cold writes and must replay.
                cover: dict[tuple[int, int], FilesetReader | None] = {}
                pts: dict[tuple[int, int, bytes], dict[int, float]] = {}
                bsz = ns.opts.block_size_nanos

                def _covered(sh: Shard, e: CommitLogEntry) -> bool:
                    bs = (e.time_nanos // bsz) * bsz
                    if bs not in sh._flushed_blocks:
                        return False
                    rk = (sh.id, bs)
                    if rk not in cover:
                        fid = next(
                            (
                                f
                                for f in list_filesets(self.base, name, sh.id)
                                if f.block_start == bs
                            ),
                            None,
                        )
                        cover[rk] = FilesetReader(self.base, fid) if fid else None
                    reader = cover[rk]
                    if reader is None:
                        return False
                    pk = (sh.id, bs, e.series_id)
                    if pk not in pts:
                        stream = reader.stream(e.series_id)
                        pts[pk] = (
                            {dp.timestamp: dp.value for dp in decode(stream)}
                            if stream
                            else {}
                        )
                    return pts[pk].get(e.time_nanos) == e.value

                for e in entries:
                    sh = ns.shard_for(e.series_id)
                    if _covered(sh, e):
                        continue
                    sh.write(e.series_id, e.time_nanos, e.value, e.unit)
                    self._reindex(ns, e.series_id, e.time_nanos)
                result["commitlog_entries"] += len(entries)
            self.bootstrapped = True
            return result

    def close(self) -> None:
        with self.lock:
            for cl in self._commitlogs.values():
                cl.close()
