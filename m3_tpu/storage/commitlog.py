"""Commit log: segmented append-only WAL with rotation, replay and cleanup.

Reference: /root/reference/src/dbnode/persist/fs/commitlog/ — NewCommitLog
(commit_log.go:249), batched async writes behind a single writer
(writeBehind :804), flush interval/fsync policy, RotateLogs (:370), chunked
reader (reader.go).

The log is a directory of numbered segment files (``commitlog-<seq>.wal``).
Rotation seals the active segment and opens the next; sealed segments are
only DELETED once their entries are durable elsewhere (flushed filesets
and/or snapshot files — the reference removes commit logs only when covered
by snapshots, commit_log cleanup in storage/cleanup.go). Replay walks all
segments in sequence order and tolerates a torn final record. Record CRCs
cover series_id AND payload so a corrupted id cannot replay datapoints into
the wrong series.
"""

from __future__ import annotations

import errno
import os
import queue
import re
import struct
import threading
import time
import zlib
from dataclasses import dataclass

from ..utils.instrument import DEFAULT as METRICS
from ..utils.xtime import Unit
from .faults import DISK, DiskFullError, crash_point

_MAGIC = 0x6D33574C  # "m3WL"
_HDR = struct.Struct("<IHI")  # crc32 of (series_id + payload), id len, payload len
_SEG_RE = re.compile(r"^commitlog-(\d+)\.wal$")

_ENOSPC_ERRNOS = (errno.ENOSPC, errno.EDQUOT)

# disk-full degrade surface: one process-wide gauge (any commit log
# degraded), one event counter. Per-log state lives on the instance; the
# registry aggregates here so the SLO plane sees capacity pressure.
_DISK_FULL_GAUGE = METRICS.gauge(
    "storage_disk_full",
    "1 while any commit log is in disk-full degraded mode",
)
_DISK_FULL_EVENTS = METRICS.counter(
    "storage_disk_full_events_total",
    "commit log disk-full degrade events",
)
_degraded_dirs: set = set()
_degraded_lock = threading.Lock()


def _mark_degraded(dir_path: str, on: bool) -> None:
    with _degraded_lock:
        if on:
            _degraded_dirs.add(dir_path)
        else:
            _degraded_dirs.discard(dir_path)
        _DISK_FULL_GAUGE.set(1.0 if _degraded_dirs else 0.0)


@dataclass
class CommitLogEntry:
    series_id: bytes
    time_nanos: int
    value: float
    unit: Unit = Unit.SECOND
    annotation: bytes = b""


def _seg_path(dir_path: str, seq: int) -> str:
    return os.path.join(dir_path, f"commitlog-{seq}.wal")


def _list_segments(dir_path: str) -> list[tuple[int, str]]:
    try:
        names = os.listdir(dir_path)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _SEG_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, n)))
    return sorted(out)


class CommitLog:
    """Segmented WAL with WRITE-BEHIND: callers enqueue onto a bounded
    queue and return immediately; a single writer thread drains the queue,
    appends, and fsyncs when either ``flush_every`` records are pending or
    ``flush_interval`` seconds have elapsed with anything pending — the
    reference's single writer goroutine + flush interval/fsync policy
    (commit_log.go:293 writerLoop, :408/:804 writeBehind). The loss window
    on a hard kill is therefore bounded by the flush interval, even at
    arbitrarily low write rates.

    ``flush()`` is a durability barrier: it blocks until every previously
    enqueued record is appended AND fsynced. ``write_behind=False`` gives
    the fully synchronous mode (tests, tools)."""

    _SENTINEL = object()

    def __init__(
        self,
        dir_path: str,
        flush_every: int = 64,
        flush_interval: float = 1.0,
        write_behind: bool = True,
        queue_size: int = 65536,
        degraded_retry_interval: float = 0.05,
    ) -> None:
        self.dir = dir_path
        self.flush_every = flush_every
        self.flush_interval = flush_interval
        self.write_behind = write_behind
        self.degraded_retry_interval = degraded_retry_interval
        # set to the triggering OSError while the log is parked in
        # disk-full degraded mode; cleared when a retry succeeds
        self._degraded: BaseException | None = None
        self._parked: list = []  # dequeued cmds being retried while degraded
        # the writer thread owns the file; this lock only guards the
        # synchronous mode and open/close edges
        self._wlock = threading.RLock()
        os.makedirs(dir_path, exist_ok=True)
        segs = _list_segments(dir_path)
        # a fresh segment per open — the previous process's tail stays sealed
        self.active_seq = (segs[-1][0] + 1) if segs else 0
        self._f = self._open_segment(self.active_seq)
        self._pending = 0
        self._active_entries = 0
        self._closed = False
        self._failed: BaseException | None = None
        self._inflight = None  # command being served by the writer thread
        # serializes enqueue vs close: once close() wins, no barrier/entry
        # command can slip into the queue behind the 'close' command (it
        # would never be serviced — its waiter would hang forever). The
        # writer thread never takes this lock, so a blocked bounded put
        # under it still drains.
        self._qlock = threading.Lock()
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._writer: threading.Thread | None = None
        if write_behind:
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True, name="commitlog-writer"
            )
            self._writer.start()

    def _open_segment(self, seq: int):
        path = _seg_path(self.dir, seq)
        f = DISK.open(path, "ab")
        self._fpath = path
        if f.tell() == 0:
            DISK.write(f, path, struct.pack("<I", _MAGIC))
            DISK.fsync(f, path)
        return f

    # --- caller-facing surface ---

    def _check_failed(self) -> None:
        if self._failed is not None:
            raise RuntimeError("commit log writer failed") from self._failed

    @property
    def disk_full(self) -> bool:
        """True while the log is parked in disk-full degraded mode: new
        writes are shed with the typed retryable :class:`DiskFullError`
        instead of being acked into a WAL that cannot land them."""
        return self._degraded is not None

    def _check_disk_full(self) -> None:
        if self._degraded is not None:
            raise DiskFullError(f"commit log disk full: {self.dir}")

    def _enter_degraded(self, exc: OSError) -> None:
        if self._degraded is None:
            _DISK_FULL_EVENTS.inc()
            _mark_degraded(self.dir, True)
        self._degraded = exc

    def _clear_degraded(self) -> None:
        if self._degraded is not None:
            self._degraded = None
            _mark_degraded(self.dir, False)

    def _enqueue(self, cmd) -> bool:
        """Enqueue unless closed. Returns False when the log is closed."""
        with self._qlock:
            if self._closed:
                return False
            self._q.put(cmd)
            return True

    def write(self, entry: CommitLogEntry) -> None:
        if self.write_behind:
            self._check_disk_full()  # shed instead of acking into a parked WAL
            if not self._enqueue(("entry", entry)):  # blocks when full
                self._check_failed()
                raise ValueError("commit log is closed")
        else:
            with self._wlock:
                if self._closed:
                    raise ValueError("commit log is closed")
                try:
                    self._append(entry)
                    if self._pending >= self.flush_every:
                        self._fsync()
                except OSError as exc:
                    self._map_sync_oserror(exc)
                self._clear_degraded()

    def write_batch(self, entries: list[CommitLogEntry]) -> None:
        if self.write_behind:
            self._check_disk_full()
            # ONE queue command for the whole batch: per-entry queue puts
            # were ~6µs each and dominated batched ingest
            if not self._enqueue(("batch", entries)):
                self._check_failed()
                raise ValueError("commit log is closed")
        else:
            with self._wlock:
                if self._closed:
                    raise ValueError("commit log is closed")
                try:
                    for e in entries:
                        self._append(e)
                    self._fsync()
                except OSError as exc:
                    self._map_sync_oserror(exc)
                self._clear_degraded()

    def _map_sync_oserror(self, exc: OSError) -> None:
        """Sync-mode failure mapping: ENOSPC degrades to the typed
        retryable DiskFullError (a duplicate re-append after the caller's
        retry is benign — replay dedupes (sid, t) last-wins); anything
        else propagates as the hard failure it is."""
        if exc.errno in _ENOSPC_ERRNOS:
            self._enter_degraded(exc)
            raise DiskFullError(f"commit log disk full: {self.dir}") from exc
        raise exc

    def flush(self) -> None:
        """Durability barrier: everything enqueued before this call is on
        disk when it returns. No-op after close (close fsyncs). While
        disk-full degraded the barrier cannot be met — fail typed-retryable
        rather than blocking until space frees."""
        if self.write_behind:
            self._check_disk_full()
            ev = threading.Event()
            if self._enqueue(("flush", ev)):
                ev.wait()
            self._check_failed()
            self._check_disk_full()
        else:
            with self._wlock:
                if not self._closed:
                    try:
                        self._fsync()
                    except OSError as exc:
                        self._map_sync_oserror(exc)
                    self._clear_degraded()

    def rotate(self) -> int:
        """RotateLogs (:370): seal the active segment, open the next.
        Returns the sealed segment's sequence number. Rotating an EMPTY
        active segment is a no-op (a periodic mediator would otherwise
        mint one segment file per pass)."""
        if self.write_behind:
            ev = threading.Event()
            holder: list[int] = []
            if not self._enqueue(("rotate", ev, holder)):
                return self.active_seq
            ev.wait()
            return holder[0]
        with self._wlock:
            if self._closed:
                return self.active_seq
            return self._rotate_now()

    def close(self) -> None:
        if self.write_behind:
            with self._qlock:
                if self._closed:
                    return
                self._closed = True  # no further command can follow 'close'
                ev = threading.Event()
                self._q.put(("close", ev))
            ev.wait()
            if self._writer is not None:
                self._writer.join(timeout=5)
                self._writer = None
        else:
            with self._wlock:
                if not self._closed:
                    self._fsync()
                    self._f.close()
                    self._closed = True

    # --- writer thread (single owner of the file in write-behind mode) ---

    def _writer_loop(self) -> None:
        try:
            self._writer_loop_inner()
        except BaseException as exc:  # disk full, fd error, ...
            # a dead writer must not hang the process: record the failure,
            # refuse further work, and release every barrier waiter —
            # INCLUDING the command that was in flight when the failure
            # struck (it was already dequeued, so the drain below would
            # miss it). Callers re-raise via _check_failed.
            self._failed = exc
            with self._qlock:
                self._closed = True
            # Neutralize the file object: a dead writer's BufferedWriter
            # must never flush/close at GC time — fd numbers get reused,
            # and a GC-time flush was observed writing stale bytes into
            # (then closing) an UNRELATED database's WAL. dup2(devnull)
            # makes the object's fd harmless whether the original fd is
            # broken-but-open (disk error) or already closed.
            try:
                devnull = os.open(os.devnull, os.O_WRONLY)
                try:
                    os.dup2(devnull, self._f.fileno())
                finally:
                    os.close(devnull)
                self._f.close()
            except (OSError, ValueError):
                pass

            def release(cmd) -> None:
                if cmd is None:
                    return
                if cmd[0] in ("flush", "close"):
                    cmd[1].set()
                elif cmd[0] == "rotate":
                    cmd[2].append(self.active_seq)
                    cmd[1].set()

            release(self._inflight)
            self._inflight = None
            # commands dequeued into the degraded-retry park must release
            # too — they are no longer in the queue, so the drain below
            # would miss their waiters
            for cmd in self._parked:
                release(cmd)
            self._parked = []
            try:
                while True:
                    release(self._q.get_nowait())
            except queue.Empty:
                pass

    def _writer_loop_inner(self) -> None:
        last_fsync = time.monotonic()
        while True:
            self._inflight = None
            timeout = None
            if self._pending:
                timeout = max(
                    0.0, self.flush_interval - (time.monotonic() - last_fsync)
                )
            try:
                cmd = self._q.get(timeout=timeout)
            except queue.Empty:
                cmd = ("fsync",)  # interval elapsed with records pending
            self._inflight = cmd
            try:
                done = self._process_cmd(cmd)
            except OSError as exc:
                if exc.errno not in _ENOSPC_ERRNOS:
                    raise
                done = self._degraded_drain(cmd, exc)
            last_fsync = time.monotonic()
            if done:
                return

    def _process_cmd(self, cmd) -> bool:
        """Serve one writer command; True means the log just closed.
        Shared between the healthy loop and the degraded-retry loop —
        re-serving a command whose first attempt partially appended is
        safe because replay dedupes (sid, t) last-wins at bootstrap."""
        kind = cmd[0]
        if kind == "fsync":
            self._fsync()
        elif kind == "entry":
            self._append(cmd[1])
            if self._pending >= self.flush_every:
                self._fsync()
        elif kind == "batch":
            for e in cmd[1]:
                self._append(e)
            if self._pending >= self.flush_every:
                self._fsync()
        elif kind == "flush":
            self._fsync()
            cmd[1].set()
        elif kind == "rotate":
            cmd[2].append(self._rotate_now())
            cmd[1].set()
        elif kind == "close":
            self._fsync()
            self._f.close()
            cmd[1].set()
            return True
        return False

    def _degraded_drain(self, first_cmd, exc: OSError) -> bool:
        """Disk full: park instead of dying. New writes shed typed-
        retryable (see ``write``); everything already accepted — the
        failed command plus whatever queued behind it — retries in FIFO
        order until space frees, so no acked record is dropped and no
        ordering inverts. A close while still full force-closes (the
        caller is tearing the process down; spinning against a dead-full
        disk would hang shutdown forever). Returns True when the log
        closed during the drain."""
        self._enter_degraded(exc)
        self._parked = [first_cmd] if first_cmd[0] != "fsync" else []
        while True:
            try:
                while True:
                    self._parked.append(self._q.get_nowait())
            except queue.Empty:
                pass
            try:
                while self._parked:
                    done = self._process_cmd(self._parked[0])
                    self._parked.pop(0)
                    if done:
                        self._clear_degraded()
                        return True
                self._fsync()  # park entered with unsynced appends pending
                self._clear_degraded()
                return False
            except OSError as retry_exc:
                if retry_exc.errno not in _ENOSPC_ERRNOS:
                    raise
                self._enter_degraded(retry_exc)
                if any(c[0] == "close" for c in self._parked):
                    self._force_close_degraded()
                    return True
                time.sleep(self.degraded_retry_interval)

    def _force_close_degraded(self) -> None:
        """Close against a still-full disk: neutralize the file object
        (python-buffered bytes must not flush at GC time into a reused
        fd — see _crash) and release every parked waiter. Records parked
        but never landed are lost, the same bound as a process kill here;
        the on-disk WAL stays a clean torn tail that replay tolerates."""
        with self._qlock:
            self._closed = True
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            try:
                os.dup2(devnull, self._f.fileno())
            finally:
                os.close(devnull)
            self._f.close()
        except (OSError, ValueError):
            pass  # fd neutralization is best-effort; waiters still release
        for cmd in self._parked:
            if cmd[0] in ("flush", "close"):
                cmd[1].set()
            elif cmd[0] == "rotate":
                cmd[2].append(self.active_seq)
                cmd[1].set()
        self._parked = []

    # --- file ops (writer thread in write-behind mode; else under _wlock) ---

    def _append(self, entry: CommitLogEntry) -> None:
        payload = (
            struct.pack(
                "<qdBH",
                entry.time_nanos,
                entry.value,
                int(entry.unit),
                len(entry.annotation),
            )
            + entry.annotation
        )
        crc = zlib.crc32(entry.series_id + payload)
        rec = _HDR.pack(crc, len(entry.series_id), len(payload)) + entry.series_id + payload
        DISK.write(self._f, self._fpath, rec)
        self._pending += 1
        self._active_entries += 1

    def _fsync(self) -> None:
        DISK.fsync(self._f, self._fpath)
        self._pending = 0

    def _rotate_now(self) -> int:
        sealed = self.active_seq
        if self._active_entries == 0:
            return sealed
        self._fsync()
        self._f.close()
        # the sealed segment is durable and closed; the next one does not
        # exist yet — the exact torn state a rotation-time kill leaves
        crash_point("commitlog:mid-rotation")
        self.active_seq += 1
        self._f = self._open_segment(self.active_seq)
        self._pending = 0
        self._active_entries = 0
        return sealed

    def _crash(self) -> None:
        """TEST ONLY: simulate a hard process kill (SIGKILL). Acked writes
        still sitting in the queue die; so does the Python-level file
        buffer. Bytes already written through to the OS survive, exactly as
        they would a real process death."""
        self._closed = True
        try:
            while True:
                cmd = self._q.get_nowait()
                if cmd[0] in ("flush", "close"):
                    cmd[1].set()  # unblock any barrier waiter
                elif cmd[0] == "rotate":
                    cmd[2].append(self.active_seq)
                    cmd[1].set()
        except queue.Empty:
            pass
        # Lose the Python-buffered bytes WITHOUT leaving a zombie file
        # object: redirect the fd to /dev/null and close normally. A bare
        # os.close left the BufferedWriter "open" holding a dead fd number;
        # its flush at GC time then wrote stale bytes into (and closed!)
        # whatever unrelated file had REUSED that fd — observed as a
        # different database's WAL writer dying with EBADF mid-test-suite.
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, self._f.fileno())  # real file keeps only
            os.close(devnull)  # what the OS already had (SIGKILL bytes)
            self._f.close()  # buffer flushes harmlessly into /dev/null
        except (OSError, ValueError):
            pass

    # --- cleanup (storage/cleanup.go commit-log removal semantics) ---

    def inactive_segments(self) -> list[tuple[int, str]]:
        return [(s, p) for s, p in _list_segments(self.dir) if s < self.active_seq]

    def cleanup(self, covered) -> int:
        """Delete sealed segments in which EVERY entry satisfies ``covered``
        (a predicate CommitLogEntry -> bool, i.e. durable elsewhere),
        OLDEST-FIRST and stopping at the first retained segment — the
        surviving WAL must stay a contiguous SUFFIX of write history.
        Deleting a newer segment around an older survivor would let the
        survivor's stale same-timestamp entries win replay's last-wins
        ordering over values that now live only in filesets.
        Returns the number of segments removed."""
        removed = 0
        for _, path in self.inactive_segments():
            if not all(covered(e) for e in self.replay_segment(path)):
                break
            os.remove(path)
            removed += 1
        return removed

    def remove_inactive(self) -> int:
        """Delete ALL sealed segments (caller guarantees coverage, e.g. a
        just-written snapshot of every buffer)."""
        removed = 0
        for _, path in self.inactive_segments():
            os.remove(path)
            removed += 1
        return removed

    # --- replay (reader.go) ---

    @staticmethod
    def replay_segment(path: str) -> list[CommitLogEntry]:
        """Stream records from one segment; stop cleanly at a torn tail."""
        out: list[CommitLogEntry] = []
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return out
        if len(buf) < 4 or struct.unpack_from("<I", buf, 0)[0] != _MAGIC:
            return out
        pos = 4
        while pos + _HDR.size <= len(buf):
            crc, id_len, p_len = _HDR.unpack_from(buf, pos)
            start = pos + _HDR.size
            end = start + id_len + p_len
            if end > len(buf):
                break  # torn tail
            sid = buf[start : start + id_len]
            payload = buf[start + id_len : end]
            if zlib.crc32(sid + payload) != crc:
                break  # corruption: stop replay (reference surfaces an error)
            t, v, unit, ann_len = struct.unpack_from("<qdBH", payload, 0)
            ann = payload[19 : 19 + ann_len]
            out.append(CommitLogEntry(sid, t, v, Unit(unit), ann))
            pos = end
        return out

    @staticmethod
    def replay(dir_path: str) -> list[CommitLogEntry]:
        """All entries across all segments, in write order."""
        out: list[CommitLogEntry] = []
        for _, path in _list_segments(dir_path):
            out.extend(CommitLog.replay_segment(path))
        return out
