"""Commit log: uncompressed append-only WAL with rotation and replay.

Reference: /root/reference/src/dbnode/persist/fs/commitlog/ — NewCommitLog
(commit_log.go:249), batched async writes behind a single writer
(writeBehind :804), flush interval/fsync policy, RotateLogs (:370), chunked
reader (reader.go). Entries here are length-prefixed binary records; replay
tolerates a torn final record (crash mid-append).
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass

from ..utils.xtime import Unit

_MAGIC = 0x6D33574C  # "m3WL"
_HDR = struct.Struct("<IHI")  # crc32 of payload, id length, payload length


@dataclass
class CommitLogEntry:
    series_id: bytes
    time_nanos: int
    value: float
    unit: Unit = Unit.SECOND
    annotation: bytes = b""


class CommitLog:
    """Single-writer WAL. fsync policy: "always" or batched every N writes
    (the reference's flush interval maps to flush_every here)."""

    def __init__(self, path: str, flush_every: int = 64) -> None:
        self.path = path
        self.flush_every = flush_every
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        if self._f.tell() == 0:
            self._f.write(struct.pack("<I", _MAGIC))
            self._f.flush()
        self._pending = 0

    def write(self, entry: CommitLogEntry) -> None:
        payload = (
            struct.pack(
                "<qdBH",
                entry.time_nanos,
                entry.value,
                int(entry.unit),
                len(entry.annotation),
            )
            + entry.annotation
        )
        rec = (
            _HDR.pack(zlib.crc32(payload), len(entry.series_id), len(payload))
            + entry.series_id
            + payload
        )
        self._f.write(rec)
        self._pending += 1
        if self._pending >= self.flush_every:
            self.flush()

    def write_batch(self, entries: list[CommitLogEntry]) -> None:
        for e in entries:
            self.write(e)
        self.flush()

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._pending = 0

    def close(self) -> None:
        self.flush()
        self._f.close()

    def rotate(self, new_path: str) -> str:
        """RotateLogs (:370): seal current file, open a fresh one."""
        self.close()
        old = self.path
        self.path = new_path
        self._f = open(new_path, "ab")
        if self._f.tell() == 0:
            self._f.write(struct.pack("<I", _MAGIC))
            self._f.flush()
        return old

    @staticmethod
    def replay(path: str) -> list[CommitLogEntry]:
        """reader.go: stream records; stop cleanly at a torn tail."""
        out: list[CommitLogEntry] = []
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return out
        if len(buf) < 4 or struct.unpack_from("<I", buf, 0)[0] != _MAGIC:
            return out
        pos = 4
        while pos + _HDR.size <= len(buf):
            crc, id_len, p_len = _HDR.unpack_from(buf, pos)
            start = pos + _HDR.size
            end = start + id_len + p_len
            if end > len(buf):
                break  # torn tail
            sid = buf[start : start + id_len]
            payload = buf[start + id_len : end]
            if zlib.crc32(payload) != crc:
                break  # corruption: stop replay (reference surfaces an error)
            t, v, unit, ann_len = struct.unpack_from("<qdBH", payload, 0)
            ann = payload[19 : 19 + ann_len]
            out.append(CommitLogEntry(sid, t, v, Unit(unit), ann))
            pos = end
        return out
