"""Commit log: segmented append-only WAL with rotation, replay and cleanup.

Reference: /root/reference/src/dbnode/persist/fs/commitlog/ — NewCommitLog
(commit_log.go:249), batched async writes behind a single writer
(writeBehind :804), flush interval/fsync policy, RotateLogs (:370), chunked
reader (reader.go).

The log is a directory of numbered segment files (``commitlog-<seq>.wal``).
Rotation seals the active segment and opens the next; sealed segments are
only DELETED once their entries are durable elsewhere (flushed filesets
and/or snapshot files — the reference removes commit logs only when covered
by snapshots, commit_log cleanup in storage/cleanup.go). Replay walks all
segments in sequence order and tolerates a torn final record. Record CRCs
cover series_id AND payload so a corrupted id cannot replay datapoints into
the wrong series.
"""

from __future__ import annotations

import os
import queue
import re
import struct
import threading
import time
import zlib
from dataclasses import dataclass

from ..utils.xtime import Unit

_MAGIC = 0x6D33574C  # "m3WL"
_HDR = struct.Struct("<IHI")  # crc32 of (series_id + payload), id len, payload len
_SEG_RE = re.compile(r"^commitlog-(\d+)\.wal$")


@dataclass
class CommitLogEntry:
    series_id: bytes
    time_nanos: int
    value: float
    unit: Unit = Unit.SECOND
    annotation: bytes = b""


def _seg_path(dir_path: str, seq: int) -> str:
    return os.path.join(dir_path, f"commitlog-{seq}.wal")


def _list_segments(dir_path: str) -> list[tuple[int, str]]:
    try:
        names = os.listdir(dir_path)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _SEG_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, n)))
    return sorted(out)


class CommitLog:
    """Segmented WAL with WRITE-BEHIND: callers enqueue onto a bounded
    queue and return immediately; a single writer thread drains the queue,
    appends, and fsyncs when either ``flush_every`` records are pending or
    ``flush_interval`` seconds have elapsed with anything pending — the
    reference's single writer goroutine + flush interval/fsync policy
    (commit_log.go:293 writerLoop, :408/:804 writeBehind). The loss window
    on a hard kill is therefore bounded by the flush interval, even at
    arbitrarily low write rates.

    ``flush()`` is a durability barrier: it blocks until every previously
    enqueued record is appended AND fsynced. ``write_behind=False`` gives
    the fully synchronous mode (tests, tools)."""

    _SENTINEL = object()

    def __init__(
        self,
        dir_path: str,
        flush_every: int = 64,
        flush_interval: float = 1.0,
        write_behind: bool = True,
        queue_size: int = 65536,
    ) -> None:
        self.dir = dir_path
        self.flush_every = flush_every
        self.flush_interval = flush_interval
        self.write_behind = write_behind
        # the writer thread owns the file; this lock only guards the
        # synchronous mode and open/close edges
        self._wlock = threading.RLock()
        os.makedirs(dir_path, exist_ok=True)
        segs = _list_segments(dir_path)
        # a fresh segment per open — the previous process's tail stays sealed
        self.active_seq = (segs[-1][0] + 1) if segs else 0
        self._f = self._open_segment(self.active_seq)
        self._pending = 0
        self._active_entries = 0
        self._closed = False
        self._failed: BaseException | None = None
        self._inflight = None  # command being served by the writer thread
        # serializes enqueue vs close: once close() wins, no barrier/entry
        # command can slip into the queue behind the 'close' command (it
        # would never be serviced — its waiter would hang forever). The
        # writer thread never takes this lock, so a blocked bounded put
        # under it still drains.
        self._qlock = threading.Lock()
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._writer: threading.Thread | None = None
        if write_behind:
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True, name="commitlog-writer"
            )
            self._writer.start()

    def _open_segment(self, seq: int):
        f = open(_seg_path(self.dir, seq), "ab")
        if f.tell() == 0:
            f.write(struct.pack("<I", _MAGIC))
            f.flush()
            os.fsync(f.fileno())
        return f

    # --- caller-facing surface ---

    def _check_failed(self) -> None:
        if self._failed is not None:
            raise RuntimeError("commit log writer failed") from self._failed

    def _enqueue(self, cmd) -> bool:
        """Enqueue unless closed. Returns False when the log is closed."""
        with self._qlock:
            if self._closed:
                return False
            self._q.put(cmd)
            return True

    def write(self, entry: CommitLogEntry) -> None:
        if self.write_behind:
            if not self._enqueue(("entry", entry)):  # blocks when full
                self._check_failed()
                raise ValueError("commit log is closed")
        else:
            with self._wlock:
                if self._closed:
                    raise ValueError("commit log is closed")
                self._append(entry)
                if self._pending >= self.flush_every:
                    self._fsync()

    def write_batch(self, entries: list[CommitLogEntry]) -> None:
        if self.write_behind:
            # ONE queue command for the whole batch: per-entry queue puts
            # were ~6µs each and dominated batched ingest
            if not self._enqueue(("batch", entries)):
                self._check_failed()
                raise ValueError("commit log is closed")
        else:
            with self._wlock:
                if self._closed:
                    raise ValueError("commit log is closed")
                for e in entries:
                    self._append(e)
                self._fsync()

    def flush(self) -> None:
        """Durability barrier: everything enqueued before this call is on
        disk when it returns. No-op after close (close fsyncs)."""
        if self.write_behind:
            ev = threading.Event()
            if self._enqueue(("flush", ev)):
                ev.wait()
            self._check_failed()
        else:
            with self._wlock:
                if not self._closed:
                    self._fsync()

    def rotate(self) -> int:
        """RotateLogs (:370): seal the active segment, open the next.
        Returns the sealed segment's sequence number. Rotating an EMPTY
        active segment is a no-op (a periodic mediator would otherwise
        mint one segment file per pass)."""
        if self.write_behind:
            ev = threading.Event()
            holder: list[int] = []
            if not self._enqueue(("rotate", ev, holder)):
                return self.active_seq
            ev.wait()
            return holder[0]
        with self._wlock:
            if self._closed:
                return self.active_seq
            return self._rotate_now()

    def close(self) -> None:
        if self.write_behind:
            with self._qlock:
                if self._closed:
                    return
                self._closed = True  # no further command can follow 'close'
                ev = threading.Event()
                self._q.put(("close", ev))
            ev.wait()
            if self._writer is not None:
                self._writer.join(timeout=5)
                self._writer = None
        else:
            with self._wlock:
                if not self._closed:
                    self._fsync()
                    self._f.close()
                    self._closed = True

    # --- writer thread (single owner of the file in write-behind mode) ---

    def _writer_loop(self) -> None:
        try:
            self._writer_loop_inner()
        except BaseException as exc:  # disk full, fd error, ...
            # a dead writer must not hang the process: record the failure,
            # refuse further work, and release every barrier waiter —
            # INCLUDING the command that was in flight when the failure
            # struck (it was already dequeued, so the drain below would
            # miss it). Callers re-raise via _check_failed.
            self._failed = exc
            with self._qlock:
                self._closed = True
            # Neutralize the file object: a dead writer's BufferedWriter
            # must never flush/close at GC time — fd numbers get reused,
            # and a GC-time flush was observed writing stale bytes into
            # (then closing) an UNRELATED database's WAL. dup2(devnull)
            # makes the object's fd harmless whether the original fd is
            # broken-but-open (disk error) or already closed.
            try:
                devnull = os.open(os.devnull, os.O_WRONLY)
                try:
                    os.dup2(devnull, self._f.fileno())
                finally:
                    os.close(devnull)
                self._f.close()
            except (OSError, ValueError):
                pass

            def release(cmd) -> None:
                if cmd is None:
                    return
                if cmd[0] in ("flush", "close"):
                    cmd[1].set()
                elif cmd[0] == "rotate":
                    cmd[2].append(self.active_seq)
                    cmd[1].set()

            release(self._inflight)
            self._inflight = None
            try:
                while True:
                    release(self._q.get_nowait())
            except queue.Empty:
                pass

    def _writer_loop_inner(self) -> None:
        last_fsync = time.monotonic()
        while True:
            self._inflight = None
            timeout = None
            if self._pending:
                timeout = max(
                    0.0, self.flush_interval - (time.monotonic() - last_fsync)
                )
            try:
                cmd = self._q.get(timeout=timeout)
            except queue.Empty:
                self._fsync()  # interval elapsed with records pending
                last_fsync = time.monotonic()
                continue
            self._inflight = cmd
            kind = cmd[0]
            if kind == "entry":
                self._append(cmd[1])
                if self._pending >= self.flush_every:
                    self._fsync()
                    last_fsync = time.monotonic()
            elif kind == "batch":
                for e in cmd[1]:
                    self._append(e)
                if self._pending >= self.flush_every:
                    self._fsync()
                    last_fsync = time.monotonic()
            elif kind == "flush":
                self._fsync()
                last_fsync = time.monotonic()
                cmd[1].set()
            elif kind == "rotate":
                cmd[2].append(self._rotate_now())
                last_fsync = time.monotonic()
                cmd[1].set()
            elif kind == "close":
                self._fsync()
                self._f.close()
                cmd[1].set()
                return

    # --- file ops (writer thread in write-behind mode; else under _wlock) ---

    def _append(self, entry: CommitLogEntry) -> None:
        payload = (
            struct.pack(
                "<qdBH",
                entry.time_nanos,
                entry.value,
                int(entry.unit),
                len(entry.annotation),
            )
            + entry.annotation
        )
        crc = zlib.crc32(entry.series_id + payload)
        rec = _HDR.pack(crc, len(entry.series_id), len(payload)) + entry.series_id + payload
        self._f.write(rec)
        self._pending += 1
        self._active_entries += 1

    def _fsync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._pending = 0

    def _rotate_now(self) -> int:
        sealed = self.active_seq
        if self._active_entries == 0:
            return sealed
        self._fsync()
        self._f.close()
        self.active_seq += 1
        self._f = self._open_segment(self.active_seq)
        self._pending = 0
        self._active_entries = 0
        return sealed

    def _crash(self) -> None:
        """TEST ONLY: simulate a hard process kill (SIGKILL). Acked writes
        still sitting in the queue die; so does the Python-level file
        buffer. Bytes already written through to the OS survive, exactly as
        they would a real process death."""
        self._closed = True
        try:
            while True:
                cmd = self._q.get_nowait()
                if cmd[0] in ("flush", "close"):
                    cmd[1].set()  # unblock any barrier waiter
                elif cmd[0] == "rotate":
                    cmd[2].append(self.active_seq)
                    cmd[1].set()
        except queue.Empty:
            pass
        # Lose the Python-buffered bytes WITHOUT leaving a zombie file
        # object: redirect the fd to /dev/null and close normally. A bare
        # os.close left the BufferedWriter "open" holding a dead fd number;
        # its flush at GC time then wrote stale bytes into (and closed!)
        # whatever unrelated file had REUSED that fd — observed as a
        # different database's WAL writer dying with EBADF mid-test-suite.
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, self._f.fileno())  # real file keeps only
            os.close(devnull)  # what the OS already had (SIGKILL bytes)
            self._f.close()  # buffer flushes harmlessly into /dev/null
        except (OSError, ValueError):
            pass

    # --- cleanup (storage/cleanup.go commit-log removal semantics) ---

    def inactive_segments(self) -> list[tuple[int, str]]:
        return [(s, p) for s, p in _list_segments(self.dir) if s < self.active_seq]

    def cleanup(self, covered) -> int:
        """Delete sealed segments in which EVERY entry satisfies ``covered``
        (a predicate CommitLogEntry -> bool, i.e. durable elsewhere),
        OLDEST-FIRST and stopping at the first retained segment — the
        surviving WAL must stay a contiguous SUFFIX of write history.
        Deleting a newer segment around an older survivor would let the
        survivor's stale same-timestamp entries win replay's last-wins
        ordering over values that now live only in filesets.
        Returns the number of segments removed."""
        removed = 0
        for _, path in self.inactive_segments():
            if not all(covered(e) for e in self.replay_segment(path)):
                break
            os.remove(path)
            removed += 1
        return removed

    def remove_inactive(self) -> int:
        """Delete ALL sealed segments (caller guarantees coverage, e.g. a
        just-written snapshot of every buffer)."""
        removed = 0
        for _, path in self.inactive_segments():
            os.remove(path)
            removed += 1
        return removed

    # --- replay (reader.go) ---

    @staticmethod
    def replay_segment(path: str) -> list[CommitLogEntry]:
        """Stream records from one segment; stop cleanly at a torn tail."""
        out: list[CommitLogEntry] = []
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return out
        if len(buf) < 4 or struct.unpack_from("<I", buf, 0)[0] != _MAGIC:
            return out
        pos = 4
        while pos + _HDR.size <= len(buf):
            crc, id_len, p_len = _HDR.unpack_from(buf, pos)
            start = pos + _HDR.size
            end = start + id_len + p_len
            if end > len(buf):
                break  # torn tail
            sid = buf[start : start + id_len]
            payload = buf[start + id_len : end]
            if zlib.crc32(sid + payload) != crc:
                break  # corruption: stop replay (reference surfaces an error)
            t, v, unit, ann_len = struct.unpack_from("<qdBH", payload, 0)
            ann = payload[19 : 19 + ann_len]
            out.append(CommitLogEntry(sid, t, v, Unit(unit), ann))
            pos = end
        return out

    @staticmethod
    def replay(dir_path: str) -> list[CommitLogEntry]:
        """All entries across all segments, in write order."""
        out: list[CommitLogEntry] = []
        for _, path in _list_segments(dir_path):
            out.extend(CommitLog.replay_segment(path))
        return out
