"""Commit log: segmented append-only WAL with rotation, replay and cleanup.

Reference: /root/reference/src/dbnode/persist/fs/commitlog/ — NewCommitLog
(commit_log.go:249), batched async writes behind a single writer
(writeBehind :804), flush interval/fsync policy, RotateLogs (:370), chunked
reader (reader.go).

The log is a directory of numbered segment files (``commitlog-<seq>.wal``).
Rotation seals the active segment and opens the next; sealed segments are
only DELETED once their entries are durable elsewhere (flushed filesets
and/or snapshot files — the reference removes commit logs only when covered
by snapshots, commit_log cleanup in storage/cleanup.go). Replay walks all
segments in sequence order and tolerates a torn final record. Record CRCs
cover series_id AND payload so a corrupted id cannot replay datapoints into
the wrong series.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import zlib
from dataclasses import dataclass

from ..utils.xtime import Unit

_MAGIC = 0x6D33574C  # "m3WL"
_HDR = struct.Struct("<IHI")  # crc32 of (series_id + payload), id len, payload len
_SEG_RE = re.compile(r"^commitlog-(\d+)\.wal$")


@dataclass
class CommitLogEntry:
    series_id: bytes
    time_nanos: int
    value: float
    unit: Unit = Unit.SECOND
    annotation: bytes = b""


def _seg_path(dir_path: str, seq: int) -> str:
    return os.path.join(dir_path, f"commitlog-{seq}.wal")


def _list_segments(dir_path: str) -> list[tuple[int, str]]:
    try:
        names = os.listdir(dir_path)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _SEG_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, n)))
    return sorted(out)


class CommitLog:
    """Single-writer segmented WAL. fsync policy: batched every N writes
    (the reference's flush interval maps to flush_every here)."""

    def __init__(self, dir_path: str, flush_every: int = 64) -> None:
        self.dir = dir_path
        self.flush_every = flush_every
        # single-writer lock: appends from per-shard write paths serialize
        # here (the reference's commit log has its own writer queue)
        self._wlock = threading.RLock()
        os.makedirs(dir_path, exist_ok=True)
        segs = _list_segments(dir_path)
        # a fresh segment per open — the previous process's tail stays sealed
        self.active_seq = (segs[-1][0] + 1) if segs else 0
        self._f = self._open_segment(self.active_seq)
        self._pending = 0
        self._active_entries = 0

    def _open_segment(self, seq: int):
        f = open(_seg_path(self.dir, seq), "ab")
        if f.tell() == 0:
            f.write(struct.pack("<I", _MAGIC))
            f.flush()
            os.fsync(f.fileno())
        return f

    def write(self, entry: CommitLogEntry) -> None:
        with self._wlock:
            self._write_locked(entry)

    def _write_locked(self, entry: CommitLogEntry) -> None:
        payload = (
            struct.pack(
                "<qdBH",
                entry.time_nanos,
                entry.value,
                int(entry.unit),
                len(entry.annotation),
            )
            + entry.annotation
        )
        crc = zlib.crc32(entry.series_id + payload)
        rec = _HDR.pack(crc, len(entry.series_id), len(payload)) + entry.series_id + payload
        self._f.write(rec)
        self._pending += 1
        self._active_entries += 1
        if self._pending >= self.flush_every:
            self.flush()

    def write_batch(self, entries: list[CommitLogEntry]) -> None:
        with self._wlock:
            for e in entries:
                self._write_locked(e)
            self.flush()

    def flush(self) -> None:
        with self._wlock:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._pending = 0

    def close(self) -> None:
        with self._wlock:
            self.flush()
            self._f.close()

    def rotate(self) -> int:
        with self._wlock:
            return self._rotate_locked()

    def _rotate_locked(self) -> int:
        """RotateLogs (:370): seal the active segment, open the next.
        Returns the sealed segment's sequence number. Rotating an EMPTY
        active segment is a no-op (a periodic mediator would otherwise
        mint one segment file per pass)."""
        sealed = self.active_seq
        if self._active_entries == 0:
            return sealed
        self.close()
        self.active_seq += 1
        self._f = self._open_segment(self.active_seq)
        self._pending = 0
        self._active_entries = 0
        return sealed

    # --- cleanup (storage/cleanup.go commit-log removal semantics) ---

    def inactive_segments(self) -> list[tuple[int, str]]:
        return [(s, p) for s, p in _list_segments(self.dir) if s < self.active_seq]

    def cleanup(self, covered) -> int:
        """Delete sealed segments in which EVERY entry satisfies ``covered``
        (a predicate CommitLogEntry -> bool, i.e. durable elsewhere),
        OLDEST-FIRST and stopping at the first retained segment — the
        surviving WAL must stay a contiguous SUFFIX of write history.
        Deleting a newer segment around an older survivor would let the
        survivor's stale same-timestamp entries win replay's last-wins
        ordering over values that now live only in filesets.
        Returns the number of segments removed."""
        removed = 0
        for _, path in self.inactive_segments():
            if not all(covered(e) for e in self.replay_segment(path)):
                break
            os.remove(path)
            removed += 1
        return removed

    def remove_inactive(self) -> int:
        """Delete ALL sealed segments (caller guarantees coverage, e.g. a
        just-written snapshot of every buffer)."""
        removed = 0
        for _, path in self.inactive_segments():
            os.remove(path)
            removed += 1
        return removed

    # --- replay (reader.go) ---

    @staticmethod
    def replay_segment(path: str) -> list[CommitLogEntry]:
        """Stream records from one segment; stop cleanly at a torn tail."""
        out: list[CommitLogEntry] = []
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return out
        if len(buf) < 4 or struct.unpack_from("<I", buf, 0)[0] != _MAGIC:
            return out
        pos = 4
        while pos + _HDR.size <= len(buf):
            crc, id_len, p_len = _HDR.unpack_from(buf, pos)
            start = pos + _HDR.size
            end = start + id_len + p_len
            if end > len(buf):
                break  # torn tail
            sid = buf[start : start + id_len]
            payload = buf[start + id_len : end]
            if zlib.crc32(sid + payload) != crc:
                break  # corruption: stop replay (reference surfaces an error)
            t, v, unit, ann_len = struct.unpack_from("<qdBH", payload, 0)
            ann = payload[19 : 19 + ann_len]
            out.append(CommitLogEntry(sid, t, v, Unit(unit), ann))
            pos = end
        return out

    @staticmethod
    def replay(dir_path: str) -> list[CommitLogEntry]:
        """All entries across all segments, in write order."""
        out: list[CommitLogEntry] = []
        for _, path in _list_segments(dir_path):
            out.extend(CommitLog.replay_segment(path))
        return out
