"""Runtime options: KV-watched live reconfiguration of a running node.

Reference: /root/reference/src/dbnode/runtime/runtime_options_manager.go +
src/dbnode/kvconfig/keys.go — operators flip node behavior (tick/flush
cadence, write limits) through the cluster KV without restarts; components
register listeners and apply changes on the next pass.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace

RUNTIME_KEY = "_runtime/options"


@dataclass(frozen=True)
class RuntimeOptions:
    """The live-tunable subset (runtime/types.go Options)."""

    tick_interval_secs: float = 10.0
    flush_interval_secs: float = 60.0
    snapshot_interval_secs: float = 60.0
    buffer_past_secs: float = 600.0
    # max NEW series insertions per second, 0 = unlimited
    # (kvconfig ClusterNewSeriesInsertLimit)
    write_new_series_limit_per_sec: int = 0


class RuntimeOptionsManager:
    """options manager + kvconfig watch: get() is always current; listeners
    fire on every KV update."""

    def __init__(self, kv, defaults: RuntimeOptions | None = None) -> None:
        self.kv = kv
        self._lock = threading.Lock()
        self._current = defaults or RuntimeOptions()
        self._from_kv = False  # becomes True after a real KV update
        self._listeners: list = []
        self._unsub = kv.watch(RUNTIME_KEY, self._on_update)
        vv = kv.get(RUNTIME_KEY)
        if vv is not None:
            self._on_update(vv)

    def _on_update(self, vv) -> None:
        data = vv.value
        if not isinstance(data, dict):
            return
        with self._lock:
            known = {
                k: v for k, v in data.items() if hasattr(self._current, k)
            }
            self._current = replace(self._current, **known)
            self._from_kv = True
            listeners = list(self._listeners)
            current = self._current
        for fn in listeners:
            fn(current)

    def get(self) -> RuntimeOptions:
        with self._lock:
            return self._current

    def watch(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)
            replay = self._from_kv
        if replay:
            # replay only options that actually came from KV — firing the
            # built-in defaults would clobber a caller's explicit config
            fn(self.get())

    def close(self) -> None:
        self._unsub()


def set_runtime_options(kv, **updates) -> None:
    """Admin helper: merge updates into the runtime options KV key."""
    vv = kv.get(RUNTIME_KEY)
    cur = dict(vv.value) if vv and isinstance(vv.value, dict) else {}
    cur.update(updates)
    kv.set(RUNTIME_KEY, cur)
