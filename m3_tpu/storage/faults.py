"""Deterministic disk-fault injection + crash points for the storage layer.

The write-side twin of ``net/faults.py``: a seeded plan of rules keyed on
(operation, path class) that injects the disk failures a durability story
must survive, through ONE seam (:class:`DiskIO`) threaded under
``fs.py`` / ``commitlog.py`` / ``snapshot.py`` / ``utils/blob.py``:

- ``eio``: the write/fsync/open raises ``EIO`` before any byte lands —
  the dead-disk path;
- ``enospc``: raises ``ENOSPC`` — the full-disk path callers must degrade
  through (commitlog turns it into a typed retryable
  :class:`DiskFullError`);
- ``torn``: the payload is truncated at a seeded byte offset and the
  write then fails — what a power cut mid-write leaves on disk;
- ``bitflip``: one seeded bit of the payload is flipped and the write
  SUCCEEDS — silent media corruption, detectable only by digest
  verification (the scrubber's prey).

Every draw comes from one plan-owned RNG, so a fixed seed plus a fixed
I/O sequence replays the exact same faults. Spawned dbnodes pick a plan
up from the ``M3_TPU_DISK_FAULT_PLAN`` env var (JSON); nothing is
installed when it is unset.

Separately, **crash points** are named sites inside multi-file commit
protocols (``fileset:pre-checkpoint``, ``commitlog:mid-rotation``, ...)
that hard-exit the process when armed via ``M3_TPU_CRASH_POINT``, so a
recovery gate can SIGKILL-equivalent a node at an exact torn-state
boundary instead of a random sleep.
"""

from __future__ import annotations

import errno
import json
import os
import random
import sys
import threading
from dataclasses import asdict, dataclass

from ..utils.instrument import DEFAULT as METRICS

DISK_FAULT_PLAN_ENV = "M3_TPU_DISK_FAULT_PLAN"
CRASH_POINT_ENV = "M3_TPU_CRASH_POINT"

#: exit code a tripped crash point dies with (mirrors SIGKILL's 128+9 so
#: process-level tooling treats both the same way)
CRASH_EXIT_CODE = 137

#: every named crash site wired into the storage layer, in commit order.
#: Naming convention: ``<subsystem>:<boundary>`` where the boundary names
#: the state the disk is left in (see CONTRIBUTING.md).
CRASH_POINTS = (
    "fileset:data-written",     # data file durable, digest+checkpoint absent
    "fileset:pre-checkpoint",   # all files + digest durable, checkpoint absent
    "commitlog:mid-rotation",   # old segment closed, next segment not yet open
    "snapshot:pre-cleanup",     # new snapshot durable, superseded ones remain
)

DISK_OPS = ("open", "read", "write", "fsync", "rename")

#: path classes a rule can scope to: the fileset file roles plus the two
#: non-fileset storage dirs; anything else classifies as "other"
PATH_CLASSES = (
    "info", "index", "summaries", "bloomfilter", "data", "side",
    "digest", "checkpoint", "commitlog", "snapshot", "other",
)


class DiskFaultError(OSError):
    """Injected disk failure (EIO / torn-write surface)."""


class DiskFullError(OSError):
    """Typed retryable disk-full rejection.

    Raised by the commitlog / flush path when the disk is out of space:
    rides ``wire.RETRYABLE_ETYPES`` so clients back off and retry instead
    of erroring, and the SLO plane sees shed capacity rather than
    failures. Writes resume on their own once space frees."""

    def __init__(self, msg: str) -> None:
        super().__init__(errno.ENOSPC, msg)


def classify_path(path: str) -> str:
    """Map a storage path to its fault-plan path class.

    Temp-file spellings (``.{name}.tmp`` from the durable-write seam)
    classify the same as their final name, so a rule on ``checkpoint``
    also faults the checkpoint's temp write."""
    name = os.path.basename(path)
    if name.startswith(".") and name.endswith(".tmp"):
        name = name[1:-4]
    parts = path.replace("\\", "/").split("/")
    if name.endswith(".wal") or "commitlogs" in parts:
        return "commitlog"
    if name.startswith("snapshot") or "snapshots" in parts:
        return "snapshot"
    if name.startswith("fileset-") and name.endswith(".db"):
        bits = name[: -len(".db")].split("-")
        if len(bits) == 4 and bits[3] in PATH_CLASSES:
            return bits[3]
    return "other"


@dataclass
class DiskFaultRule:
    """One match+action row. ``op``/``path_class`` of None match anything;
    probabilities are independent draws in [0, 1]. ``max_hits`` bounds how
    many faults the rule injects in total (0 = unlimited) — a plan can say
    "exactly one torn write, then a healthy disk"."""

    op: str | None = None
    path_class: str | None = None
    eio: float = 0.0
    enospc: float = 0.0
    torn: float = 0.0
    bitflip: float = 0.0
    max_hits: int = 0
    hits: int = 0

    def matches(self, op: str, path_class: str) -> bool:
        if self.op is not None and self.op != op:
            return False
        if self.path_class is not None and self.path_class != path_class:
            return False
        return not (self.max_hits and self.hits >= self.max_hits)


class DiskFaultPlan:
    """Seeded fault schedule over (op, path class) decision points."""

    def __init__(self, rules: list[DiskFaultRule], seed: int = 0) -> None:
        self.rules = list(rules)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._injected = {
            kind: METRICS.counter(
                "disk_faults_injected_total",
                "disk faults injected by the active DiskFaultPlan",
                labels={"kind": kind},
            )
            for kind in ("eio", "enospc", "torn", "bitflip")
        }

    def decide(self, op: str, path_class: str, size: int = 0) -> tuple[str, int]:
        """One decision draw: (action, seeded offset).

        action ∈ {'pass','eio','enospc','torn','bitflip'}; the offset is a
        byte offset for 'torn' (truncate the payload there) and a BIT
        offset for 'bitflip' (flip that bit), drawn from the plan RNG so
        the corruption itself replays."""
        with self._lock:
            for rule in self.rules:
                if not rule.matches(op, path_class):
                    continue
                if rule.eio > 0.0 and self._rng.random() < rule.eio:
                    rule.hits += 1
                    self._injected["eio"].inc()
                    return "eio", 0
                if rule.enospc > 0.0 and self._rng.random() < rule.enospc:
                    rule.hits += 1
                    self._injected["enospc"].inc()
                    return "enospc", 0
                if rule.torn > 0.0 and self._rng.random() < rule.torn:
                    rule.hits += 1
                    self._injected["torn"].inc()
                    return "torn", self._rng.randrange(max(size, 1))
                if rule.bitflip > 0.0 and self._rng.random() < rule.bitflip:
                    rule.hits += 1
                    self._injected["bitflip"].inc()
                    return "bitflip", self._rng.randrange(max(size * 8, 1))
        return "pass", 0

    def to_json(self) -> str:
        rules = []
        for r in self.rules:
            d = asdict(r)
            d.pop("hits", None)  # runtime state, not plan spec
            rules.append(d)
        return json.dumps({"seed": self.seed, "rules": rules})

    @classmethod
    def from_json(cls, raw: str) -> "DiskFaultPlan":
        spec = json.loads(raw)
        rules = [DiskFaultRule(**r) for r in spec.get("rules", [])]
        return cls(rules, seed=int(spec.get("seed", 0)))


def plan_from_env(env=None) -> DiskFaultPlan | None:
    """A DiskFaultPlan from M3_TPU_DISK_FAULT_PLAN, or None when unset.
    Malformed JSON raises — a chaos run silently running without its
    faults would pass vacuously."""
    raw = (env if env is not None else os.environ).get(DISK_FAULT_PLAN_ENV, "")
    if not raw:
        return None
    return DiskFaultPlan.from_json(raw)


class DiskIO:
    """THE injectable I/O seam every durable write in ``m3_tpu/storage/``
    goes through (m3lint M3L008 enforces this statically). With no plan
    installed every method is a thin passthrough."""

    def __init__(self, plan: DiskFaultPlan | None = None) -> None:
        self.plan = plan

    # -- primitive ops --

    def open(self, path: str, mode: str = "rb"):
        if self.plan is not None:
            action, _ = self.plan.decide("open", classify_path(path))
            if action in ("eio", "enospc"):
                raise _os_error(action, "open", path)
        return open(path, mode)

    def read(self, f, path: str, n: int = -1) -> bytes:
        if self.plan is not None:
            action, _ = self.plan.decide("read", classify_path(path))
            if action == "eio":
                raise _os_error("eio", "read", path)
        return f.read(n)

    def write(self, f, path: str, payload: bytes) -> None:
        """One payload write. 'torn' lands a truncated prefix THEN fails
        (what the disk holds after a cut); 'bitflip' corrupts one bit and
        succeeds silently."""
        if self.plan is not None:
            action, off = self.plan.decide(
                "write", classify_path(path), len(payload)
            )
            if action in ("eio", "enospc"):
                raise _os_error(action, "write", path)
            if action == "torn":
                f.write(payload[:off])
                f.flush()
                raise _os_error("eio", "torn write", path)
            if action == "bitflip" and payload:
                buf = bytearray(payload)
                buf[off // 8] ^= 1 << (off % 8)
                f.write(bytes(buf))
                return
        f.write(payload)

    def fsync(self, f, path: str) -> None:
        if self.plan is not None:
            action, _ = self.plan.decide("fsync", classify_path(path))
            if action in ("eio", "enospc"):
                raise _os_error(action, "fsync", path)
        f.flush()
        os.fsync(f.fileno())

    def fsync_path(self, path: str) -> None:
        """fsync an already-closed file by path (migration commit)."""
        if self.plan is not None:
            action, _ = self.plan.decide("fsync", classify_path(path))
            if action in ("eio", "enospc"):
                raise _os_error(action, "fsync", path)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def replace(self, src: str, dst: str) -> None:
        if self.plan is not None:
            action, _ = self.plan.decide("rename", classify_path(dst))
            if action in ("eio", "enospc"):
                raise _os_error(action, "rename", dst)
        os.replace(src, dst)

    # -- the shared durable-write primitive --

    def write_durable(self, path: str, payload: bytes) -> None:
        """write-temp → fsync → rename: the ONE way storage code lands a
        whole durable file. A crash or fault at any point leaves either
        the old file or no file — never a torn final path. The temp file
        classifies as its final name, so faults aimed at e.g.
        ``checkpoint`` hit here too; a failed temp write is removed."""
        d = os.path.dirname(path) or "."
        tmp = os.path.join(d, f".{os.path.basename(path)}.tmp")
        try:
            with self.open(tmp, "wb") as f:
                self.write(f, path, payload)
                self.fsync(f, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass  # best-effort temp cleanup; the original error propagates
            raise
        self.replace(tmp, path)


def _os_error(kind: str, op: str, path: str) -> OSError:
    if kind == "enospc":
        return DiskFaultError(errno.ENOSPC, f"injected ENOSPC: {op} {path}")
    return DiskFaultError(errno.EIO, f"injected EIO: {op} {path}")


#: process-wide seam instance; spawned dbnodes inherit a plan from the
#: env at import, tests swap one in with :func:`install_plan`
DISK = DiskIO(plan_from_env())


def install_plan(plan: DiskFaultPlan | None) -> None:
    DISK.plan = plan


# -- crash points --

# test hook: unit tests monkeypatch this to observe the trip without
# dying; spawned-process gates leave it as os._exit (a hard exit that
# skips atexit/finally — the closest in-process stand-in for SIGKILL)
_exit = os._exit


def armed_crash_points(env=None) -> frozenset:
    raw = (env if env is not None else os.environ).get(CRASH_POINT_ENV, "")
    return frozenset(s.strip() for s in raw.split(",") if s.strip())


def crash_point(site: str) -> None:
    """Hard-exit the process iff ``site`` is armed via env. Sites live at
    exact commit-protocol boundaries; the env read happens per call so a
    fixture can arm between restarts of the same process image."""
    if site in armed_crash_points():
        sys.stderr.write(f"CRASH_POINT {site}\n")
        sys.stderr.flush()
        _exit(CRASH_EXIT_CODE)
