"""Background mediator: the clock-driven lifecycle loop of a storage node.

Reference: /root/reference/src/dbnode/storage/mediator.go:78 — a running node
ticks, warm/cold-flushes, snapshots, and cleans up continuously; nothing in
the durability machinery waits for an operator call. Here one daemon thread
per Database drives `run_once` on an interval; tests drive `run_once(now)`
directly with a fake clock, so every transition is deterministic.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from .series import NANOS


@dataclass
class MediatorOptions:
    """Cadence knobs (flush manager / tick defaults in the reference)."""

    tick_interval_nanos: int = 10 * NANOS
    # wall-clock pause between run_once calls of the background thread
    loop_interval_secs: float = 1.0
    # a block flushes once now >= block_end + buffer_past (flush_mgr.go)
    buffer_past_nanos: int = 10 * 60 * NANOS
    snapshot_interval_nanos: int = 60 * NANOS
    # floor between flush passes when the cutoff block hasn't advanced —
    # flush also runs WAL/snapshot cleanup (O(sealed bytes) disk reads), so
    # it must not run every loop pass
    flush_interval_nanos: int = 60 * NANOS


class Mediator:
    """Drives tick → flush → snapshot for one Database."""

    def __init__(
        self,
        db,
        opts: MediatorOptions | None = None,
        clock=time.time_ns,
        runtime=None,
    ):
        self.db = db
        self.opts = opts or MediatorOptions()
        self.clock = clock
        if runtime is not None:
            # live reconfig (storage/runtime.py): cadence updates apply on
            # the next pass
            runtime.watch(self._apply_runtime)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._last_tick = 0
        self._last_snapshot = 0
        self._last_flush = 0
        self._last_cutoff: dict[str, int] = {}
        self.runs = 0
        self.errors = 0
        self.last_error: BaseException | None = None

    def _apply_runtime(self, ro) -> None:
        self.opts.tick_interval_nanos = int(ro.tick_interval_secs * NANOS)
        self.opts.flush_interval_nanos = int(ro.flush_interval_secs * NANOS)
        self.opts.snapshot_interval_nanos = int(ro.snapshot_interval_secs * NANOS)
        self.opts.buffer_past_nanos = int(ro.buffer_past_secs * NANOS)

    # -- one deterministic pass (tests call this with a fake now) --

    def run_once(self, now_nanos: int | None = None) -> dict:
        now = self.clock() if now_nanos is None else now_nanos
        did: dict = {"tick": False, "flushed": [], "snapshots": 0}
        if now - self._last_tick >= self.opts.tick_interval_nanos:
            self.db.tick(now)
            self._last_tick = now
            did["tick"] = True
        flush_due = now - self._last_flush >= self.opts.flush_interval_nanos
        for name, ns in list(self.db.namespaces.items()):
            bsz = ns.opts.block_size_nanos
            cutoff = ((now - self.opts.buffer_past_nanos) // bsz) * bsz
            # flush when the cutoff reaches a new block (warm flush due) or
            # on the periodic interval (drains cold writes + cleanup)
            if not flush_due and cutoff <= self._last_cutoff.get(name, -1):
                continue
            flushed = self.db.flush(name, cutoff)
            self._last_cutoff[name] = cutoff
            self._last_flush = now
            if flushed:
                did["flushed"].extend(flushed)
        if now - self._last_snapshot >= self.opts.snapshot_interval_nanos:
            for name in list(self.db.namespaces):
                did["snapshots"] += self.db.snapshot(name)
            self._last_snapshot = now
        self.runs += 1
        return did

    # -- background thread --

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.opts.loop_interval_secs):
                try:
                    self.run_once()
                except Exception as exc:  # noqa: BLE001 — the lifecycle loop
                    # must survive transient errors (disk full, races); a
                    # dead mediator silently stops all durability work
                    self.errors += 1
                    self.last_error = exc

        self._thread = threading.Thread(target=loop, name="m3tpu-mediator", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10)
        self._thread = None
