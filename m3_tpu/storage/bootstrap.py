"""Bootstrap process: an ordered source chain with shard-time-range
accounting.

Reference: /root/reference/src/dbnode/storage/bootstrap/process.go:147 —
the process computes the shard-time-ranges a node must cover (its owned
shards × the retention window's block starts), then walks the bootstrapper
chain (filesystem → commitlog+snapshot → peers → uninitialized_topology,
bootstrapper/base.go); each source claims the sub-ranges it can fulfill
and passes the remainder down. Peers (bootstrapper/peers/source.go:117)
streams shards with no local provenance from replicas; uninitialized
claims ranges no replica can serve (a brand-new cluster's shards).

Sources here are callables bound to Database internals:

    source(ns_name, remaining: ShardTimeRanges) -> ShardTimeRanges  # fulfilled

The Database composes its fs/snapshot/commitlog restoration into such
callables (database.py bootstrap()); ClusterDatabase supplies the peers
source for shards gained through placement changes (AssignShardSet
semantics, database.go:386)."""

from __future__ import annotations

from dataclasses import dataclass, field


class ShardTimeRanges:
    """shard id → set of block-start nanos still to cover."""

    def __init__(self, ranges: dict[int, set[int]] | None = None) -> None:
        self.ranges: dict[int, set[int]] = {
            s: set(bs) for s, bs in (ranges or {}).items() if bs
        }

    @staticmethod
    def for_window(
        shard_ids, start_nanos: int, end_nanos: int, block_size_nanos: int
    ) -> "ShardTimeRanges":
        first = (start_nanos // block_size_nanos) * block_size_nanos
        blocks = set(range(first, end_nanos, block_size_nanos))
        return ShardTimeRanges({s: set(blocks) for s in shard_ids})

    def is_empty(self) -> bool:
        return not self.ranges

    def num_blocks(self) -> int:
        return sum(len(bs) for bs in self.ranges.values())

    def shards(self) -> list[int]:
        return sorted(self.ranges)

    def copy(self) -> "ShardTimeRanges":
        return ShardTimeRanges(self.ranges)

    def add(self, shard: int, block_start: int) -> None:
        self.ranges.setdefault(shard, set()).add(block_start)

    def add_shard_blocks(self, shard: int, block_starts) -> None:
        if block_starts:
            self.ranges.setdefault(shard, set()).update(block_starts)

    def subtract(self, other: "ShardTimeRanges") -> None:
        for s, bs in other.ranges.items():
            mine = self.ranges.get(s)
            if mine is None:
                continue
            mine -= bs
            if not mine:
                del self.ranges[s]

    def intersect(self, other: "ShardTimeRanges") -> "ShardTimeRanges":
        out: dict[int, set[int]] = {}
        for s, bs in self.ranges.items():
            ob = other.ranges.get(s)
            if ob:
                common = bs & ob
                if common:
                    out[s] = common
        return ShardTimeRanges(out)

    def to_dict(self) -> dict[int, list[int]]:
        return {s: sorted(bs) for s, bs in sorted(self.ranges.items())}

    def __repr__(self) -> str:  # debugging / bootstrap result logging
        return f"ShardTimeRanges({self.to_dict()})"


@dataclass
class BootstrapResult:
    """Per-source fulfillment accounting (bootstrap/result/ role)."""

    target_blocks: int = 0
    fulfilled_by_source: dict[str, int] = field(default_factory=dict)
    unfulfilled: dict[int, list[int]] = field(default_factory=dict)

    def record(self, source_name: str, fulfilled: ShardTimeRanges) -> None:
        self.fulfilled_by_source[source_name] = (
            self.fulfilled_by_source.get(source_name, 0) + fulfilled.num_blocks()
        )


class BootstrapProcess:
    """Walk the source chain, each claiming from the remaining ranges."""

    def __init__(self, sources: list[tuple[str, object]]) -> None:
        self.sources = sources  # [(name, callable)]

    def run(self, ns_name: str, target: ShardTimeRanges) -> BootstrapResult:
        result = BootstrapResult(target_blocks=target.num_blocks())
        remaining = target.copy()
        for name, source in self.sources:
            if remaining.is_empty():
                break
            fulfilled = source(ns_name, remaining)
            # a source may only claim what was still remaining
            fulfilled = fulfilled.intersect(remaining)
            result.record(name, fulfilled)
            remaining.subtract(fulfilled)
        result.unfulfilled = remaining.to_dict()
        return result


def uninitialized_source(has_peer_with_shard=None):
    """Last-chain source (bootstrapper/uninitialized): claim ranges no
    replica can serve — a brand-new cluster's shards legitimately start
    empty. ``has_peer_with_shard(shard) -> bool`` narrows the claim when
    topology knowledge exists; with none, everything left is claimed."""

    def source(ns_name: str, remaining: ShardTimeRanges) -> ShardTimeRanges:
        out = ShardTimeRanges()
        for shard, blocks in remaining.ranges.items():
            if has_peer_with_shard is not None and has_peer_with_shard(shard):
                continue  # a peer owns data for this shard: do NOT claim empty
            out.add_shard_blocks(shard, blocks)
        return out

    return source
