"""Active anti-entropy repair: checksum-diff replicas, stream only diffs.

Reference: /root/reference/src/dbnode/storage/repair.go:67 — shardRepairer
compares per-(series, block) metadata (size + checksum) across replicas and
streams only the blocks whose metadata differs, instead of full-shard
copies. Metadata here is (point count, adler32) over the DECODED merged
point set of each series block — flushed filesets and in-memory buffers
digest identically, so repair converges regardless of flush timing.
"""

from __future__ import annotations

import struct
import threading
import time
import zlib
from dataclasses import dataclass, field

from ..utils.instrument import DEFAULT as METRICS
from ..utils.schedule import FixedRateTicker
from ..utils.serialize import decode_tags, is_tag_id
from .database import ColdWriteError
from ..utils.xtime import Unit

_PT = struct.Struct("<qdB")  # canonical per-point record for digests

# transport-shaped failures a repair pass survives; programming errors
# (AttributeError/TypeError/...) propagate
_PEER_ERRORS = (ConnectionError, OSError, RuntimeError, ValueError)


def default_tags_for(sid: bytes):
    """Recover tags from canonical tag-format series IDs (utils/serialize)
    so repaired points maintain the reverse index."""
    if is_tag_id(sid):
        try:
            return tuple(sorted(decode_tags(sid)))
        except ValueError:
            return None
    return None


def _canonical_digest(sh, sid: bytes, bs: int, bsz: int):
    """(count, checksum) over the DECODED merged point set of one series
    block — canonical across flush states (buffered, flushed, or cold
    writes atop a flushed volume all digest identically). The digest bytes
    are the packed '<qdB' per-point records; the numpy structured layout
    below is byte-identical, so the native-array fast path and the
    Datapoint fallback produce the same checksum."""
    dps = sh.read(sid, bs, bs + bsz, populate_cache=False)
    if not dps:
        return None
    import numpy as np

    rec = np.empty(
        len(dps), dtype=np.dtype([("t", "<i8"), ("v", "<f8"), ("u", "u1")])
    )
    rec["t"] = [dp.timestamp for dp in dps]
    rec["v"] = [dp.value for dp in dps]
    rec["u"] = [int(dp.unit) for dp in dps]
    assert rec.dtype.itemsize == _PT.size
    return [len(dps), zlib.adler32(rec.tobytes())]


def block_metadata(db, ns: str, shard_id: int) -> list[list]:
    """[[block_start, sid, n_points, checksum], ...] for one shard — the
    repair metadata exchange (repair.go Metadata step). Digests are over
    decoded points, so replicas at different flush stages compare equal.

    The global lock is held only to snapshot the key set and per digest —
    not across the whole scan — so serving traffic interleaves."""
    with db.lock:
        namespace = db.namespaces[ns]
        bsz = namespace.opts.block_size_nanos
        sh = namespace.shards[shard_id]
        keys: set[tuple[int, bytes]] = set()
        for fid in sh.filesets():
            reader = sh.reader_or_none(fid)
            if reader is None:
                continue  # retention raced it away or it just quarantined
            for sid in reader.series_ids:
                keys.add((fid.block_start, sid))
        for sid, buf in sh.series.items():
            for bs in buf.buckets:
                keys.add((bs, sid))
    out = []
    for bs, sid in sorted(keys):
        with db.lock:
            digest = _canonical_digest(sh, sid, bs, bsz)
        if digest is not None:
            out.append([bs, sid, digest[0], digest[1]])
    return out


def stream_series_blocks(
    db, ns: str, items: list[tuple[bytes, int]], shard_id: int | None = None
) -> list:
    """[(sid, block_start, datapoints)] for the requested series-blocks —
    the repair data fetch (only differing blocks are requested). When
    ``shard_id`` is given, requests for series outside that shard are
    rejected (the RPC is scoped per shard)."""
    with db.lock:
        namespace = db.namespaces[ns]
        bsz = namespace.opts.block_size_nanos
        out = []
        for sid, bs in items:
            sh = namespace.shard_for(sid)
            if shard_id is not None and sh.id != shard_id:
                raise ValueError(
                    f"series {sid!r} belongs to shard {sh.id}, not {shard_id}"
                )
            dps = sh.read(sid, bs, bs + bsz, populate_cache=False)
            out.append((sid, bs, dps))
        return out


@dataclass
class RepairResult:
    """shardRepairer result counters (repair.go repair stats)."""

    shards_repaired: int = 0
    blocks_compared: int = 0
    blocks_streamed: int = 0
    points_merged: int = 0
    # diffs in flushed blocks of cold-disabled namespaces can't backfill
    # through the write path; counted, not errors (repair still converges
    # everything repairable)
    points_skipped_cold: int = 0
    peer_errors: list = field(default_factory=list)


def repair_shard(db, ns: str, shard_id: int, peers: list, tags_for=None) -> RepairResult:
    """Compare this node's (series, block) checksums with each peer's;
    stream ONLY differing/missing blocks and merge them locally.

    ``peers`` expose block_metadata(ns, shard) / stream_series_blocks(ns,
    shard, items) — the net.client.RemoteNode surface.
    """
    if tags_for is None:
        tags_for = default_tags_for
    res = RepairResult()
    namespace = db.namespaces[ns]
    bsz = namespace.opts.block_size_nanos
    local = {
        (bs, bytes(sid)): [n, crc]
        for bs, sid, n, crc in block_metadata(db, ns, shard_id)
    }
    for peer in peers:
        try:
            peer_meta = peer.block_metadata(ns, shard_id)
        except _PEER_ERRORS as exc:
            res.peer_errors.append(str(exc))
            continue
        need = []
        for bs, sid, n, crc in peer_meta:
            sid = bytes(sid)
            res.blocks_compared += 1
            if local.get((bs, sid)) != [n, crc]:
                need.append((sid, bs))
        if not need:
            continue
        try:
            streamed = peer.stream_series_blocks(ns, shard_id, need)
        except _PEER_ERRORS as exc:
            res.peer_errors.append(str(exc))
            continue
        for sid, bs, dps in streamed:
            sid = bytes(sid)
            res.blocks_streamed += 1
            sh = namespace.shard_for(sid)
            have = {
                dp.timestamp
                for dp in sh.read(sid, bs, bs + bsz, populate_cache=False)
            }
            # replication context (selfmon/guard.py): repairing a reserved
            # self-monitoring namespace moves telemetry a sanctioned
            # writer already admitted on the source replica
            from ..selfmon.guard import selfmon_writer

            with selfmon_writer():
                for dp in dps:
                    if dp.timestamp in have:
                        continue
                    unit = dp.unit if isinstance(dp.unit, Unit) else Unit(dp.unit)
                    try:
                        if (tags := tags_for(sid)):
                            db.write_tagged(ns, tags, dp.timestamp, dp.value, unit)
                        else:
                            db.write(ns, sid, dp.timestamp, dp.value, unit)
                        res.points_merged += 1
                    except ColdWriteError:
                        res.points_skipped_cold += 1
            # repaired block re-merges from source on next read (points
            # route through the write path, which fires on_write per point;
            # this covers blocks whose every point was skipped cold)
            db.cache_invalidator.on_repair(ns, sh.id, sid, bs)
            # refresh the local digest so later peers don't re-stream what
            # this peer just repaired
            local[(bs, sid)] = _canonical_digest(sh, sid, bs, bsz)
    res.shards_repaired = 1
    return res


# --- background scrubber (the read side of the fault-tolerance plane) ---

_M_SCRUB_PASSES = METRICS.counter(
    "storage_scrub_passes_total", "completed background scrub passes"
)
_M_SCRUB_BYTES = METRICS.counter(
    "storage_scrub_bytes_total", "fileset bytes digest-verified by the scrubber"
)
_M_SCRUB_ERRORS = METRICS.counter(
    "storage_scrub_errors_total", "scrub passes aborted by an unexpected error"
)


class Scrubber:
    """Background scrub daemon: digest-verifies every sealed fileset
    volume at a fixed cadence (FixedRateTicker — absolute schedule,
    per-node phase spread) with a bounded read rate, so silent media
    corruption is found within one scrub period instead of at the next
    unlucky query. Any mismatch quarantines the volume through the
    shard's invalidation seam and the repair plane re-replicates.

    ``bytes_per_sec`` and ``iops`` pace the pass: after each fileset the
    loop sleeps until the pass's cumulative read rate AND file-open rate
    both fall back under budget — whichever budget is further behind wins
    (0 = that dimension unpaced — tools and tests). Verifying one fileset
    opens every file role once, so opens are modeled as len(SUFFIXES) per
    fileset. ``quarantine_retention_secs`` > 0 additionally runs
    quarantine retention GC (fs.prune_quarantine) at the end of each
    pass, bounding post-mortem disk held by quarantined volumes to one
    retention window. ``run_once`` is the deterministic synchronous entry
    point the daemon loop and tests share."""

    def __init__(
        self,
        db,
        interval: float = 300.0,
        bytes_per_sec: int = 32 << 20,
        iops: int = 0,
        quarantine_retention_secs: float = 0.0,
        phase_key: str = "scrubber",
        clock=time.monotonic,
        sleep=time.sleep,
    ) -> None:
        self.db = db
        self.interval = float(interval)
        self.bytes_per_sec = int(bytes_per_sec)
        self.iops = int(iops)
        self.quarantine_retention_secs = float(quarantine_retention_secs)
        self.phase_key = phase_key
        self._clock = clock
        self._sleep = sleep
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.passes = 0
        self.quarantined = 0

    def run_once(self) -> dict:
        from . import fs as fsm

        totals = {"scanned": 0, "quarantined": 0, "bytes": 0, "opens": 0, "pruned": 0}
        start = self._clock()
        for name in list(self.db.namespaces):
            namespace = self.db.namespaces.get(name)
            if namespace is None:
                continue  # namespace dropped mid-pass
            for shard in namespace.shards:
                for fid in fsm.list_fileset_volumes(
                    self.db.base, shard.namespace, shard.id
                ):
                    if self._stop.is_set():
                        return totals
                    totals["bytes"] += fsm.fileset_bytes(self.db.base, fid)
                    problems = fsm.verify_fileset(self.db.base, fid)
                    totals["scanned"] += 1
                    if problems:
                        with shard.lock:
                            # retention/supersede deletes happen under the
                            # shard lock — re-verify under it so a fileset
                            # deleted mid-verify doesn't count as corrupt
                            if fsm.fileset_complete(self.db.base, fid):
                                problems = fsm.verify_fileset(self.db.base, fid)
                                if problems:
                                    shard._quarantine_locked(fid, problems)
                                    totals["quarantined"] += 1
                    totals["opens"] += len(fsm.SUFFIXES)
                    elapsed = self._clock() - start
                    ahead = 0.0
                    if self.bytes_per_sec > 0:
                        ahead = totals["bytes"] / self.bytes_per_sec - elapsed
                    if self.iops > 0:
                        ahead = max(
                            ahead, totals["opens"] / self.iops - elapsed
                        )
                    if ahead > 0:
                        self._sleep(ahead)
        if self.quarantine_retention_secs > 0:
            totals["pruned"] = fsm.prune_quarantine(
                self.db.base, self.quarantine_retention_secs
            )
        self.passes += 1
        self.quarantined += totals["quarantined"]
        _M_SCRUB_PASSES.inc()
        _M_SCRUB_BYTES.inc(totals["bytes"])
        return totals

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="storage-scrubber"
        )
        self._thread.start()

    def _loop(self) -> None:
        ticker = FixedRateTicker(
            self.interval, phase_key=self.phase_key, stop=self._stop
        )
        while True:
            stopped, _missed = ticker.wait_next()
            if stopped:
                return
            try:
                self.run_once()
            except Exception:
                # the daemon must outlive one bad pass (a fileset deleted
                # under it, a transient read error) — counted, not fatal
                _M_SCRUB_ERRORS.inc()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def repair_database(db, ns: str, peers: list, shard_ids=None, tags_for=None) -> RepairResult:
    """Repair every (or the given) shards against the peer set."""
    total = RepairResult()
    namespace = db.namespaces[ns]
    ids = range(len(namespace.shards)) if shard_ids is None else shard_ids
    for shard_id in ids:
        r = repair_shard(db, ns, shard_id, peers, tags_for=tags_for)
        total.shards_repaired += r.shards_repaired
        total.blocks_compared += r.blocks_compared
        total.blocks_streamed += r.blocks_streamed
        total.points_merged += r.points_merged
        total.points_skipped_cold += r.points_skipped_cold
        total.peer_errors.extend(r.peer_errors)
    return total
