"""Cluster-aware node runtime: placement watch → shard assignment → peers
bootstrap, inside the node process.

Reference: /root/reference/src/dbnode/storage/cluster/database.go — the
clusterDatabase wraps a storage database, watches the dynamic topology
(src/dbnode/topology/dynamic.go:107), and on placement change calls
db.AssignShardSet (src/dbnode/storage/database.go:386), which triggers a
bootstrap of the gained shards; the peers bootstrapper then streams those
shards' data from replicas (bootstrapper/peers/source.go:117). Once a
gained shard's data is in, the node marks it AVAILABLE through the
placement service CAS so the source's LEAVING shard is dropped
(placement/service MarkShardsAvailable).

Here the same loop runs over the networked control plane: the placement
arrives through a (Remote)KVStore watch; peers are reached through the
socket data plane (net.client.RemoteNode) using the endpoints recorded in
the placement instances.
"""

from __future__ import annotations

import threading

from ..cluster.placement import Placement, PlacementService, ShardState
from ..utils.instrument import DEFAULT as METRICS


def _default_peer_factory(endpoint: str):
    from ..net.client import RemoteNode

    return RemoteNode.connect(endpoint)


class ClusterDatabase:
    """Watch placement; apply shard ownership; peers-bootstrap gained shards.

    ``node_service`` is the RPC dispatch object whose ``assigned_shards``
    gates reads; ``db`` is the storage Database written into during peer
    streaming.
    """

    def __init__(
        self,
        db,
        node_id: str,
        placement_svc: PlacementService,
        node_service=None,
        peer_factory=_default_peer_factory,
        on_bootstrapped=None,
        retry_secs: float = 2.0,
    ) -> None:
        self.db = db
        self.node_id = node_id
        self.placement_svc = placement_svc
        self.node_service = node_service
        self.peer_factory = peer_factory
        self.on_bootstrapped = on_bootstrapped
        self.retry_secs = retry_secs
        self._lock = threading.Lock()
        self._bootstrapping: set[int] = set()
        self._stopped = threading.Event()
        self._unsub = None

    def start(self) -> None:
        self._stopped.clear()
        self._unsub = self.placement_svc.watch(self._on_placement)

    def stop(self) -> None:
        self._stopped.set()
        if self._unsub is not None:
            self._unsub()
            self._unsub = None

    # -- placement reaction --

    def _on_placement(self, p: Placement) -> None:
        inst = p.instances.get(self.node_id)
        shards = set(inst.shards) if inst else set()
        if self.node_service is not None:
            self.node_service.assigned_shards = shards
        if inst is None:
            return
        with self._lock:
            gained = [
                (s, a)
                for s, a in inst.shards.items()
                if a.state == ShardState.INITIALIZING and s not in self._bootstrapping
            ]
            self._bootstrapping.update(s for s, _ in gained)
        if gained:
            # streaming can take a while; never block the watch thread
            threading.Thread(
                target=self._bootstrap_gained, args=(p, gained), daemon=True,
                name=f"peers-bootstrap-{self.node_id}",
            ).start()

    # -- peers bootstrap for gained INITIALIZING shards --

    def _stream_sources(self, p: Placement, shard: int, preferred: str | None):
        """Candidate peers ordered: preferred source first (the leaving
        instance during a handoff, if still up), then AVAILABLE replicas."""
        ordered = []
        if preferred and preferred in p.instances:
            ordered.append(p.instances[preferred])
        for inst in p.instances.values():
            a = inst.shards.get(shard)
            if inst.id in (self.node_id, preferred) or a is None:
                continue
            if a.state in (ShardState.AVAILABLE, ShardState.LEAVING):
                ordered.append(inst)
        return [i for i in ordered if i.endpoint]

    def _bootstrap_gained(self, p: Placement, gained) -> None:
        """Run the gained shards through the node's OWN bootstrap chain
        (fs → commitlog+snapshot → peers → uninitialized) with
        shard-time-range accounting — the AssignShardSet-driven bootstrap
        of database.go:386/:442 with bootstrapper/peers as the streaming
        source."""
        gained_ids = [s for s, _ in gained]
        preferred = {s: a.source_instance for s, a in gained}

        def peers_source(ns_name: str, shard: int):
            for src in self._stream_sources(p, shard, preferred.get(shard)):
                try:
                    peer = self.peer_factory(src.endpoint)
                except Exception:
                    continue
                try:
                    return peer.stream_shard(ns_name, shard)
                except Exception:
                    continue  # dead/unreachable peer: try the next replica
                finally:
                    try:
                        peer.close()
                    except Exception:
                        # m3lint: disable=M3L007 -- best-effort close of a peer that just failed to stream; nothing to act on
                        pass
            return None  # nothing reachable held this shard

        def has_peer_with_shard(shard: int) -> bool:
            return any(
                inst.shards.get(shard) is not None
                and inst.shards[shard].state
                in (ShardState.AVAILABLE, ShardState.LEAVING)
                for inst in p.instances.values()
                if inst.id != self.node_id
            )

        try:
            res = self.db.bootstrap_shards(
                gained_ids, peers_source, has_peer_with_shard
            )
            unfulfilled: set[int] = set()
            for ns_res in res.get("sources", {}).values():
                unfulfilled |= {int(s) for s in ns_res.get("unfulfilled", {})}
        except Exception:
            unfulfilled = set(gained_ids)
        done = [s for s in gained_ids if s not in unfulfilled]
        failed = bool(unfulfilled)
        with self._lock:
            for shard in gained_ids:
                self._bootstrapping.discard(shard)
        if done:
            self._mark_available(done)
            METRICS.counter("peers_bootstrap_shards_total").inc(len(done))
            if self.on_bootstrapped is not None:
                self.on_bootstrapped(done)
        if failed and not self._stopped.is_set():
            # a transiently unreachable source must not wedge the shard in
            # INITIALIZING until some unrelated placement write: re-drive
            # the current placement after a backoff (bootstrap retry loop,
            # bootstrap.go's repeated-attempt semantics)
            def _retry() -> None:
                if self._stopped.wait(self.retry_secs):
                    return
                try:
                    cur = self.placement_svc.get()
                except Exception:
                    cur = None
                if cur is not None:
                    self._on_placement(cur)

            threading.Thread(
                target=_retry, daemon=True,
                name=f"peers-bootstrap-retry-{self.node_id}",
            ).start()

    def _mark_available(self, shards: list[int]) -> None:
        from ..cluster.placement import mark_shards_available

        while True:
            cur, version = self.placement_svc.get_versioned()
            if cur is None or self.node_id not in cur.instances:
                return
            mark_shards_available(cur, self.node_id, shards)
            try:
                self.placement_svc.check_and_set(cur, version)
                return
            except ValueError:
                continue  # placement moved; re-read and re-apply
