"""Cluster-aware node runtime: placement watch → shard assignment → peers
bootstrap, inside the node process.

Reference: /root/reference/src/dbnode/storage/cluster/database.go — the
clusterDatabase wraps a storage database, watches the dynamic topology
(src/dbnode/topology/dynamic.go:107), and on placement change calls
db.AssignShardSet (src/dbnode/storage/database.go:386), which triggers a
bootstrap of the gained shards; the peers bootstrapper then streams those
shards' data from replicas (bootstrapper/peers/source.go:117). Once a
gained shard's data is in, the node marks it AVAILABLE through the
placement service CAS so the source's LEAVING shard is dropped
(placement/service MarkShardsAvailable).

Here the same loop runs over the networked control plane: the placement
arrives through a (Remote)KVStore watch; peers are reached through the
socket data plane (net.client.RemoteNode) using the endpoints recorded in
the placement instances.
"""

from __future__ import annotations

import threading

from ..cluster.placement import Placement, PlacementService, ShardState
from ..utils.instrument import DEFAULT as METRICS


def _default_peer_factory(endpoint: str):
    from ..net.client import RemoteNode

    return RemoteNode.connect(endpoint)


class ClusterDatabase:
    """Watch placement; apply shard ownership; peers-bootstrap gained shards.

    ``node_service`` is the RPC dispatch object whose ``assigned_shards``
    gates reads; ``db`` is the storage Database written into during peer
    streaming.
    """

    def __init__(
        self,
        db,
        node_id: str,
        placement_svc: PlacementService,
        node_service=None,
        peer_factory=_default_peer_factory,
        on_bootstrapped=None,
        retry_secs: float = 2.0,
        migration_enabled: bool = True,
        migration_chunk_bytes: int = 1 << 20,
        migration_chunk_timeout: float = 5.0,
    ) -> None:
        self.db = db
        self.node_id = node_id
        self.placement_svc = placement_svc
        self.node_service = node_service
        self.peer_factory = peer_factory
        self.on_bootstrapped = on_bootstrapped
        self.retry_secs = retry_secs
        self.migration_enabled = migration_enabled
        self.migration_chunk_bytes = migration_chunk_bytes
        self.migration_chunk_timeout = migration_chunk_timeout
        self._lock = threading.Lock()
        self._bootstrapping: set[int] = set()
        self._assigned: set[int] | None = None  # None until first placement
        self._stopped = threading.Event()
        self._unsub = None

    def start(self) -> None:
        self._stopped.clear()
        self._unsub = self.placement_svc.watch(self._on_placement)

    def stop(self) -> None:
        self._stopped.set()
        if self._unsub is not None:
            self._unsub()
            self._unsub = None

    # -- placement reaction --

    def _on_placement(self, p: Placement) -> None:
        inst = p.instances.get(self.node_id)
        shards = set(inst.shards) if inst else set()
        if self.node_service is not None:
            self.node_service.assigned_shards = shards
        with self._lock:
            lost = self._assigned - shards if self._assigned is not None else set()
            self._assigned = shards
        if lost:
            # source side of a handoff: the receiver marked our shard's
            # replacement AVAILABLE and the placement dropped it here —
            # free its residency so the surviving shards get the budget
            self._on_shards_lost(sorted(lost))
        if inst is None:
            return
        with self._lock:
            gained = [
                (s, a)
                for s, a in inst.shards.items()
                if a.state == ShardState.INITIALIZING and s not in self._bootstrapping
            ]
            self._bootstrapping.update(s for s, _ in gained)
        if gained:
            # streaming can take a while; never block the watch thread
            threading.Thread(
                target=self._bootstrap_gained, args=(p, gained), daemon=True,
                name=f"peers-bootstrap-{self.node_id}",
            ).start()

    # -- peers bootstrap for gained INITIALIZING shards --

    def _stream_sources(self, p: Placement, shard: int, preferred: str | None):
        """Candidate peers ordered: preferred source first (the leaving
        instance during a handoff, if still up), then AVAILABLE replicas."""
        ordered = []
        if preferred and preferred in p.instances:
            ordered.append(p.instances[preferred])
        for inst in p.instances.values():
            a = inst.shards.get(shard)
            if inst.id in (self.node_id, preferred) or a is None:
                continue
            if a.state in (ShardState.AVAILABLE, ShardState.LEAVING):
                ordered.append(inst)
        return [i for i in ordered if i.endpoint]

    def _bootstrap_gained(self, p: Placement, gained) -> None:
        """Run the gained shards through the node's OWN bootstrap chain
        (fs → commitlog+snapshot → peers → uninitialized) with
        shard-time-range accounting — the AssignShardSet-driven bootstrap
        of database.go:386/:442 with bootstrapper/peers as the streaming
        source."""
        gained_ids = [s for s, _ in gained]
        preferred = {s: a.source_instance for s, a in gained}

        # warm residency migration BEFORE the bootstrap chain runs: pull
        # sealed blocks' raw fileset bytes (compressed pages + packed side
        # planes) from the handoff sources so the resident pool and index
        # are warm before the shards flip AVAILABLE. Returns the migrated
        # block starts per (ns, shard); the decoded peers stream below
        # excludes them so sealed content never re-enters the write path
        # (re-buffering would force the streamed scan path post-cutover).
        migrated = self._migrate_gained(p, gained)

        def peers_source(ns_name: str, shard: int):
            excl = sorted(migrated.get((ns_name, shard), ()))
            for src in self._stream_sources(p, shard, preferred.get(shard)):
                try:
                    peer = self.peer_factory(src.endpoint)
                except Exception:
                    continue
                try:
                    return peer.stream_shard(ns_name, shard, exclude_blocks=excl)
                except Exception:
                    continue  # dead/unreachable peer: try the next replica
                finally:
                    try:
                        peer.close()
                    except Exception:
                        # m3lint: disable=M3L007 -- best-effort close of a peer that just failed to stream; nothing to act on
                        pass
            return None  # nothing reachable held this shard

        def has_peer_with_shard(shard: int) -> bool:
            return any(
                inst.shards.get(shard) is not None
                and inst.shards[shard].state
                in (ShardState.AVAILABLE, ShardState.LEAVING)
                for inst in p.instances.values()
                if inst.id != self.node_id
            )

        try:
            res = self.db.bootstrap_shards(
                gained_ids, peers_source, has_peer_with_shard
            )
            unfulfilled: set[int] = set()
            for ns_res in res.get("sources", {}).values():
                unfulfilled |= {int(s) for s in ns_res.get("unfulfilled", {})}
        except Exception:
            unfulfilled = set(gained_ids)
        done = [s for s in gained_ids if s not in unfulfilled]
        failed = bool(unfulfilled)
        with self._lock:
            for shard in gained_ids:
                self._bootstrapping.discard(shard)
        if done:
            self._mark_available(done)
            METRICS.counter("peers_bootstrap_shards_total").inc(len(done))
            # topology changed and the gained shards are serving: re-split
            # the resident byte budget by observed demand so cold incumbent
            # shards shed pages the migrated hot shards are owed
            self._rebalance_pool()
            if self.on_bootstrapped is not None:
                self.on_bootstrapped(done)
        if failed and not self._stopped.is_set():
            # a transiently unreachable source must not wedge the shard in
            # INITIALIZING until some unrelated placement write: re-drive
            # the current placement after a backoff (bootstrap retry loop,
            # bootstrap.go's repeated-attempt semantics)
            def _retry() -> None:
                if self._stopped.wait(self.retry_secs):
                    return
                try:
                    cur = self.placement_svc.get()
                except Exception:
                    cur = None
                if cur is not None:
                    self._on_placement(cur)

            threading.Thread(
                target=_retry, daemon=True,
                name=f"peers-bootstrap-retry-{self.node_id}",
            ).start()

    # -- warm residency migration (sealed fileset bytes move ahead of cutover) --

    def _migrate_gained(self, p: Placement, gained) -> dict:
        """Stream sealed filesets' raw bytes from the handoff sources for
        every gained shard, hottest shard first, committing + admitting
        each fileset as it lands so the resident pool and device index
        warm BEFORE the shard flips AVAILABLE.

        Returns {(ns_name, shard): {block_start, ...}} of blocks whose
        fileset content was committed locally — the decoded peers stream
        excludes exactly these. A shard whose every source fails mid-way
        falls back to the decoded fileset-driven rebuild for whatever was
        not yet committed (counted, never wedging INITIALIZING: committed
        filesets stay excluded, everything else streams normally)."""
        migrated: dict[tuple[str, int], set[int]] = {}
        if not self.migration_enabled:
            return migrated
        preferred = {s: a.source_instance for s, a in gained}
        peers: dict[str, object] = {}

        def _peer(endpoint: str):
            peer = peers.get(endpoint)
            if peer is None:
                peer = peers[endpoint] = self.peer_factory(endpoint)
            return peer

        # one residency-heat snapshot per distinct handoff source: order
        # the gained shards hottest-first so a budget cut or mid-handoff
        # death leaves warm what queries actually touch
        heat: dict[int, float] = {}
        for src_id in {preferred.get(s) for s, _ in gained}:
            inst = p.instances.get(src_id) if src_id else None
            if inst is None or not inst.endpoint:
                continue
            try:
                dump = _peer(inst.endpoint).resident_stats().get("shard_heat", {})
            except Exception:
                continue  # heat ordering is a hint; cold order still works
            for sid_str, h in dump.items():
                try:
                    sid = int(sid_str)
                except (TypeError, ValueError):
                    continue
                heat[sid] = (
                    heat.get(sid, 0.0)
                    + float(h.get("hits", 0))
                    + float(h.get("misses", 0))
                )

        with self.db.lock:
            ns_names = list(self.db.namespaces)
        order = sorted(
            (s for s, _ in gained), key=lambda s: heat.get(s, 0.0), reverse=True
        )
        try:
            for shard in order:
                sources = self._stream_sources(p, shard, preferred.get(shard))
                for ns_name in ns_names:
                    try:
                        n = self._migrate_shard(
                            ns_name, shard, sources, _peer, migrated
                        )
                    except Exception:
                        # all sources died mid-stream for this shard: the
                        # decoded rebuild covers the uncommitted remainder
                        METRICS.counter(
                            "migration_stream_failures_total",
                            "shard migrations that fell back to the decoded "
                            "fileset-driven rebuild",
                        ).inc()
                        continue
                    if n:
                        METRICS.counter(
                            "migration_shards_warm_total",
                            "(ns, shard) handoffs whose sealed filesets were "
                            "fully warm before cutover",
                        ).inc()
        finally:
            for peer in peers.values():
                try:
                    peer.close()
                except Exception:
                    # m3lint: disable=M3L007 -- best-effort close of migration peers; transfer already finished or failed
                    pass
        return migrated

    def _migrate_shard(self, ns_name, shard, sources, _peer, migrated) -> int:
        """Migrate one (ns, shard)'s sealed filesets. Sources are tried in
        placement order (preferred handoff source first); a source dying
        mid-file costs at most one chunk — the next source resumes at the
        local byte offset. Raises only when every source failed before the
        manifest drained (committed filesets stay in ``migrated``)."""
        from . import fs as _fs

        warmed = 0
        last_err = None
        for src in sources:
            try:
                peer = _peer(src.endpoint)
                manifest = peer.migrate_manifest(ns_name, shard)
            except Exception as e:
                last_err = e
                continue
            # newest blocks first: budget pushback in the pool keeps what
            # is admitted first, and the newest sealed blocks are hottest
            manifest.sort(
                key=lambda m: (m["blockStart"], m["volume"]), reverse=True
            )
            try:
                for entry in manifest:
                    fid = _fs.FilesetID(
                        ns_name, shard, int(entry["blockStart"]),
                        int(entry["volume"]),
                    )
                    if not _fs.fileset_complete(self.db.base, fid):
                        self._fetch_fileset(peer, src.id, fid, entry["files"])
                        _fs.commit_imported_fileset(self.db.base, fid)
                    self.db.admit_imported_fileset(ns_name, shard, fid)
                    migrated.setdefault((ns_name, shard), set()).add(
                        fid.block_start
                    )
                    warmed += 1
                    METRICS.counter(
                        "migration_filesets_total",
                        "sealed filesets committed + admitted via migration",
                    ).inc()
                return warmed
            except Exception as e:
                last_err = e
                continue
        if last_err is not None:
            raise last_err
        return warmed  # no reachable source held sealed data for this shard

    def _fetch_fileset(self, peer, peer_id: str, fid, files: dict) -> None:
        """Chunked resumable fetch of every streamable file role of one
        fileset: resume offset = local partial size, each chunk
        deadline-bounded and transparently retried under the idempotent-op
        budget. The checkpoint is never fetched — commit writes it locally
        LAST, so a partial import stays invisible to queries."""
        from . import fs as _fs

        base = self.db.base
        for suffix in _fs.MIGRATION_SUFFIXES:
            total = int(files.get(suffix, 0))
            offset = _fs.migration_file_size(base, fid, suffix)
            if offset == 0 and total == 0:
                # role exists but is empty: create it so commit can verify
                _fs.append_fileset_chunk(base, fid, suffix, 0, b"")
            while offset < total:
                resp = peer.migrate_fetch(
                    fid.namespace, fid.shard, fid.block_start, fid.volume,
                    suffix, offset, self.migration_chunk_bytes,
                    _timeout=self.migration_chunk_timeout,
                )
                data = resp["data"]
                if data:
                    _fs.append_fileset_chunk(base, fid, suffix, offset, data)
                    offset += len(data)
                    METRICS.counter(
                        "migration_streamed_bytes_total",
                        "raw fileset bytes pulled during shard handoff",
                        labels={"peer": peer_id},
                    ).inc(len(data))
                if resp.get("eof"):
                    # source file shorter than the manifest said: commit's
                    # digest verification decides whether that matters
                    break
                if not data:
                    raise OSError(
                        f"migration stalled: empty non-eof chunk for "
                        f"{fid} {suffix} @ {offset}"
                    )

    def _rebalance_pool(self) -> None:
        pool = getattr(self.db, "resident_pool", None)
        if pool is None or not getattr(pool, "enabled", False):
            return
        try:
            pool.rebalance(pool.heat.dump())
        except Exception:
            # m3lint: disable=M3L007 -- budget redistribution is advisory; a failure must not take down placement handling
            pass

    def _on_shards_lost(self, shards: list[int]) -> None:
        """Source-side cleanup after a handoff completes: the receiver is
        AVAILABLE and the placement no longer assigns these shards here.
        Reads are already gated by assigned_shards; drop the dead
        residency and re-split the budget across surviving shards."""
        pool = getattr(self.db, "resident_pool", None)
        if pool is None or not getattr(pool, "enabled", False):
            return
        dropped = 0
        for shard in shards:
            try:
                dropped += pool.drop_shard(None, shard)
            except Exception:
                continue  # best-effort cleanup; entries age out via LRU anyway
        if dropped:
            METRICS.counter(
                "migration_source_dropped_total",
                "resident entries dropped on the source after handoff",
            ).inc(dropped)
        self._rebalance_pool()

    def _mark_available(self, shards: list[int]) -> None:
        from ..cluster.placement import mark_shards_available

        while True:
            cur, version = self.placement_svc.get_versioned()
            if cur is None or self.node_id not in cur.instances:
                return
            mark_shards_available(cur, self.node_id, shards)
            try:
                self.placement_svc.check_and_set(cur, version)
                return
            except ValueError:
                continue  # placement moved; re-read and re-apply
