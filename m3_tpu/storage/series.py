"""Per-series in-memory buffer: block-windowed encoders with warm/cold writes.

Reference: /root/reference/src/dbnode/storage/series/ — dbSeries.Write
(series.go:289) routes datapoints into dbBuffer buckets per block window
(buffer.go:250); the warm/cold decision (:268-313) classifies writes inside
the buffer-past/buffer-future window as warm, everything else as cold
(out-of-order, flushed separately). Tick merges bucket encoders
(buffer.go:413-478).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..codec.m3tsz import Datapoint, Encoder, decode
from ..utils.xtime import Unit

NANOS = 1_000_000_000


@dataclass
class BufferBucket:
    """One RAW-COLUMN buffer per block window — buffer.go buckets.

    The reference buckets hold incremental encoders; here the hot write
    path is an O(1) column append (the per-point Python m3tsz encode cost
    ~25µs capped node ingest at ~25k writes/s/core), and the canonical
    m3tsz stream is produced lazily — through the NATIVE batch encoder —
    only when a reader or flush actually needs it, then cached until the
    next write. Merge semantics are unchanged: time-sorted, later write
    wins on duplicate timestamps (buffer.go:413-478)."""

    block_start: int
    times: list = field(default_factory=list)
    values: list = field(default_factory=list)
    units: list = field(default_factory=list)
    last_write_nanos: int = -1
    num_writes: int = 0
    _stream_cache: bytes | None = None
    # memoized decode of the merged stream: None = not computed,
    # False = annotated (arrays can't represent it), tuple = arrays
    _arrays_cache: "tuple | bool | None" = None

    def write(self, t_nanos: int, value: float, unit: Unit) -> None:
        self.times.append(t_nanos)
        self.values.append(value)
        self.units.append(int(unit))
        self.last_write_nanos = max(self.last_write_nanos, t_nanos)
        self.num_writes += 1
        self._stream_cache = None
        self._arrays_cache = None

    def merged_points(self):
        """(times, values, units) time-sorted, later-write-wins — the
        canonical point set, no codec round trip."""
        import numpy as np

        t = np.asarray(self.times, np.int64)
        order = np.argsort(t, kind="stable")
        ts = t[order]
        keep = np.empty(len(ts), bool)
        if len(ts):
            keep[:-1] = ts[1:] != ts[:-1]
            keep[-1] = True
        idx = order[keep]
        v = np.asarray(self.values, np.float64)[idx]
        u = np.asarray(self.units, np.int32)[idx]
        return t[idx], v, u

    def merged_stream(self) -> bytes:
        """Canonical m3tsz stream of the merged point set (the reference's
        bucket merge output) — native batch encoder, python fallback."""
        if self._stream_cache is not None:
            return self._stream_cache
        if not self.times:
            return b""
        t, v, u = self.merged_points()
        from .. import native

        stream = native.encode_one(t, v, u)
        if stream is None:  # no native lib: reference python encoder
            enc = Encoder(int(t[0]))
            for tt, vv, uu in zip(t, v, u):
                enc.encode(int(tt), float(vv), unit=Unit(int(uu)))
            stream = enc.stream()
        self._stream_cache = stream
        return stream

    def merged_arrays(self):
        """Decoded (times, values, units) arrays of the canonical merged
        stream, memoized until the next write — the buffered-data analog
        of the decoded-block cache (repeated reads of an unsealed block
        skip the re-decode, not just the re-encode). Decoding the STREAM
        (not the raw columns) keeps codec-roundtrip parity: the codec
        truncates timestamps to the time unit. Returns None for annotated
        streams (memoized as False so the probe isn't repeated — the
        caller's iterator fallback owns those)."""
        if self._arrays_cache is None:
            from ..codec.native_read import decode_stream_arrays

            arrs = decode_stream_arrays(self.merged_stream())
            self._arrays_cache = arrs if arrs is not None else False
        return self._arrays_cache or None


class SeriesBuffer:
    """dbSeries + dbBuffer: buckets keyed by block start."""

    def __init__(self, series_id: bytes, block_size_nanos: int) -> None:
        self.id = series_id
        self.block_size = block_size_nanos
        self.buckets: dict[int, BufferBucket] = {}

    def block_start(self, t_nanos: int) -> int:
        return (t_nanos // self.block_size) * self.block_size

    def write(self, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        bs = self.block_start(t_nanos)
        bucket = self.buckets.get(bs)
        if bucket is None:
            bucket = BufferBucket(block_start=bs)
            self.buckets[bs] = bucket
        bucket.write(t_nanos, value, unit)

    def read(self, start_nanos: int, end_nanos: int) -> list[Datapoint]:
        out: list[Datapoint] = []
        for bs in sorted(self.buckets):
            if bs + self.block_size <= start_nanos or bs >= end_nanos:
                continue
            stream = self.buckets[bs].merged_stream()
            for dp in decode(stream):
                if start_nanos <= dp.timestamp < end_nanos:
                    out.append(dp)
        return out

    def streams(self, start_nanos: int, end_nanos: int) -> list[bytes]:
        """Merged per-bucket encoded streams overlapping [start, end),
        oldest block first (dbBuffer.ReadEncoded, buffer.go:633)."""
        out = []
        for bs in sorted(self.buckets):
            if bs + self.block_size <= start_nanos or bs >= end_nanos:
                continue
            stream = self.buckets[bs].merged_stream()
            if stream:
                out.append(stream)
        return out

    def has_points(self, start_nanos: int, end_nanos: int) -> bool:
        """True when any buffered bucket overlapping [start, end) holds
        datapoints — the resident-scan router's buffer-overlay check: live
        buffer data overlays sealed blocks at read time, so a scan served
        purely from residency would miss it and must fall back."""
        for bs, bucket in self.buckets.items():
            if bs + self.block_size <= start_nanos or bs >= end_nanos:
                continue
            if bucket.times:
                return True
        return False

    def streams_before(self, flush_before_nanos: int) -> dict[int, bytes]:
        """Canonical merged streams for blocks entirely before the cutoff
        (WarmFlush input, shard.go:2146)."""
        return {
            bs: b.merged_stream()
            for bs, b in self.buckets.items()
            if bs + self.block_size <= flush_before_nanos
        }

    def evict_before(self, t_nanos: int) -> list[int]:
        """Drop buckets entirely before the cutoff; returns the removed
        block starts so the shard's buffered-block summary can decrement
        exactly what disappeared."""
        removed = [b for b in self.buckets if b + self.block_size <= t_nanos]
        for bs in removed:
            del self.buckets[bs]
        return removed

    def evict_block(self, block_start: int) -> bool:
        """Drop one bucket; True iff it existed (summary bookkeeping)."""
        return self.buckets.pop(block_start, None) is not None
