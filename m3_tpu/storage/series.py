"""Per-series in-memory buffer: block-windowed encoders with warm/cold writes.

Reference: /root/reference/src/dbnode/storage/series/ — dbSeries.Write
(series.go:289) routes datapoints into dbBuffer buckets per block window
(buffer.go:250); the warm/cold decision (:268-313) classifies writes inside
the buffer-past/buffer-future window as warm, everything else as cold
(out-of-order, flushed separately). Tick merges bucket encoders
(buffer.go:413-478).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..codec.m3tsz import Datapoint, Encoder, decode
from ..utils.xtime import Unit

NANOS = 1_000_000_000


@dataclass
class BufferBucket:
    """One encoder per (block window, warm/cold version) — buffer.go buckets."""

    block_start: int
    encoder: Encoder | None = None
    # raw out-of-order points kept aside until merge (cold writes land here)
    pending: list[tuple[int, float, Unit]] = field(default_factory=list)
    last_write_nanos: int = -1
    num_writes: int = 0

    def write(self, t_nanos: int, value: float, unit: Unit) -> None:
        if self.encoder is not None and t_nanos > self.last_write_nanos:
            self.encoder.encode(t_nanos, value, unit=unit)
        else:
            if self.encoder is None and t_nanos > self.last_write_nanos:
                self.encoder = Encoder(t_nanos)
                self.encoder.encode(t_nanos, value, unit=unit)
            else:
                self.pending.append((t_nanos, value, unit))
        self.last_write_nanos = max(self.last_write_nanos, t_nanos)
        self.num_writes += 1

    def merged_stream(self) -> bytes:
        """Merge in-order encoder + pending out-of-order points into one
        canonical stream (the reference's bucket merge, buffer.go:413-478)."""
        points: list[Datapoint] = []
        if self.encoder is not None:
            points.extend(decode(self.encoder.stream()))
        for t, v, u in self.pending:
            points.append(Datapoint(timestamp=t, value=v, unit=u))
        if not points:
            return b""
        # sort by time; later write wins on duplicate timestamps
        dedup: dict[int, Datapoint] = {}
        for dp in points:
            dedup[dp.timestamp] = dp
        enc = Encoder(min(dedup))
        for t in sorted(dedup):
            dp = dedup[t]
            enc.encode(dp.timestamp, dp.value, unit=dp.unit)
        return enc.stream()


class SeriesBuffer:
    """dbSeries + dbBuffer: buckets keyed by block start."""

    def __init__(self, series_id: bytes, block_size_nanos: int) -> None:
        self.id = series_id
        self.block_size = block_size_nanos
        self.buckets: dict[int, BufferBucket] = {}

    def block_start(self, t_nanos: int) -> int:
        return (t_nanos // self.block_size) * self.block_size

    def write(self, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        bs = self.block_start(t_nanos)
        bucket = self.buckets.get(bs)
        if bucket is None:
            bucket = BufferBucket(block_start=bs)
            self.buckets[bs] = bucket
        bucket.write(t_nanos, value, unit)

    def read(self, start_nanos: int, end_nanos: int) -> list[Datapoint]:
        out: list[Datapoint] = []
        for bs in sorted(self.buckets):
            if bs + self.block_size <= start_nanos or bs >= end_nanos:
                continue
            stream = self.buckets[bs].merged_stream()
            for dp in decode(stream):
                if start_nanos <= dp.timestamp < end_nanos:
                    out.append(dp)
        return out

    def streams(self, start_nanos: int, end_nanos: int) -> list[bytes]:
        """Merged per-bucket encoded streams overlapping [start, end),
        oldest block first (dbBuffer.ReadEncoded, buffer.go:633)."""
        out = []
        for bs in sorted(self.buckets):
            if bs + self.block_size <= start_nanos or bs >= end_nanos:
                continue
            stream = self.buckets[bs].merged_stream()
            if stream:
                out.append(stream)
        return out

    def streams_before(self, flush_before_nanos: int) -> dict[int, bytes]:
        """Canonical merged streams for blocks entirely before the cutoff
        (WarmFlush input, shard.go:2146)."""
        return {
            bs: b.merged_stream()
            for bs, b in self.buckets.items()
            if bs + self.block_size <= flush_before_nanos
        }

    def evict_before(self, t_nanos: int) -> None:
        for bs in [b for b in self.buckets if b + self.block_size <= t_nanos]:
            del self.buckets[bs]

    def evict_block(self, block_start: int) -> None:
        self.buckets.pop(block_start, None)
