"""Fileset persistence: immutable per-(shard, blockStart, volume) flushed files.

Reference: /root/reference/src/dbnode/persist/fs/ — file roles from fs.go:26-36
(`info`, `index`, `summaries`, `bloomfilter`, `data`, `digest`, `checkpoint`),
writer write.go, reader read.go, seeker seek.go:63-79 (bloom filter →
index-lookup binary search → data read), checkpoint-written-last as the atomic
commit marker (files.go:1428 reads it to decide completeness).

The on-disk format is ours (the framework defines its own filesets), but every
file role and the recovery semantics are preserved — plus one addition the
reference doesn't have: a `side` file carrying the per-chunk decoder-state
side table (ops/chunked.py) so flushed blocks device-decode without a host
prescan.
"""

from __future__ import annotations

import errno
import json
import os
import struct
import time
import zlib
from dataclasses import dataclass

import numpy as np

from ..utils.instrument import DEFAULT as METRICS
from .faults import DISK, DiskFullError, crash_point

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # ops.chunked pulls in jax; storage nodes import lazily
    from ..ops.chunked import ChunkedBatch

CHUNK_K = 32
SUMMARY_EVERY = 64  # index-entry sampling rate for the summaries file

# per-chunk snapshot record (see snapshot_stream); v2 adds the fast-chunk
# classification flags byte (device kernel specialization, ops/fused.py)
SIDE_DTYPE_V1 = np.dtype(
    [
        ("off", "<u4"),
        ("prev_time", "<u8"),
        ("prev_delta", "<u8"),
        ("prev_float_bits", "<u8"),
        ("prev_xor", "<u8"),
        ("int_val", "<u8"),
        ("time_unit", "<u1"),
        ("sig", "<u1"),
        ("mult", "<u1"),
        ("is_float", "<u1"),
    ]
)
SIDE_DTYPE = np.dtype(SIDE_DTYPE_V1.descr + [("flags", "<u1")])
# v3: the packed 10-word-per-chunk layout (ops/sideplane.py) — the SAME
# rows the resident pool's side planes hold, so admission stages without
# re-walking streams, and the record shrinks 45 -> 40 bytes. Falls back
# to the v2 struct for a whole fileset when any chunk's state overflows
# the packed ranges; readers accept v1/v2/v3.
SIDE_VERSION = 3
SIDE_REC_V3 = 40  # SIDE_WORDS * 4

SUFFIXES = ("info", "index", "summaries", "bloomfilter", "data", "side", "digest", "checkpoint")

#: subdirectory (next to ``data/``) where corrupt fileset volumes are
#: renamed aside for post-mortem inspection instead of deleted
QUARANTINE_DIR = "quarantine"


class CorruptFilesetError(RuntimeError):
    """A checkpoint-complete fileset failed digest verification — torn or
    bit-rotted on disk after commit. Carries the per-file evidence so the
    quarantine path can count ``storage_corruption_total{file,reason}``."""

    def __init__(self, fid: "FilesetID", problems: list[tuple[str, str]]) -> None:
        super().__init__(f"corrupt fileset {fid}: {problems}")
        self.fid = fid
        self.problems = problems  # [(file_role, reason)]


def _bloom_bits(n: int) -> int:
    return max(64, 1 << (n * 10).bit_length())


class BloomFilter:
    """Simple double-hash bloom filter (role of persist/fs/bloom)."""

    def __init__(self, m_bits: int, k: int = 7, bits: np.ndarray | None = None) -> None:
        self.m = m_bits
        self.k = k
        self.bits = bits if bits is not None else np.zeros(m_bits // 8, np.uint8)

    def _hashes(self, key: bytes):
        h1 = zlib.crc32(key)
        h2 = zlib.adler32(key) | 1
        for i in range(self.k):
            yield (h1 + i * h2) % self.m

    def add(self, key: bytes) -> None:
        for h in self._hashes(key):
            self.bits[h >> 3] |= 1 << (h & 7)

    def test(self, key: bytes) -> bool:
        return all(self.bits[h >> 3] & (1 << (h & 7)) for h in self._hashes(key))


@dataclass
class FilesetID:
    namespace: str
    shard: int
    block_start: int
    volume: int = 0


def _dir(base: str, fid: FilesetID) -> str:
    return os.path.join(base, "data", fid.namespace, str(fid.shard))


def _path(base: str, fid: FilesetID, suffix: str) -> str:
    return os.path.join(
        _dir(base, fid), f"fileset-{fid.block_start}-{fid.volume}-{suffix}.db"
    )


def write_fileset(
    base: str,
    fid: FilesetID,
    series: dict[bytes, bytes],
    block_size_nanos: int,
    chunk_k: int = CHUNK_K,
    side_rows: dict | None = None,
) -> None:
    """Write all fileset files, checkpoint LAST (write.go ordering).

    ``side_rows`` optionally maps sid -> packed uint32[n_chunks, 10]
    side rows ALREADY computed (the device encode path emits them at
    seal, ops/encode.side_rows_for) — those sids skip the host prescan
    entirely; absent sids prescan as before. The rows are bit-identical
    to the prescan's packing, so the persisted side file is the same
    bytes either way."""
    from .. import native

    os.makedirs(_dir(base, fid), exist_ok=True)
    ids = sorted(series)
    data_parts: list[bytes] = []
    index_entries: list[bytes] = []
    side_parts: list[bytes] = []
    bloom = BloomFilter(_bloom_bits(max(len(ids), 1)))
    offset = 0
    index_off = 0
    summaries: list[bytes] = []
    side_rows = {k: v for k, v in (side_rows or {}).items() if v is not None}
    need = [i for i, sid in enumerate(ids) if sid not in side_rows]
    all_snaps: list = [None] * len(ids)
    if need:
        if native.available():
            scanned = native.prescan_batch(
                [series[ids[i]] for i in need], k=chunk_k
            )
        else:
            from ..ops.chunked import snapshot_stream

            scanned = [snapshot_stream(series[ids[i]], chunk_k) for i in need]
        for i, snaps in zip(need, scanned):
            all_snaps[i] = snaps
    from ..ops.sideplane import pack_side_rows

    # side-file version for THIS fileset: v3 packed rows when every
    # chunk's state fits the packed ranges, else the v2 struct for the
    # whole file (records are fixed-width; the version is per file)
    side_version = SIDE_VERSION
    packed_all = [
        side_rows[sid]
        if sid in side_rows
        else pack_side_rows(all_snaps[i], fid.block_start)
        for i, sid in enumerate(ids)
    ]
    if any(p is None for p in packed_all):
        side_version = 2
        from ..ops.sideplane import unpack_side_rows

        for i, sid in enumerate(ids):
            if all_snaps[i] is None:
                # v2 needs snapshot dicts; the packed->dict unpack is
                # bit-exact for every row the packer accepted
                all_snaps[i] = unpack_side_rows(packed_all[i], fid.block_start)

    def _side_bytes(i: int) -> bytes:
        if side_version >= 3:
            return packed_all[i].astype("<u4").tobytes()
        snaps = all_snaps[i]
        side = np.zeros(len(snaps), SIDE_DTYPE)
        for j, p in enumerate(snaps):
            side[j] = (
                p["off"],
                p["prev_time"],
                p["prev_delta"],
                p["prev_float_bits"],
                p["prev_xor"],
                p["int_val"],
                p["time_unit"],
                p["sig"],
                p["mult"],
                int(p["is_float"]),
                # flags: bit 0 int-fast chunk, bit 1 float-fast chunk
                (1 if p.get("fast") else 0) | (2 if p.get("fast_float") else 0),
            )
        return side.tobytes()

    for i, sid in enumerate(ids):
        stream = series[sid]
        n_chunks = (
            len(packed_all[i]) if all_snaps[i] is None else len(all_snaps[i])
        )
        side_bytes = _side_bytes(i)
        index_entries.append(
            struct.pack("<IIQI", len(sid), len(stream), offset, n_chunks) + sid
        )
        data_parts.append(stream)
        side_parts.append(side_bytes)
        bloom.add(sid)
        offset += len(stream)
        if i % SUMMARY_EVERY == 0:
            # sampled summaries: (id, byte offset of this entry in the INDEX
            # file) — the seeker bisects these then scans <= SUMMARY_EVERY
            # index entries (persist/fs/seek.go:79 index-lookup search)
            summaries.append(struct.pack("<IQ", len(sid), index_off) + sid)
        index_off += len(index_entries[-1])

    files = {
        "info": json.dumps(
            {
                "blockStart": fid.block_start,
                "blockSize": block_size_nanos,
                "volume": fid.volume,
                "numSeries": len(ids),
                "chunkK": chunk_k,
                "bloomBits": bloom.m,
                "bloomK": bloom.k,
                "summariesIndexOffsets": True,
                "sideVersion": side_version,
            }
        ).encode(),
        "index": b"".join(index_entries),
        "summaries": b"".join(summaries),
        "bloomfilter": bloom.bits.tobytes(),
        "data": b"".join(data_parts),
        "side": b"".join(side_parts),
    }
    digests = {}
    try:
        for suffix, payload in files.items():
            DISK.write_durable(_path(base, fid, suffix), payload)
            digests[suffix] = zlib.adler32(payload)
            if suffix == "data":
                crash_point("fileset:data-written")
        digest_payload = json.dumps(digests).encode()
        DISK.write_durable(_path(base, fid, "digest"), digest_payload)
        crash_point("fileset:pre-checkpoint")
        # checkpoint carries the digest-of-digests and commits the fileset
        DISK.write_durable(
            _path(base, fid, "checkpoint"),
            struct.pack("<I", zlib.adler32(digest_payload)),
        )
    except OSError as exc:
        # the checkpoint never landed, so the partial set was invisible —
        # remove it so the retried flush starts clean; disk-full degrades
        # to the typed retryable rejection instead of a crash
        delete_fileset(base, fid)
        if isinstance(exc, DiskFullError):
            raise
        if exc.errno in (errno.ENOSPC, errno.EDQUOT):
            raise DiskFullError(f"disk full writing fileset {fid}") from exc
        raise


def fileset_complete(base: str, fid: FilesetID) -> bool:
    """files.go:1428 — a fileset exists iff its checkpoint is valid."""
    try:
        with open(_path(base, fid, "checkpoint"), "rb") as f:
            (want,) = struct.unpack("<I", f.read(4))
        with open(_path(base, fid, "digest"), "rb") as f:
            return zlib.adler32(f.read()) == want
    except (FileNotFoundError, struct.error):
        return False


def delete_fileset(base: str, fid: FilesetID) -> None:
    """Remove every file of a fileset, checkpoint FIRST so a crash mid-delete
    leaves an incomplete (ignored) fileset rather than a corrupt-looking one."""
    for suffix in ("checkpoint", "digest") + SUFFIXES[:-2]:
        try:
            os.remove(_path(base, fid, suffix))
        except FileNotFoundError:
            pass


# --- verify + quarantine (scrub plane) ---

_CORRUPTION_CHILDREN: dict = {}
_QUARANTINE_GAUGE = METRICS.gauge(
    "storage_quarantined_volumes",
    "fileset volumes quarantined since process start",
)
_quarantined_total = 0


def _count_corruption(file_role: str, reason: str) -> None:
    child = _CORRUPTION_CHILDREN.get((file_role, reason))
    if child is None:
        child = METRICS.counter(
            "storage_corruption_total",
            "corrupt fileset files detected by verify/scrub",
            labels={"file": file_role, "reason": reason},
        )
        _CORRUPTION_CHILDREN[(file_role, reason)] = child
    child.inc()


def _read_role(base: str, fid: FilesetID, suffix: str) -> bytes:
    path = _path(base, fid, suffix)
    with DISK.open(path, "rb") as f:
        return DISK.read(f, path)


def verify_fileset(base: str, fid: FilesetID) -> list[tuple[str, str]]:
    """Digest-verify every file of a fileset against its digest file and
    the digest file against its checkpoint. Returns [] when clean, else
    (file_role, reason) evidence pairs with reason in {"missing", "torn",
    "digest-mismatch"}. Reads are full sequential file reads — callers
    cache the verdict (reader LRU / scrub cursor), never per query."""
    try:
        cp = _read_role(base, fid, "checkpoint")
    except OSError:
        return [("checkpoint", "missing")]
    if len(cp) != 4:
        return [("checkpoint", "torn")]
    try:
        digest_payload = _read_role(base, fid, "digest")
    except OSError:
        return [("digest", "missing")]
    (want,) = struct.unpack("<I", cp)
    if zlib.adler32(digest_payload) != want:
        return [("digest", "digest-mismatch")]
    digests = json.loads(digest_payload.decode())
    problems: list[tuple[str, str]] = []
    for suffix in SUFFIXES[:-2]:
        try:
            payload = _read_role(base, fid, suffix)
        except OSError:
            problems.append((suffix, "missing"))
            continue
        if zlib.adler32(payload) != digests.get(suffix):
            problems.append((suffix, "digest-mismatch"))
    return problems


def fileset_bytes(base: str, fid: FilesetID) -> int:
    """Total on-disk bytes of a fileset (the scrubber's rate-limit unit)."""
    total = 0
    for suffix in SUFFIXES:
        try:
            total += os.path.getsize(_path(base, fid, suffix))
        except OSError:
            continue
    return total


def quarantine_fileset(
    base: str, fid: FilesetID, problems: list[tuple[str, str]] | None = None
) -> str:
    """Rename a corrupt fileset aside into ``base/quarantine/<ns>/<shard>/``,
    checkpoint FIRST — the instant it moves, the volume stops being
    'complete' to every lister, so a crash mid-quarantine leaves an
    incomplete (ignored) fileset, never a half-visible one. Counts
    ``storage_corruption_total{file,reason}`` per evidence pair and bumps
    the quarantine gauge. Returns the quarantine directory."""
    global _quarantined_total
    qdir = os.path.join(base, QUARANTINE_DIR, fid.namespace, str(fid.shard))
    os.makedirs(qdir, exist_ok=True)
    for suffix in ("checkpoint", "digest") + SUFFIXES[:-2]:
        src = _path(base, fid, suffix)
        try:
            os.replace(src, os.path.join(qdir, os.path.basename(src)))
        except FileNotFoundError:
            pass
    for file_role, reason in problems or [("checkpoint", "unknown")]:
        _count_corruption(file_role, reason)
    _quarantined_total += 1
    _QUARANTINE_GAUGE.set(_quarantined_total)
    return qdir


def list_quarantined(base: str, namespace: str, shard: int) -> list[str]:
    """File names currently sitting in one shard's quarantine directory."""
    d = os.path.join(base, QUARANTINE_DIR, namespace, str(shard))
    try:
        return sorted(os.listdir(d))
    except FileNotFoundError:
        return []


_M_QUARANTINE_PRUNED = METRICS.counter(
    "storage_quarantine_pruned_total",
    "quarantined fileset volumes removed by retention GC",
)


def prune_quarantine(
    base: str, retention_secs: float, now: float | None = None
) -> int:
    """Retention GC for ``base/quarantine/``: delete quarantined fileset
    volumes whose NEWEST file is older than ``retention_secs`` (mtime is
    stamped by the quarantine rename, so age = time since quarantine).
    Whole volumes prune atomically — a volume with any fresh file is kept
    intact so post-mortem evidence is never half-deleted. Decrements the
    quarantine gauge and counts
    ``storage_quarantine_pruned_total`` per volume. Returns the number of
    volumes pruned; ``retention_secs <= 0`` means keep forever."""
    global _quarantined_total
    if retention_secs <= 0:
        return 0
    # m3lint: disable=M3L004 -- quarantine age is judged against file mtimes, which are wall-clock stamps; monotonic time has no relation to st_mtime
    cutoff = (time.time() if now is None else now) - float(retention_secs)
    pruned = 0
    for dirpath, _dirnames, filenames in os.walk(
        os.path.join(base, QUARANTINE_DIR)
    ):
        volumes: dict[tuple[str, str], list[str]] = {}
        for name in filenames:
            parts = name.split("-")
            if len(parts) != 4 or parts[0] != "fileset":
                continue
            volumes.setdefault((parts[1], parts[2]), []).append(name)
        for _vol, names in sorted(volumes.items()):
            paths = [os.path.join(dirpath, n) for n in names]
            try:
                newest = max(os.path.getmtime(p) for p in paths)
            except OSError:
                continue  # pruned by a concurrent pass
            if newest > cutoff:
                continue
            for p in paths:
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass
            pruned += 1
    if pruned:
        _M_QUARANTINE_PRUNED.inc(pruned)
        _quarantined_total = max(0, _quarantined_total - pruned)
        _QUARANTINE_GAUGE.set(_quarantined_total)
    return pruned


def list_fileset_volumes(base: str, namespace: str, shard: int) -> list[FilesetID]:
    """ALL complete volumes (not just the winning one per block)."""
    d = os.path.join(base, "data", namespace, str(shard))
    out = []
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return []
    for name in names:
        if not name.endswith("-checkpoint.db"):
            continue
        _, bs, vol, _ = name.split("-")
        fid = FilesetID(namespace, shard, int(bs), int(vol))
        if fileset_complete(base, fid):
            out.append(fid)
    return sorted(out, key=lambda f: (f.block_start, f.volume))


def list_filesets(base: str, namespace: str, shard: int) -> list[FilesetID]:
    """Latest complete volume per block start (cold flush volumes win)."""
    best: dict[int, FilesetID] = {}
    for fid in list_fileset_volumes(base, namespace, shard):
        best[fid.block_start] = fid
    return sorted(best.values(), key=lambda f: f.block_start)


def read_index_ids(base: str, fid: FilesetID) -> list[bytes]:
    """Series IDs of a complete fileset, reading ONLY the index file (used by
    bootstrap to re-index flushed series without touching data/side files)."""
    if not fileset_complete(base, fid):
        raise FileNotFoundError(f"incomplete fileset {fid}")
    with open(_path(base, fid, "index"), "rb") as f:
        buf = f.read()
    out = []
    pos = 0
    while pos < len(buf):
        id_len, _, _, _ = struct.unpack_from("<IIQI", buf, pos)
        pos += 20
        out.append(buf[pos : pos + id_len])
        pos += id_len
    return out


# --- live-migration raw-file surface (shard handoff / warm residency) ---
#
# On shard handoff the source streams sealed filesets FILE-BY-FILE,
# byte-for-byte: the data file IS the compressed pages and the side file
# IS the packed side planes the receiver's resident pool admits, so no
# decode/re-encode happens on either side and the imported fileset is
# bit-identical to the source's (digest-verified). The checkpoint is
# NEVER streamed: the receiver commits it locally LAST, so a
# partially-fetched fileset stays invisible to list_filesets
# (fileset_complete gates on the checkpoint) and a resumed transfer picks
# up at the local partial file size — resumability, atomicity, and
# integrity all fall out of the persistence format's own commit protocol.

MIGRATION_SUFFIXES = SUFFIXES[:-1]  # everything but the checkpoint


def migration_manifest(base: str, namespace: str, shard: int) -> list[dict]:
    """Streamable fileset inventory for one shard: per complete fileset,
    the byte size of every file role a receiver must fetch. A fileset
    raced away by retention mid-listing is simply omitted (the receiver's
    fallback covers anything it misses)."""
    out = []
    for fid in list_filesets(base, namespace, shard):
        files: dict[str, int] = {}
        ok = True
        for suffix in MIGRATION_SUFFIXES:
            try:
                files[suffix] = os.path.getsize(_path(base, fid, suffix))
            except OSError:
                ok = False
                break
        if ok:
            out.append(
                {"blockStart": fid.block_start, "volume": fid.volume,
                 "files": files}
            )
    return out


def read_fileset_chunk(
    base: str, fid: FilesetID, suffix: str, offset: int, max_bytes: int
) -> tuple[bytes, bool]:
    """(payload, eof): one byte-range read of one fileset file role — the
    resumable unit of migration streaming. Raises FileNotFoundError when
    retention deleted the fileset mid-stream (the receiver falls back)."""
    if suffix not in MIGRATION_SUFFIXES:
        raise ValueError(f"not a streamable fileset file role: {suffix!r}")
    path = _path(base, fid, suffix)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        f.seek(int(offset))
        data = f.read(int(max_bytes))
        return data, f.tell() >= size


def migration_file_size(base: str, fid: FilesetID, suffix: str) -> int:
    """Local partial size of one file role being imported — the resume
    offset after a receiver restart or a retried transfer (0 = nothing
    fetched yet)."""
    try:
        return os.path.getsize(_path(base, fid, suffix))
    except OSError:
        return 0


def append_fileset_chunk(
    base: str, fid: FilesetID, suffix: str, offset: int, data: bytes
) -> None:
    """Append one fetched chunk. The offset must equal the local partial
    size (append-only resume); a mismatch means this importer lost a race
    with another and must re-sync from migration_file_size."""
    os.makedirs(_dir(base, fid), exist_ok=True)
    path = _path(base, fid, suffix)
    with DISK.open(path, "ab") as f:
        if f.tell() != int(offset):
            raise ValueError(
                f"resume offset {offset} != local size {f.tell()} for "
                f"{fid} {suffix}"
            )
        DISK.write(f, path, data)


def commit_imported_fileset(base: str, fid: FilesetID) -> None:
    """Commit a fully-fetched fileset: verify every imported file against
    the fetched digest, fsync them, then write the checkpoint LAST —
    exactly write_fileset's crash-ordering, so an imported fileset is
    indistinguishable from a locally flushed one. On digest mismatch the
    partial files are deleted (the retried import starts clean) and
    ValueError propagates so the caller counts the failure."""
    with open(_path(base, fid, "digest"), "rb") as f:
        digest_payload = f.read()
    digests = json.loads(digest_payload.decode())
    try:
        for suffix in MIGRATION_SUFFIXES[:-1]:  # digest itself verified by checkpoint
            with open(_path(base, fid, suffix), "rb") as f:
                payload = f.read()
            if zlib.adler32(payload) != digests.get(suffix):
                raise ValueError(
                    f"imported {suffix} digest mismatch for {fid}"
                )
    except (FileNotFoundError, ValueError):
        delete_fileset(base, fid)
        raise
    for suffix in MIGRATION_SUFFIXES:
        DISK.fsync_path(_path(base, fid, suffix))
    DISK.write_durable(
        _path(base, fid, "checkpoint"),
        struct.pack("<I", zlib.adler32(digest_payload)),
    )


class FilesetReader:
    """The mmap seeker (read.go + seek.go): id lookup via bloom filter →
    summaries binary search → bounded index scan → mmap'd data slice.

    Nothing beyond the info/bloom/summaries files is materialized up front:
    data, side, and index are memory-mapped and only the bytes a lookup
    touches are faulted in (the reference's seeker mmaps data + index the
    same way, seek.go:63). Full-index parses happen lazily and only for
    whole-fileset consumers (series_ids, shard streaming)."""

    def __init__(self, base: str, fid: FilesetID, verify: bool = True) -> None:
        if not fileset_complete(base, fid):
            raise FileNotFoundError(f"incomplete fileset {fid}")
        if verify:
            # verify-on-first-read: one full digest pass when the reader
            # materializes (readers are LRU-cached by the shard, so this
            # is per serving volume, never per query)
            problems = verify_fileset(base, fid)
            if problems:
                raise CorruptFilesetError(fid, problems)
        self.fid = fid
        self.info = json.loads(self._read(base, "info"))
        self.bloom = BloomFilter(
            self.info["bloomBits"],
            self.info["bloomK"],
            np.frombuffer(self._read(base, "bloomfilter"), np.uint8).copy(),
        )
        self._data = self._mmap(base, "data")
        self._side = self._mmap(base, "side")
        self._side_version = int(self.info.get("sideVersion", 1))
        self._side_dtype = (
            SIDE_DTYPE if self._side_version >= 2 else SIDE_DTYPE_V1
        )
        # per-chunk record size drives the side-cursor walk; v3 stores
        # packed 10-word rows, v1/v2 the struct dtype
        self._side_rec = (
            SIDE_REC_V3 if self._side_version >= 3
            else self._side_dtype.itemsize
        )
        self._index_mm = self._mmap(base, "index")
        self._entries: dict[bytes, tuple[int, int, int, int] | None] = {}
        self._side_bases: dict[int, int] = {0: 0}
        self._full_index: dict[bytes, tuple[int, int, int, int]] | None = None
        self.full_index_parses = 0  # observability: whole-index scans
        # summaries: sampled (sid, index offset) pairs, sorted by sid —
        # absent on pre-seek filesets (no summariesIndexOffsets marker)
        self._summary_ids: list[bytes] = []
        self._summary_offs: list[int] = []
        if self.info.get("summariesIndexOffsets"):
            buf = self._read(base, "summaries")
            pos = 0
            while pos < len(buf):
                id_len, index_off = struct.unpack_from("<IQ", buf, pos)
                pos += 12
                self._summary_ids.append(buf[pos : pos + id_len])
                pos += id_len
                self._summary_offs.append(index_off)

    def _read(self, base: str, suffix: str) -> bytes:
        path = _path(base, self.fid, suffix)
        with DISK.open(path, "rb") as f:
            return DISK.read(f, path)

    def _mmap(self, base: str, suffix: str):
        import mmap as _mmap_mod

        with DISK.open(_path(base, self.fid, suffix), "rb") as f:
            size = os.fstat(f.fileno()).st_size
            if size == 0:
                return memoryview(b"")
            return memoryview(
                _mmap_mod.mmap(f.fileno(), size, access=_mmap_mod.ACCESS_READ)
            )

    # --- index lookup ---

    def _parse_entry(self, pos: int) -> tuple[bytes, tuple[int, int, int, int], int]:
        """Index entry at byte ``pos`` → (sid, (data_off, length, side_off,
        n_chunks), next_pos). side_off comes from a side-cursor walk at full
        parse; for seek hits it is recomputed from the entry scan below."""
        id_len, length, offset, n_chunks = struct.unpack_from(
            "<IIQI", self._index_mm, pos
        )
        pos += 20
        sid = bytes(self._index_mm[pos : pos + id_len])
        return sid, (offset, length, 0, n_chunks), pos + id_len

    def _ensure_full_index(self) -> dict[bytes, tuple[int, int, int, int]]:
        if self._full_index is None:
            self.full_index_parses += 1
            out: dict[bytes, tuple[int, int, int, int]] = {}
            pos = 0
            side_off = 0
            n = len(self._index_mm)
            while pos < n:
                sid, (offset, length, _, n_chunks), pos = self._parse_entry(pos)
                out[sid] = (offset, length, side_off, n_chunks)
                side_off += n_chunks * self._side_rec
            self._full_index = out
        return self._full_index

    def _lookup(self, sid: bytes) -> tuple[int, int, int, int] | None:
        if self._full_index is not None:
            return self._full_index.get(sid)
        if sid in self._entries:
            return self._entries[sid]
        if not self._summary_ids:
            return self._ensure_full_index().get(sid)
        # bisect the sampled summaries for the scan start; side offsets are
        # not sampled, so walk entries accumulating n_chunks from the sample.
        # Side offsets accumulate from file start, so sample i's side base is
        # unknown — recover it by scanning from the previous sample with a
        # known base: samples are every SUMMARY_EVERY entries, so instead we
        # accumulate side_off from entry 0 of the sampled region by storing
        # the side cursor alongside each region's first scan (cached below).
        import bisect

        i = bisect.bisect_right(self._summary_ids, sid) - 1
        if i < 0:
            self._entries[sid] = None
            return None
        start = self._summary_offs[i]
        side_base = self._side_base(i)
        pos, side_off = start, side_base
        n = len(self._index_mm)
        count = 0
        found = None
        while pos < n and count < SUMMARY_EVERY:
            entry_sid, (offset, length, _, n_chunks), pos = self._parse_entry(pos)
            if entry_sid == sid:
                found = (offset, length, side_off, n_chunks)
                break
            if entry_sid > sid:
                break
            side_off += n_chunks * self._side_rec
            count += 1
        self._entries[sid] = found
        return found

    def _side_base(self, sample_i: int) -> int:
        """Side-file byte offset of sample ``sample_i``'s first entry,
        computed once per sample region by walking from the nearest earlier
        known sample (region walks are <= SUMMARY_EVERY entries each)."""
        bases = self._side_bases
        known = sample_i
        while known not in bases:
            known -= 1
        while known < sample_i:
            pos = self._summary_offs[known]
            stop = self._summary_offs[known + 1]
            side_off = bases[known]
            while pos < stop:
                _, (_, _, _, n_chunks), pos = self._parse_entry(pos)
                side_off += n_chunks * self._side_rec
            known += 1
            bases[known] = side_off
        return bases[sample_i]

    @property
    def index(self) -> dict[bytes, tuple[int, int, int, int]]:
        return self._ensure_full_index()

    @property
    def series_ids(self) -> list[bytes]:
        return list(self._ensure_full_index())

    def stream(self, sid: bytes) -> bytes | None:
        if not self.bloom.test(sid):
            return None
        entry = self._lookup(sid)
        if entry is None:
            return None
        offset, length, _, _ = entry
        return bytes(self._data[offset : offset + length])

    def side_table(self, sid: bytes) -> list[dict] | None:
        if not self.bloom.test(sid):
            return None
        entry = self._lookup(sid)
        if entry is None:
            return None
        offset, length, side_off, n_chunks = entry
        if self._side_version >= 3:
            from ..ops.sideplane import unpack_side_rows

            rows = np.frombuffer(
                self._side, "<u4", count=n_chunks * (SIDE_REC_V3 // 4),
                offset=side_off,
            ).reshape(n_chunks, SIDE_REC_V3 // 4)
            snaps = unpack_side_rows(rows, self.info["blockStart"])
            offs = [p["off"] for p in snaps] + [length * 8]
            for j, p in enumerate(snaps):
                p["span"] = int(offs[j + 1]) - int(p["off"])
                p["total_bits"] = length * 8
            return snaps
        raw = np.frombuffer(
            self._side, self._side_dtype, count=n_chunks, offset=side_off
        )
        snaps = []
        offs = list(raw["off"]) + [length * 8]
        for j in range(n_chunks):
            snaps.append(
                dict(
                    off=int(raw["off"][j]),
                    prev_time=int(raw["prev_time"][j]),
                    prev_delta=int(raw["prev_delta"][j]),
                    prev_float_bits=int(raw["prev_float_bits"][j]),
                    prev_xor=int(raw["prev_xor"][j]),
                    int_val=int(raw["int_val"][j]),
                    time_unit=int(raw["time_unit"][j]),
                    sig=int(raw["sig"][j]),
                    mult=int(raw["mult"][j]),
                    is_float=bool(raw["is_float"][j]),
                    fast=bool(raw["flags"][j] & 1)
                    if "flags" in raw.dtype.names
                    else False,
                    fast_float=bool(raw["flags"][j] & 2)
                    if "flags" in raw.dtype.names
                    else False,
                    span=int(offs[j + 1]) - int(raw["off"][j]),
                    total_bits=length * 8,
                )
            )
        return snaps

    def chunked_batch(self, sids: list[bytes] | None = None) -> "ChunkedBatch":
        """Assemble a device-decodable batch straight from the fileset —
        no CPU prescan (the side file already holds the snapshots)."""
        from ..ops.chunked import assemble_chunked

        sids = sids if sids is not None else self.series_ids
        streams = []
        snaps = []
        for sid in sids:
            st = self.stream(sid)
            streams.append(st or b"")
            snaps.append(self.side_table(sid) or [])
        return assemble_chunked(streams, snaps, self.info["chunkK"])
