"""Snapshot files: periodic capture of un-flushed series buffers.

Reference: /root/reference/src/dbnode/storage/shard.go:2335 (Snapshot) +
persist/fs/snapshot_metadata_{read,write}.go — snapshots bound commit-log
replay: once a snapshot of every buffer is durable, all earlier WAL segments
can be removed, and bootstrap = filesets + latest snapshot + WAL tail.

One snapshot file per (namespace, shard), atomically replaced
(utils/blob.py); records are (series_id, block_start, m3tsz stream). Only the
newest sequence is kept.
"""

from __future__ import annotations

import os
import re
import struct

from ..utils.blob import read_checked_blob, write_atomic_checked_blob
from .faults import crash_point

_MAGIC = 0x6D335350  # "m3SP" (v3: records the fileset volume at snapshot)
_REC = struct.Struct("<IqIi")  # id len, block_start, stream len, volume
_SNAP_RE = re.compile(r"^snapshot-(\d+)\.db$")


def _dir(base: str, ns: str, shard: int) -> str:
    return os.path.join(base, "snapshots", ns, str(shard))


def _list(base: str, ns: str, shard: int) -> list[tuple[int, str]]:
    d = _dir(base, ns, shard)
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _SNAP_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(d, n)))
    return sorted(out)


def write_snapshot(
    base: str, ns: str, shard: int, records: list[tuple[bytes, int, bytes, int]]
) -> int:
    """Write records [(series_id, block_start, stream, volume)]; ``volume``
    is the block's fileset volume when the snapshot was taken (-1 = none) —
    bootstrap orders snapshot data against filesets with it: a fileset whose
    volume has since advanced supersedes the record (any warm or cold flush
    bumps the volume), while an unchanged volume means the record is a
    cold-write overlay NEWER than the fileset. Returns the new sequence
    number. Older snapshots are removed after the new one commits."""
    existing = _list(base, ns, shard)
    seq = (existing[-1][0] + 1) if existing else 0
    parts = [struct.pack("<I", len(records))]
    for sid, bs, stream, volume in records:
        parts.append(_REC.pack(len(sid), bs, len(stream), volume))
        parts.append(sid)
        parts.append(stream)
    write_atomic_checked_blob(
        os.path.join(_dir(base, ns, shard), f"snapshot-{seq}.db"),
        _MAGIC,
        b"".join(parts),
    )
    # the new snapshot is durable; the superseded ones still exist — a
    # kill here must leave a readable newest snapshot (read_latest walks
    # newest-first, so the stale survivors are inert)
    crash_point("snapshot:pre-cleanup")
    for _, path in existing:
        os.remove(path)
    return seq


def remove_snapshots(base: str, ns: str, shard: int) -> int:
    """Delete all snapshot files for a shard (flush covered their records);
    returns how many files were removed. Reference: storage/cleanup.go removes
    snapshots once their data is in flushed filesets."""
    removed = 0
    for _, path in _list(base, ns, shard):
        try:
            os.remove(path)
            removed += 1
        except FileNotFoundError:
            pass
    return removed


def read_latest_snapshot(
    base: str, ns: str, shard: int
) -> list[tuple[bytes, int, bytes]] | None:
    """Records of the newest valid snapshot, or None. A corrupt newest file
    falls back to the next-newest (the atomic replace makes this rare)."""
    for _, path in reversed(_list(base, ns, shard)):
        body = read_checked_blob(path, _MAGIC)
        if body is None:
            continue
        (count,) = struct.unpack_from("<I", body, 0)
        pos = 4
        out = []
        ok = True
        for _ in range(count):
            if pos + _REC.size > len(body):
                ok = False
                break
            id_len, bs, s_len, volume = _REC.unpack_from(body, pos)
            pos += _REC.size
            sid = body[pos : pos + id_len]
            pos += id_len
            stream = body[pos : pos + s_len]
            pos += s_len
            if len(sid) != id_len or len(stream) != s_len:
                ok = False
                break
            out.append((sid, bs, stream, volume))
        if ok:
            return out
    return None
