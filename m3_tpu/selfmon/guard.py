"""Reserved self-monitoring namespace rule.

The self-scrape pipeline stores fleet telemetry under a RESERVED storage
namespace (``_m3tpu``). Two invariants keep the pipeline from feeding on
itself:

1. **Only the collector writes there.** Datapoint writes into a reserved
   namespace must come from a path that explicitly declares self-monitoring
   intent: the collector's sink runs inside :func:`selfmon_writer`, and the
   cluster write plane carries a ``selfmon`` marker on reserved-namespace
   RPCs (``net/client.RemoteNode`` injects it, ``net/server.NodeService``
   re-establishes the context around dispatch). Every OTHER ingest surface —
   Prometheus remote write, influx, graphite/carbon, the downsampler's
   rollup output, msg-bus ingest — reaches the bare ``storage.Database``
   write methods, where :func:`check_write` raises. An operator relabeling
   user metrics into ``_m3tpu`` gets a typed error, not silent pollution of
   the fleet's own telemetry.

2. **The collector never re-ingests its own write activity.** Write-path
   counters are labeled ``{ns=...}``; the snapshot conversion
   (``selfmon/convert.py``) skips children whose label values name a
   reserved namespace. The self-scrape's storage writes therefore never
   appear in the telemetry it stores — series growth stays bounded by the
   (m3lint-bounded) registry, with no feedback term.

The context is a thread-local depth counter, so nested sinks (a collector
writing through a local Database) compose. Replication paths — peer
bootstrap and repair — also run inside it: they MOVE telemetry a
sanctioned writer already admitted on the source replica, which is not a
new ingest decision.

The RULER (m3_tpu/ruler/) is the second sanctioned writer: recording
rules derive new series FROM stored telemetry and write them back through
the normal path, including into the reserved namespace (an error-rate
recorded over ``m3tpu_rpc_*`` belongs next to its inputs). It declares
intent with :func:`ruler_writer` — a distinct context so name-discipline
rules can tell the two writers apart (colon-form ``level:metric:op``
recorded names are legal ONLY from the ruler context; the collector's
conversion skips them — selfmon/convert.py), while :func:`check_write`
accepts both.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager, nullcontext

# the reserved namespace PREFIX: "_m3tpu" itself is the default namespace
# the collector writes; any "_m3tpu*" name is covered by the rule
RESERVED_NS = "_m3tpu"


class ReservedNamespaceError(ValueError):
    """A non-collector write targeted the reserved self-monitoring
    namespace (see module docstring: only tagged collector paths may)."""


_local = threading.local()


def is_reserved(namespace: str) -> bool:
    return str(namespace).startswith(RESERVED_NS)


def writer_active() -> bool:
    """Whether this thread is inside a selfmon writer context."""
    return getattr(_local, "depth", 0) > 0


@contextmanager
def selfmon_writer():
    """Declare self-monitoring write intent for the current thread —
    one of the two ways through :func:`check_write` for a reserved
    namespace (the other is the ruler's :func:`ruler_writer`)."""
    _local.depth = getattr(_local, "depth", 0) + 1
    try:
        yield
    finally:
        _local.depth -= 1


def ruler_writer_active() -> bool:
    """Whether this thread is inside a ruler writer context (recording
    rules writing derived series — the only context whose series may use
    colon-form recorded names)."""
    return getattr(_local, "ruler_depth", 0) > 0


@contextmanager
def ruler_writer():
    """Declare ruler (recording-rule) write intent for the current thread.

    Nests a :func:`selfmon_writer` so every existing seam keeps working —
    :func:`check_write` admits the write, and the cluster client's wire
    ``selfmon`` marker rides reserved-namespace RPCs as usual — while the
    extra thread-local flag lets name-discipline checks distinguish the
    ruler from the collector."""
    _local.ruler_depth = getattr(_local, "ruler_depth", 0) + 1
    try:
        with selfmon_writer():
            yield
    finally:
        _local.ruler_depth -= 1


def wire_writer(flag) -> object:
    """Server-side dispatch context: an RPC that carries the ``selfmon``
    marker re-establishes the writer context in the handler thread (the
    client's thread-local cannot cross the wire)."""
    return selfmon_writer() if flag else nullcontext()


def check_write(namespace: str) -> None:
    """Runtime assertion for the reserved-namespace rule; called by the
    ``storage.Database`` write paths on every write. Non-reserved
    namespaces cost one string prefix check."""
    if is_reserved(namespace) and not writer_active():
        raise ReservedNamespaceError(
            f"write into reserved self-monitoring namespace {namespace!r} "
            "from a non-collector path (wrap in selfmon.guard."
            "selfmon_writer() only if you ARE the self-scrape pipeline)"
        )
