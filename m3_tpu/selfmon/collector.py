"""Self-scrape collector: the fleet's telemetry through its own write path.

The M3 platform famously monitors itself; this is that loop for the
framework. A ``SelfMonCollector`` runs in every process that opts in
(dbnode / coordinator / aggregator service flags): on each tick it

1. snapshots the process registry (``Registry.collect()`` — the lock is
   held only for the dict copy, never across storage writes or sockets,
   so the new periodic thread cannot invert lock order with the write
   path it feeds);
2. on the coordinator, additionally PULLS peers over the universal
   ``metrics`` RPC op (``fmt="json"`` structured form) — placement-routed
   dbnodes and any statically configured peer (e.g. an aggregator's debug
   RPC port);
3. converts every family to tagged datapoints (selfmon/convert.py) and
4. writes them through the NORMAL ingest path via its sink — a
   ``DatabaseSink`` (local Database, or the placement-routed
   ``SessionDatabase`` → dbnode host queues), or a ``MsgSink`` (the
   aggregator's m3msg producer → coordinator ingest, riding the same bus
   as aggregated user metrics).

Everything lands under the reserved ``_m3tpu`` namespace (selfmon/guard),
so "what was resident-pool occupancy during yesterday's p99 spike" is one
PromQL query over ``m3tpu_resident_pool_bytes`` — served by the existing
query engine and ``/debug`` HTTP surface.
"""

from __future__ import annotations

import threading

from ..utils.instrument import DEFAULT as METRICS
from .convert import snapshot_to_datapoints
from .guard import RESERVED_NS, selfmon_writer

# tag value marking bus-ingested self telemetry (MsgSink): the coordinator's
# m3msg ingest strips it and routes the metric into the reserved namespace
SELFMON_MARKER = (b"__selfmon__", b"1")


class DatabaseSink:
    """Writes converted datapoints through a Database-surface object
    (``storage.Database`` or ``client.session_db.SessionDatabase``) into
    the reserved namespace — the normal batched tagged-write path, inside
    the selfmon writer context (guard invariant 1)."""

    def __init__(self, db, namespace: str = RESERVED_NS) -> None:
        self.db = db
        self.namespace = namespace

    def write(self, entries: list) -> int:
        """``entries``: (tags, time_nanos, value). Returns error count."""
        if not entries:
            return 0
        with selfmon_writer():
            errs = self.db.write_tagged_batch(
                self.namespace, [(tags, t, v, 1) for tags, t, v in entries]
            )
        return sum(1 for e in errs if e)


class MsgSink:
    """Publishes converted datapoints onto the m3msg bus as aggregated
    metrics (the aggregator's flush transport): each entry's tags gain the
    ``__selfmon__`` marker, and the coordinator's ingest routes marked
    metrics into the reserved namespace. Delivery is the bus's
    at-least-once contract (duplicate datapoint writes are storage
    upserts)."""

    def __init__(self, producer, num_shards: int, policy=None) -> None:
        from ..metrics.policy import StoragePolicy

        self.producer = producer
        self.num_shards = num_shards
        self.policy = policy or StoragePolicy.parse("10s:24h")

    def write(self, entries: list) -> int:
        from ..metrics.encoding import AggregatedMessage, encode_aggregated_batch
        from ..utils.hash import shard_for
        from ..utils.serialize import encode_tags

        by_shard: dict[int, list] = {}
        for tags, t, v in entries:
            mid = encode_tags(tuple(tags) + (SELFMON_MARKER,))
            by_shard.setdefault(shard_for(mid, self.num_shards), []).append(
                AggregatedMessage(mid, t, v, self.policy)
            )
        for shard, msgs in by_shard.items():
            self.producer.produce(shard, encode_aggregated_batch(msgs))
        return 0


class SelfMonCollector:
    """Periodic self-scrape loop (daemon thread; ``scrape_once`` is the
    testable seam). ``peers`` is an optional zero-arg callable returning
    ``{instance_id: node}`` of RPC stubs exposing ``metrics_snapshot()``
    — evaluated per tick so placement changes are picked up live."""

    def __init__(
        self,
        sink,
        interval: float = 10.0,
        instance: str = "",
        component: str = "",
        registry=None,
        peers=None,
        clock=None,
    ) -> None:
        import time as _time

        from ..utils.schedule import check_telemetry_interval

        self.sink = sink
        # sub-second scrape intervals are rejected loudly: the scraped
        # counters land in m3tsz second-unit storage, where sub-second
        # samples collapse and flatten every rate() over the telemetry
        self.interval = check_telemetry_interval(interval, "self-scrape")
        self.instance = instance
        self.component = component
        self.registry = registry if registry is not None else METRICS
        self.peers = peers
        self._clock = clock or _time.time_ns
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._m_scrapes = METRICS.counter(
            "selfmon_scrapes_total", "self-scrape ticks completed"
        )
        self._m_errors = METRICS.counter(
            "selfmon_scrape_errors_total",
            "peer pulls or sink writes that failed during a self-scrape "
            "(a persistently growing count means the fleet's own telemetry "
            "is going dark)",
        )
        self._m_datapoints = METRICS.counter(
            "selfmon_datapoints_total", "self-telemetry datapoints written"
        )
        self._m_truncated = METRICS.counter(
            "selfmon_truncated_total",
            "datapoints dropped loudly at conversion: the per-snapshot "
            "cardinality cap (convert.MAX_DATAPOINTS_PER_SNAPSHOT) or the "
            "colon-name guard (recorded-form families in a peer snapshot)",
        )
        self._m_missed = METRICS.counter(
            "selfmon_ticks_missed_total",
            "scheduled scrape ticks skipped because the loop fell a full "
            "interval behind (a stalled sink or long pause; the schedule "
            "skips forward instead of bursting to catch up)",
        )

    # -- one tick (the testable unit) --

    def scrape_once(self) -> tuple[int, int]:
        """Snapshot self (+ peers), convert, write. Returns
        (datapoints_written, errors). Never raises — the loop must outlive
        any one bad tick, and every failure is counted."""
        now = self._clock()
        errors = 0
        entries, truncated = snapshot_to_datapoints(
            self.registry.collect(), now,
            instance=self.instance, role=self.component,
        )
        if self.peers is not None:
            try:
                peer_map = dict(self.peers())
            except Exception:
                peer_map = {}
                errors += 1
            for pid, node in sorted(peer_map.items()):
                try:
                    snap = node.metrics_snapshot()
                except Exception:
                    # a down peer is expected fleet weather — counted, and
                    # visible as a gap in that instance's stored series
                    errors += 1
                    continue
                peer_entries, peer_trunc = snapshot_to_datapoints(
                    snap, now, instance=pid, role="peer"
                )
                entries.extend(peer_entries)
                truncated += peer_trunc
        try:
            sink_errors = self.sink.write(entries)
        except Exception:
            sink_errors = len(entries)
        errors += sink_errors
        # only datapoints the sink accepted count as written — during an
        # outage the pipeline must report going dark, not full throughput
        written = len(entries) - sink_errors
        self._m_scrapes.inc()
        self._m_datapoints.inc(written)
        if truncated:
            self._m_truncated.inc(truncated)
        if errors:
            self._m_errors.inc(errors)
        return written, errors

    # -- lifecycle --

    def start(self) -> "SelfMonCollector":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="selfmon-collector"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        # fixed-rate schedule with a deterministic per-instance phase
        # (utils/schedule.py): scrape work no longer drifts the period,
        # and a fleet of collectors (and ruler groups) spreads over the
        # interval instead of hitting the write path in lockstep
        from ..utils.schedule import FixedRateTicker

        ticker = FixedRateTicker(
            self.interval,
            phase_key=f"selfmon/{self.instance}/{self.component}",
            stop=self._stop,
        )
        while True:
            stopped, missed = ticker.wait_next()
            if stopped:
                return
            if missed:
                self._m_missed.inc(missed)
            self.scrape_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
