"""Self-monitoring pipeline: the fleet's own telemetry stored as
first-class series under the reserved ``_m3tpu`` namespace, queryable by
the existing PromQL engine (see collector.py for the full loop)."""

from .collector import DatabaseSink, MsgSink, SELFMON_MARKER, SelfMonCollector
from .convert import is_recorded_name, snapshot_to_datapoints
from .guard import (
    RESERVED_NS,
    ReservedNamespaceError,
    check_write,
    is_reserved,
    ruler_writer,
    ruler_writer_active,
    selfmon_writer,
    wire_writer,
    writer_active,
)

__all__ = [
    "DatabaseSink",
    "MsgSink",
    "SELFMON_MARKER",
    "SelfMonCollector",
    "is_recorded_name",
    "snapshot_to_datapoints",
    "RESERVED_NS",
    "ReservedNamespaceError",
    "check_write",
    "is_reserved",
    "ruler_writer",
    "ruler_writer_active",
    "selfmon_writer",
    "wire_writer",
    "writer_active",
]
