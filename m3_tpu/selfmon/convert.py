"""Registry snapshot → tagged datapoints: the self-scrape's codec.

Converts the structured output of ``utils.instrument.Registry.collect()``
into the ``(tags, time_nanos, value)`` entries the normal tagged-write
ingest path stores, so fleet telemetry becomes first-class series the
PromQL engine can query:

- a counter/gauge child becomes ONE series named after its family
  (``m3tpu_rpc_requests_total``), carrying the child's labels plus the
  scrape identity tags ``instance``/``role``;
- a histogram child becomes the standard Prometheus series triplet:
  ``<name>_bucket{le=...}`` per (cumulative) bucket, ``<name>_sum`` and
  ``<name>_count`` — so ``histogram_quantile(0.99,
  m3tpu_rpc_request_duration_seconds_bucket)`` works unmodified.

Feedback-loop guard: children whose label VALUES name a reserved
namespace (``ns="_m3tpu"`` write-path counters) are skipped — the
collector's own storage writes never re-enter the telemetry it stores
(selfmon/guard.py invariant 2).

Name-discipline guard: colon-form names (the Prometheus
``level:metric:operation`` recording-rule convention, see
:data:`RECORDED_NAME_RE`) may enter storage ONLY from the ruler's writer
context (selfmon/guard.ruler_writer) — they assert "this series was
derived by a configured recording rule". The registry's own families are
m3lint-enforced snake_case, so a colon family can only appear in a PEER
snapshot pulled over the wire; converting it would let a buggy or
malicious peer forge recorded series outside the ruler. Such families are
skipped and counted in the loud drop tally.
"""

from __future__ import annotations

import math
import re

from ..block.core import make_tags
from .guard import RESERVED_NS

# one scrape's series count is bounded by the registry (metric names and
# label keys are m3lint-audited literals), but a misbehaving peer snapshot
# must not be: cap datapoints per converted snapshot, loudly (the caller
# counts truncations — no silent caps).
MAX_DATAPOINTS_PER_SNAPSHOT = 50_000

# the Prometheus recording-rule naming convention: colon-separated
# snake_case segments, at least one colon (`level:metric:operation`).
# Shared by the ruler (which REQUIRES recorded names to match) and this
# module's skip-logic (which rejects them from any other ingest source);
# m3lint M3L005 enforces the same split statically.
RECORDED_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*(:[a-z_][a-z0-9_]*)+$")


def is_recorded_name(name: str) -> bool:
    """Whether ``name`` follows the recording-rule colon convention —
    legal only for series written from the ruler writer context."""
    return RECORDED_NAME_RE.match(name) is not None


def format_le(bound: float) -> str:
    """Bucket bound → ``le`` label value, matching the text exposition
    (``repr(float)``; ``+Inf`` for the overflow bucket) so stored series
    join against scraped ones."""
    return "+Inf" if math.isinf(bound) else repr(float(bound))


def snapshot_to_datapoints(
    snapshot: dict,
    time_nanos: int,
    instance: str = "",
    role: str = "",
    skip_reserved: bool = True,
    max_datapoints: int = MAX_DATAPOINTS_PER_SNAPSHOT,
) -> tuple[list, int]:
    """Convert one ``Registry.collect()`` snapshot (local or pulled over
    the universal ``metrics`` RPC op) into tagged datapoints.

    Returns ``(entries, truncated)`` where entries are
    ``(tags, time_nanos, value)`` and ``truncated`` counts datapoints
    dropped loudly — by the ``max_datapoints`` cap or by the colon-name
    guard (0 in any healthy scrape; registry families are snake_case by
    lint, so colon families only arrive in forged/buggy peer snapshots).
    """
    out: list = []
    truncated = 0
    ident = {"instance": str(instance), "role": str(role)}

    def emit(name: str, labels: dict, value: float) -> None:
        nonlocal truncated
        if len(out) >= max_datapoints:
            truncated += 1
            return
        out.append(
            (
                make_tags({**labels, **ident, "__name__": name}),
                time_nanos,
                float(value),
            )
        )

    for name, fam in snapshot.items():
        if ":" in name:
            # recorded-name guard: colon-form series come ONLY from the
            # ruler writer context, never from a scraped registry. The
            # drop tally counts what WOULD have been emitted (a histogram
            # child is its whole bucket/sum/count expansion, not 1)
            for child in fam.get("children", ()):
                if fam.get("kind") == "histogram":
                    truncated += len(child.get("buckets", ())) + 2
                else:
                    truncated += 1
            continue
        kind = fam.get("kind")
        for child in fam.get("children", ()):
            labels = {str(k): str(v) for k, v in child.get("labels", {}).items()}
            if skip_reserved and any(
                v.startswith(RESERVED_NS) for v in labels.values()
            ):
                continue
            if kind in ("counter", "gauge"):
                emit(name, labels, child["value"])
            elif kind == "histogram":
                for bound, cum in child.get("buckets", ()):
                    emit(
                        f"{name}_bucket",
                        {**labels, "le": format_le(bound)},
                        cum,
                    )
                emit(f"{name}_sum", labels, child["sum"])
                emit(f"{name}_count", labels, child["count"])
    return out, truncated
