"""Load generator: sustained synthetic write/query load against a node or
coordinator — single-process, or DISTRIBUTED as an m3nsch-role
coordinator + agents.

Reference: /root/reference/src/m3nsch/ — a gRPC coordinator splits the
workload across agent processes, each driving its own share; achieved
rates aggregate centrally. Here the same roles ride the framework's framed
RPC. Run:

single process:

    python -m m3_tpu.services.loadgen --node 127.0.0.1:9000 \
        --series 10000 --rate 5000 --duration 10

distributed (one agent per host, then a coordinator invocation):

    python -m m3_tpu.services.loadgen --listen 0          # x N agents
    python -m m3_tpu.services.loadgen --agents h1:p,h2:p,h3:p \
        --node 127.0.0.1:9000 --rate 600000 --duration 10

The coordinator splits rate + DISJOINT series ranges across agents,
polls them, and prints the aggregated stats line. Prints one JSON line of
achieved stats at the end.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

NANOS = 1_000_000_000


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-loadgen", description=__doc__)
    p.add_argument("--node", default="", help="dbnode RPC host:port")
    p.add_argument("--coordinator", default="", help="coordinator HTTP host:port")
    p.add_argument(
        "--aggregator", default="",
        help="aggregator rawtcp ingress host:port — sends TAGGED untimed "
        "gauges (tag-wire IDs) so downstream rollups stay indexable",
    )
    p.add_argument("--namespace", default="default")
    p.add_argument("--series", type=int, default=1000, help="unique series")
    p.add_argument("--rate", type=float, default=1000.0, help="target writes/sec")
    p.add_argument("--duration", type=float, default=10.0, help="seconds")
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--batch", type=int, default=100, help="writes per RPC batch")
    p.add_argument("--read-fraction", type=float, default=0.0,
                   help="fraction of ops that are reads of a random series")
    p.add_argument("--series-offset", type=int, default=0,
                   help="first series index (agents get disjoint ranges)")
    p.add_argument("--listen", type=int, default=None,
                   help="AGENT mode: serve the loadgen RPC on this port (0=auto)")
    p.add_argument("--agents", default="",
                   help="COORDINATOR mode: comma-separated agent host:port list")
    return p


class Stats:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.writes = 0
        self.reads = 0
        self.errors = 0

    def add(self, writes=0, reads=0, errors=0) -> None:
        with self.lock:
            self.writes += writes
            self.reads += reads
            self.errors += errors


def run(args, make_client) -> dict:
    stats = Stats()
    stop = time.monotonic() + args.duration
    per_worker_rate = args.rate / max(args.workers, 1)

    def worker(widx: int) -> None:
        client = make_client()
        rnd = widx * 2654435761 % args.series
        next_send = time.monotonic()
        while time.monotonic() < stop:
            batch = []
            now_nanos = time.time_ns()
            off = getattr(args, "series_offset", 0)
            for i in range(args.batch):
                sid = f"load.series.{off + (rnd + i) % args.series}".encode()
                batch.append((sid, now_nanos + i, float(i)))
            rnd = (rnd + args.batch) % args.series
            try:
                if args.read_fraction and (rnd % 100) < args.read_fraction * 100:
                    client.read(args.namespace, batch[0][0], 0, 2**62)
                    stats.add(reads=1)
                client.write_batch(args.namespace, batch)
                stats.add(writes=len(batch))
            except Exception:
                stats.add(errors=1)
            next_send += args.batch / per_worker_rate
            delay = next_send - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.duration + 30)
    elapsed = time.monotonic() - t0
    return {
        "writes": stats.writes,
        "reads": stats.reads,
        "errors": stats.errors,
        "elapsed_secs": round(elapsed, 3),
        "achieved_writes_per_sec": round(stats.writes / elapsed, 1),
        "target_writes_per_sec": args.rate,
        "series": args.series,
    }


def make_client_factory(args):
    """Target client factory from args, or None if no target given."""
    if args.aggregator:
        from ..aggregator.server import AggregatorClient
        from ..metrics.encoding import UnaggregatedMessage
        from ..metrics.types import MetricType, Untimed
        from ..rules.rules import encode_tags_id

        host, port = args.aggregator.rsplit(":", 1)

        def make_client():
            ac = AggregatorClient([(host, int(port))])

            class AggClient:
                def write_batch(self, ns, batch):
                    for sid, t, v in batch:
                        tags = ((b"__name__", b"load"), (b"series", sid))
                        ac.send(
                            UnaggregatedMessage(
                                Untimed(
                                    MetricType.GAUGE,
                                    encode_tags_id(tags),
                                    gauge_value=v,
                                ),
                                t,
                                timed=True,
                            )
                        )

                def read(self, ns, sid, start, end):
                    return []

            return AggClient()

    elif args.node:
        from ..net.client import RemoteNode

        host, port = args.node.rsplit(":", 1)

        def make_client():
            return RemoteNode(host, int(port))

    elif args.coordinator:
        import urllib.request

        base = f"http://{args.coordinator}"

        class HttpClient:
            def write_batch(self, ns, batch):
                for sid, t, v in batch:
                    body = json.dumps(
                        {
                            "tags": {"__name__": sid.decode()},
                            "timestamp": t / NANOS,
                            "value": v,
                        }
                    ).encode()
                    urllib.request.urlopen(
                        urllib.request.Request(
                            f"{base}/api/v1/json/write", data=body
                        ),
                        timeout=10,
                    )

            def read(self, ns, sid, start, end):
                return []

        def make_client():
            return HttpClient()

    else:
        return None
    return make_client


class LoadgenAgentService:
    """Agent side of the m3nsch split: lg_start launches a run with the
    coordinator-supplied workload slice; lg_poll reports progress/result."""

    def __init__(self) -> None:
        self._runs: dict[int, dict] = {}
        self._next = 0
        self._lock = threading.Lock()

    def handle(self, req: dict):
        op = req.get("op")
        if op == "health":
            return {"role": "loadgen-agent"}
        if op == "lg_start":
            ns = argparse.Namespace(**req["args"])
            make_client = make_client_factory(ns)
            if make_client is None:
                raise ValueError("agent: no target in args")
            with self._lock:
                token = self._next
                self._next += 1
                rec = self._runs[token] = {"done": False, "result": None}

            def _go():
                try:
                    rec["result"] = run(ns, make_client)
                except Exception as exc:
                    rec["result"] = {"error": f"{type(exc).__name__}: {exc}"}
                rec["done"] = True

            threading.Thread(target=_go, daemon=True).start()
            return token
        if op == "lg_poll":
            rec = self._runs.get(req["token"])
            if rec is None:
                raise KeyError(f"no run {req['token']}")
            return {"done": rec["done"], "result": rec["result"]}
        raise ValueError(f"unknown op {op!r}")


def _run_agent(args) -> int:
    import signal

    from ..net.server import RpcServer

    server = RpcServer(LoadgenAgentService(), port=args.listen, component="loadgen")

    def shutdown(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    print(f"LISTENING {server.host} {server.port}", flush=True)
    server.serve_forever()
    return 0


def _run_coordinator(args) -> int:
    """m3nsch coordinator: split rate + disjoint series ranges across
    agents, start them all, poll to completion, aggregate."""
    from ..net.client import RpcClient

    endpoints = [e.strip() for e in args.agents.split(",") if e.strip()]
    n = len(endpoints)
    clients = [RpcClient.connect(ep) for ep in endpoints]
    per_series = max(args.series // n, 1)
    tokens = []
    for i, c in enumerate(clients):
        sub = dict(
            vars(args),
            agents="",
            listen=None,
            rate=args.rate / n,
            series=per_series,
            series_offset=args.series_offset + i * per_series,
        )
        tokens.append(c._call("lg_start", args=sub))
    agg = {"writes": 0, "reads": 0, "errors": 0, "elapsed_secs": 0.0}
    per_agent = []
    deadline = time.monotonic() + args.duration + 60
    pending = set(range(n))
    poll_failures = [0] * n
    while pending and time.monotonic() < deadline:
        time.sleep(0.3)
        for i in sorted(pending):
            try:
                st = clients[i]._call("lg_poll", token=tokens[i])
                poll_failures[i] = 0
            except Exception as exc:
                # a busy agent can time out one poll; only give up after
                # several CONSECUTIVE failures (then count it and keep
                # aggregating the survivors instead of crashing)
                poll_failures[i] += 1
                if poll_failures[i] >= 5:
                    pending.discard(i)
                    per_agent.append({"error": f"agent unreachable: {exc}"})
                    agg["errors"] += 1
                continue
            if st["done"]:
                pending.discard(i)
                r = st["result"] or {}
                per_agent.append(r)
                if "error" in r:
                    agg["errors"] += 1
                    continue
                agg["writes"] += r["writes"]
                agg["reads"] += r["reads"]
                agg["errors"] += r["errors"]
                agg["elapsed_secs"] = max(agg["elapsed_secs"], r["elapsed_secs"])
    for c in clients:
        c.close()
    if pending:
        agg["errors"] += len(pending)
    elapsed = agg["elapsed_secs"] or 1.0
    out = {
        **agg,
        "achieved_writes_per_sec": round(agg["writes"] / elapsed, 1),
        "target_writes_per_sec": args.rate,
        "series": args.series,
        "agents": n,
        "per_agent_writes_per_sec": [
            r.get("achieved_writes_per_sec") for r in per_agent
        ],
    }
    print(json.dumps(out), flush=True)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.listen is not None:
        return _run_agent(args)
    if args.agents:
        return _run_coordinator(args)
    make_client = make_client_factory(args)
    if make_client is None:
        print("loadgen: need --node, --coordinator or --aggregator", file=sys.stderr)
        return 2
    print(json.dumps(run(args, make_client)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
