"""Load generator: sustained synthetic write/query load against a node or
coordinator.

Reference: /root/reference/src/m3nsch/ (+ m3comparator) — the load tier
drives configurable concurrent write workloads with unique series cardinality
and reports achieved rates. Run:

    python -m m3_tpu.services.loadgen --node 127.0.0.1:9000 \
        --series 10000 --rate 5000 --duration 10

or against a coordinator's JSON write API with --coordinator host:port.
Prints one JSON line of achieved stats at the end.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

NANOS = 1_000_000_000


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-loadgen", description=__doc__)
    p.add_argument("--node", default="", help="dbnode RPC host:port")
    p.add_argument("--coordinator", default="", help="coordinator HTTP host:port")
    p.add_argument(
        "--aggregator", default="",
        help="aggregator rawtcp ingress host:port — sends TAGGED untimed "
        "gauges (tag-wire IDs) so downstream rollups stay indexable",
    )
    p.add_argument("--namespace", default="default")
    p.add_argument("--series", type=int, default=1000, help="unique series")
    p.add_argument("--rate", type=float, default=1000.0, help="target writes/sec")
    p.add_argument("--duration", type=float, default=10.0, help="seconds")
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--batch", type=int, default=100, help="writes per RPC batch")
    p.add_argument("--read-fraction", type=float, default=0.0,
                   help="fraction of ops that are reads of a random series")
    return p


class Stats:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.writes = 0
        self.reads = 0
        self.errors = 0

    def add(self, writes=0, reads=0, errors=0) -> None:
        with self.lock:
            self.writes += writes
            self.reads += reads
            self.errors += errors


def run(args, make_client) -> dict:
    stats = Stats()
    stop = time.monotonic() + args.duration
    per_worker_rate = args.rate / max(args.workers, 1)

    def worker(widx: int) -> None:
        client = make_client()
        rnd = widx * 2654435761 % args.series
        next_send = time.monotonic()
        while time.monotonic() < stop:
            batch = []
            now_nanos = time.time_ns()
            for i in range(args.batch):
                sid = f"load.series.{(rnd + i) % args.series}".encode()
                batch.append((sid, now_nanos + i, float(i)))
            rnd = (rnd + args.batch) % args.series
            try:
                if args.read_fraction and (rnd % 100) < args.read_fraction * 100:
                    client.read(args.namespace, batch[0][0], 0, 2**62)
                    stats.add(reads=1)
                client.write_batch(args.namespace, batch)
                stats.add(writes=len(batch))
            except Exception:
                stats.add(errors=1)
            next_send += args.batch / per_worker_rate
            delay = next_send - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.duration + 30)
    elapsed = time.monotonic() - t0
    return {
        "writes": stats.writes,
        "reads": stats.reads,
        "errors": stats.errors,
        "elapsed_secs": round(elapsed, 3),
        "achieved_writes_per_sec": round(stats.writes / elapsed, 1),
        "target_writes_per_sec": args.rate,
        "series": args.series,
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.aggregator:
        from ..aggregator.server import AggregatorClient
        from ..metrics.encoding import UnaggregatedMessage
        from ..metrics.types import MetricType, Untimed
        from ..rules.rules import encode_tags_id

        host, port = args.aggregator.rsplit(":", 1)

        def make_client():
            ac = AggregatorClient([(host, int(port))])

            class AggClient:
                def write_batch(self, ns, batch):
                    for sid, t, v in batch:
                        tags = ((b"__name__", b"load"), (b"series", sid))
                        ac.send(
                            UnaggregatedMessage(
                                Untimed(
                                    MetricType.GAUGE,
                                    encode_tags_id(tags),
                                    gauge_value=v,
                                ),
                                t,
                                timed=True,
                            )
                        )

                def read(self, ns, sid, start, end):
                    return []

            return AggClient()

    elif args.node:
        from ..net.client import RemoteNode

        host, port = args.node.rsplit(":", 1)

        def make_client():
            return RemoteNode(host, int(port))

    elif args.coordinator:
        import urllib.request

        base = f"http://{args.coordinator}"

        class HttpClient:
            def write_batch(self, ns, batch):
                for sid, t, v in batch:
                    body = json.dumps(
                        {
                            "tags": {"__name__": sid.decode()},
                            "timestamp": t / NANOS,
                            "value": v,
                        }
                    ).encode()
                    urllib.request.urlopen(
                        urllib.request.Request(
                            f"{base}/api/v1/json/write", data=body
                        ),
                        timeout=10,
                    )

            def read(self, ns, sid, start, end):
                return []

        def make_client():
            return HttpClient()

    else:
        print("loadgen: need --node or --coordinator", file=sys.stderr)
        return 2
    print(json.dumps(run(args, make_client)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
