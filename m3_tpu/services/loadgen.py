"""Load generator: sustained synthetic write/query load against a node or
coordinator — single-process, or DISTRIBUTED as an m3nsch-role
coordinator + agents.

Reference: /root/reference/src/m3nsch/ — a gRPC coordinator splits the
workload across agent processes, each driving its own share; achieved
rates aggregate centrally. Here the same roles ride the framework's framed
RPC. Run:

single process:

    python -m m3_tpu.services.loadgen --node 127.0.0.1:9000 \
        --series 10000 --rate 5000 --duration 10

distributed (one agent per host, then a coordinator invocation):

    python -m m3_tpu.services.loadgen --listen 0          # x N agents
    python -m m3_tpu.services.loadgen --agents h1:p,h2:p,h3:p \
        --node 127.0.0.1:9000 --rate 600000 --duration 10

The coordinator splits rate + DISJOINT series ranges across agents,
polls them, and prints the aggregated stats line. Prints one JSON line of
achieved stats at the end.

MULTI-TENANT mode (``--tenants "alpha:3,beta:1"``): a mixed read+write
workload attributed per tenant (``M3-Tenant`` header on the coordinator
HTTP surface; the ``_tenant`` wire frame against a dbnode), driven
OPEN-LOOP at a fixed rate (utils/schedule.FixedRateTicker — ticks fire on
the absolute schedule whether or not the previous op finished, and ticks
the loop could not take are counted as ``missed_ticks`` instead of
silently stretching the period) so latency percentiles do not suffer
coordinated omission. One op = one write (a ``--batch``-sized batch
against a node, one sample against a coordinator) or one read
(``--read-fraction``); the JSON line reports sustained ops/sec plus
per-tenant p50/p95/p99 SERVICED-op latency (422s and errors are counted
apart, never mixed into the percentiles) and rejection counts:

    python -m m3_tpu.services.loadgen --coordinator 127.0.0.1:7201 \
        --tenants "alpha:3,beta:1" --rate 200 --read-fraction 0.3 \
        --series 100 --duration 10
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

NANOS = 1_000_000_000


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-loadgen", description=__doc__)
    p.add_argument("--node", default="", help="dbnode RPC host:port")
    p.add_argument("--coordinator", default="", help="coordinator HTTP host:port")
    p.add_argument(
        "--aggregator", default="",
        help="aggregator rawtcp ingress host:port — sends TAGGED untimed "
        "gauges (tag-wire IDs) so downstream rollups stay indexable",
    )
    p.add_argument("--namespace", default="default")
    p.add_argument("--series", type=int, default=1000, help="unique series")
    p.add_argument("--rate", type=float, default=1000.0, help="target writes/sec")
    p.add_argument("--duration", type=float, default=10.0, help="seconds")
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--batch", type=int, default=100, help="writes per RPC batch")
    p.add_argument("--read-fraction", type=float, default=0.0,
                   help="fraction of ops that are reads of a random series")
    p.add_argument("--series-offset", type=int, default=0,
                   help="first series index (agents get disjoint ranges)")
    p.add_argument("--listen", type=int, default=None,
                   help="AGENT mode: serve the loadgen RPC on this port (0=auto)")
    p.add_argument("--agents", default="",
                   help="COORDINATOR mode: comma-separated agent host:port list")
    p.add_argument(
        "--tenants", default="",
        help='MULTI-TENANT mode: "name:weight,..." mix (weight optional, '
        "default 1). Ops carry the tenant identity (M3-Tenant header / "
        "_tenant wire field); --rate becomes OPS/sec driven open-loop, "
        "and the stats line grows per-tenant p50/p95/p99",
    )
    return p


def parse_tenant_spec(spec: str) -> list[tuple[str, int]]:
    """``"alpha:3,beta"`` → [("alpha", 3), ("beta", 1)]."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        weight = int(w) if w else 1
        if weight < 1:
            raise ValueError(f"tenant weight must be >= 1: {part!r}")
        out.append((name, weight))
    if not out:
        raise ValueError(f"empty tenant spec {spec!r}")
    return out


class Stats:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.writes = 0
        self.reads = 0
        self.errors = 0

    def add(self, writes=0, reads=0, errors=0) -> None:
        with self.lock:
            self.writes += writes
            self.reads += reads
            self.errors += errors


def run(args, make_client) -> dict:
    stats = Stats()
    stop = time.monotonic() + args.duration
    per_worker_rate = args.rate / max(args.workers, 1)

    def worker(widx: int) -> None:
        client = make_client()
        rnd = widx * 2654435761 % args.series
        next_send = time.monotonic()
        while time.monotonic() < stop:
            batch = []
            now_nanos = time.time_ns()
            off = getattr(args, "series_offset", 0)
            for i in range(args.batch):
                sid = f"load.series.{off + (rnd + i) % args.series}".encode()
                batch.append((sid, now_nanos + i, float(i)))
            rnd = (rnd + args.batch) % args.series
            try:
                if args.read_fraction and (rnd % 100) < args.read_fraction * 100:
                    client.read(args.namespace, batch[0][0], 0, 2**62)
                    stats.add(reads=1)
                client.write_batch(args.namespace, batch)
                stats.add(writes=len(batch))
            except Exception:
                stats.add(errors=1)
            next_send += args.batch / per_worker_rate
            delay = next_send - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.duration + 30)
    elapsed = time.monotonic() - t0
    return {
        "writes": stats.writes,
        "reads": stats.reads,
        "errors": stats.errors,
        "elapsed_secs": round(elapsed, 3),
        "achieved_writes_per_sec": round(stats.writes / elapsed, 1),
        "target_writes_per_sec": args.rate,
        "series": args.series,
    }


def make_client_factory(args):
    """Target client factory from args, or None if no target given."""
    if args.aggregator:
        from ..aggregator.server import AggregatorClient
        from ..metrics.encoding import UnaggregatedMessage
        from ..metrics.types import MetricType, Untimed
        from ..rules.rules import encode_tags_id

        host, port = args.aggregator.rsplit(":", 1)

        def make_client():
            ac = AggregatorClient([(host, int(port))])

            class AggClient:
                def write_batch(self, ns, batch):
                    for sid, t, v in batch:
                        tags = ((b"__name__", b"load"), (b"series", sid))
                        ac.send(
                            UnaggregatedMessage(
                                Untimed(
                                    MetricType.GAUGE,
                                    encode_tags_id(tags),
                                    gauge_value=v,
                                ),
                                t,
                                timed=True,
                            )
                        )

                def read(self, ns, sid, start, end):
                    return []

            return AggClient()

    elif args.node:
        from ..net.client import RemoteNode

        host, port = args.node.rsplit(":", 1)

        def make_client():
            return RemoteNode(host, int(port))

    elif args.coordinator:
        import urllib.request

        base = f"http://{args.coordinator}"

        class HttpClient:
            def write_batch(self, ns, batch):
                for sid, t, v in batch:
                    body = json.dumps(
                        {
                            "tags": {"__name__": sid.decode()},
                            "timestamp": t / NANOS,
                            "value": v,
                        }
                    ).encode()
                    urllib.request.urlopen(
                        urllib.request.Request(
                            f"{base}/api/v1/json/write", data=body
                        ),
                        timeout=10,
                    )

            def read(self, ns, sid, start, end):
                return []

        def make_client():
            return HttpClient()

    else:
        return None
    return make_client


# --- multi-tenant open-loop mode ------------------------------------------


class Rejected(Exception):
    """The target refused the op on a cost limit (HTTP 422 /
    QueryLimitError over the wire) — counted apart from errors: a capped
    tenant being 422'd is the SYSTEM working, not the bench failing."""


class Shed(Exception):
    """The target load-shed the op (HTTP 503 / QueryShedError from the
    admission scheduler) — like Rejected, a typed outcome counted apart
    from hard errors: under deliberate overload, sheds landing on the
    over-limit tenant are the scheduler working as designed."""


def make_tenant_client_factory(args):
    """Tenant-attributed client factory: ops carry the tenant identity
    the way a real caller would (M3-Tenant header on the coordinator
    HTTP surface, the thread-local tenant context → ``_tenant`` wire
    frame against a dbnode)."""
    if args.coordinator:
        import urllib.error
        import urllib.request
        from urllib.parse import urlencode

        base = f"http://{args.coordinator}"

        class HttpTenantClient:
            def _open(self, req):
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        r.read()
                except urllib.error.HTTPError as exc:
                    exc.close()
                    if exc.code == 422:
                        raise Rejected(str(exc)) from exc
                    if exc.code == 503:
                        raise Shed(str(exc)) from exc
                    raise

            def write(self, tenant: str, series_idx: int) -> None:
                body = json.dumps(
                    {
                        "tags": {
                            "__name__": f"load_{tenant}_{series_idx}",
                            "tenant": tenant,
                        },
                        "timestamp": time.time(),
                        "value": float(series_idx),
                    }
                ).encode()
                self._open(
                    urllib.request.Request(
                        f"{base}/api/v1/json/write",
                        data=body,
                        headers={"M3-Tenant": tenant},
                    )
                )

            def read(self, tenant: str) -> None:
                # a range read over EVERYTHING the tenant wrote: the scan
                # that trips per-tenant datapoint limits when capped
                now = time.time()
                q = urlencode(
                    {
                        "query": f'{{__name__=~"load_{tenant}_.*"}}',
                        "start": now - 60,
                        "end": now,
                        "step": 5,
                    }
                )
                self._open(
                    urllib.request.Request(
                        f"{base}/api/v1/query_range?{q}",
                        headers={"M3-Tenant": tenant},
                    )
                )

        return HttpTenantClient

    if args.node:
        from ..net.client import RemoteError, RemoteNode
        from ..query.tenants import tenant_context

        host, port = args.node.rsplit(":", 1)
        ns = args.namespace
        batch_n = args.batch

        class NodeTenantClient:
            def __init__(self) -> None:
                self._node = RemoteNode(host, int(port))

            def write(self, tenant: str, series_idx: int) -> None:
                now_nanos = time.time_ns()
                batch = [
                    (
                        f"load.{tenant}.{(series_idx + i) % args.series}".encode(),
                        now_nanos + i,
                        float(i),
                    )
                    for i in range(batch_n)
                ]
                with tenant_context(tenant):
                    self._node.write_batch(ns, batch)

            def read(self, tenant: str) -> None:
                sid = f"load.{tenant}.0".encode()
                try:
                    with tenant_context(tenant):
                        self._node.read(ns, sid, 0, 2**62)
                except RemoteError as exc:
                    if exc.etype == "QueryLimitError":
                        raise Rejected(str(exc)) from exc
                    raise

        return NodeTenantClient

    return None


def _percentile_ms(lats: list[float], q: float) -> float:
    if not lats:
        return 0.0
    lats = sorted(lats)
    idx = min(int(q * len(lats)), len(lats) - 1)
    return round(lats[idx] * 1e3, 3)


class _TenantStats:
    __slots__ = ("writes", "reads", "errors", "rejected", "shed", "ok", "lats")
    # enough samples for a stable p99 at bench rates; past the cap new
    # latencies overwrite a rotating slot so the reservoir stays recent
    MAX_LATS = 200_000

    def __init__(self) -> None:
        self.writes = 0
        self.reads = 0
        self.errors = 0
        self.rejected = 0
        self.shed = 0
        self.ok = 0
        # SERVICED-op latencies only: a capped tenant's p99 must measure
        # what the system did for it, not the 422 fast-path round trip
        # (and a flapping backend's connect errors must not inflate a
        # healthy tenant's tail)
        self.lats: list[float] = []


def run_multitenant(args, client_cls) -> dict:
    """Open-loop fixed-rate mixed read+write load across the --tenants
    mix; returns the stats record (per-tenant latency percentiles +
    sustained ops/sec). ``--rate`` is OPS per second across all workers;
    a tick the loop could not take (previous op still running) is
    COUNTED in missed_ticks, never silently absorbed into the period —
    the open-loop discipline that keeps percentiles honest."""
    from ..utils.schedule import FixedRateTicker

    mix = parse_tenant_spec(args.tenants)
    # deterministic weighted rotation (no RNG: runs are reproducible and
    # agents need no seed plumbing)
    cycle = [name for name, w in mix for _ in range(w)]
    per_tenant = {name: _TenantStats() for name, _ in mix}
    lock = threading.Lock()
    stop_evt = threading.Event()
    workers = max(args.workers, 1)
    per_worker_rate = args.rate / workers
    if per_worker_rate <= 0:
        raise ValueError("--rate must be positive")
    missed_total = [0]
    read_pct = int(args.read_fraction * 100)

    def worker(widx: int) -> None:
        client = client_cls()
        ticker = FixedRateTicker(
            1.0 / per_worker_rate,
            phase_key=f"loadgen-{widx}",
            stop=stop_evt,
        )
        k = widx
        missed = 0
        while True:
            stopped, skipped = ticker.wait_next()
            missed += skipped
            if stopped:
                break
            tenant = cycle[k % len(cycle)]
            is_read = (k % 100) < read_pct
            k += workers
            t0 = time.perf_counter()
            outcome = "ok"
            try:
                if is_read:
                    client.read(tenant)
                else:
                    client.write(tenant, k % args.series)
            except Rejected:
                outcome = "rejected"
            except Shed:
                outcome = "shed"
            except Exception:
                outcome = "error"
            lat = time.perf_counter() - t0
            st = per_tenant[tenant]
            with lock:
                if outcome == "rejected":
                    st.rejected += 1
                elif outcome == "shed":
                    st.shed += 1
                elif outcome == "error":
                    st.errors += 1
                if is_read:
                    st.reads += 1
                else:
                    st.writes += 1
                if outcome == "ok":
                    st.ok += 1
                    if len(st.lats) < st.MAX_LATS:
                        st.lats.append(lat)
                    else:
                        st.lats[st.ok % st.MAX_LATS] = lat
        with lock:
            missed_total[0] += missed

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(workers)
    ]
    for t in threads:
        t.start()
    time.sleep(args.duration)
    stop_evt.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = max(time.monotonic() - t0, 1e-9)

    tenants_out = {}
    total_ops = total_errors = total_rejected = total_shed = 0
    for name, st in per_tenant.items():
        ops = st.writes + st.reads
        total_ops += ops
        total_errors += st.errors
        total_rejected += st.rejected
        total_shed += st.shed
        tenants_out[name] = {
            "ops": ops,
            "writes": st.writes,
            "reads": st.reads,
            "errors": st.errors,
            "rejected": st.rejected,
            "shed": st.shed,
            "ops_per_sec": round(ops / elapsed, 1),
            "p50_ms": _percentile_ms(st.lats, 0.50),
            "p95_ms": _percentile_ms(st.lats, 0.95),
            "p99_ms": _percentile_ms(st.lats, 0.99),
        }
    return {
        "mode": "multitenant",
        "elapsed_secs": round(elapsed, 3),
        "target_ops_per_sec": args.rate,
        "sustained_ops_per_sec": round(total_ops / elapsed, 1),
        "missed_ticks": missed_total[0],
        "tenants": tenants_out,
        # scalar keys the distributed coordinator's aggregation sums
        "writes": sum(s.writes for s in per_tenant.values()),
        "reads": sum(s.reads for s in per_tenant.values()),
        "errors": total_errors,
        "rejected": total_rejected,
        "shed": total_shed,
        "achieved_writes_per_sec": round(
            sum(s.writes for s in per_tenant.values()) / elapsed, 1
        ),
    }


class LoadgenAgentService:
    """Agent side of the m3nsch split: lg_start launches a run with the
    coordinator-supplied workload slice; lg_poll reports progress/result."""

    def __init__(self) -> None:
        self._runs: dict[int, dict] = {}
        self._next = 0
        self._lock = threading.Lock()

    def handle(self, req: dict):
        op = req.get("op")
        if op == "health":
            return {"role": "loadgen-agent"}
        if op == "lg_start":
            ns = argparse.Namespace(**req["args"])
            multitenant = bool(getattr(ns, "tenants", ""))
            make_client = (
                make_tenant_client_factory(ns) if multitenant
                else make_client_factory(ns)
            )
            if make_client is None:
                raise ValueError("agent: no target in args")
            with self._lock:
                token = self._next
                self._next += 1
                rec = self._runs[token] = {"done": False, "result": None}

            def _go():
                try:
                    rec["result"] = (
                        run_multitenant(ns, make_client) if multitenant
                        else run(ns, make_client)
                    )
                except Exception as exc:
                    rec["result"] = {"error": f"{type(exc).__name__}: {exc}"}
                rec["done"] = True

            threading.Thread(target=_go, daemon=True).start()
            return token
        if op == "lg_poll":
            rec = self._runs.get(req["token"])
            if rec is None:
                raise KeyError(f"no run {req['token']}")
            return {"done": rec["done"], "result": rec["result"]}
        raise ValueError(f"unknown op {op!r}")


def _run_agent(args) -> int:
    import signal

    from ..net.server import RpcServer

    server = RpcServer(LoadgenAgentService(), port=args.listen, component="loadgen")

    def shutdown(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    print(f"LISTENING {server.host} {server.port}", flush=True)
    server.serve_forever()
    return 0


def _run_coordinator(args) -> int:
    """m3nsch coordinator: split rate + disjoint series ranges across
    agents, start them all, poll to completion, aggregate."""
    from ..net.client import RpcClient

    endpoints = [e.strip() for e in args.agents.split(",") if e.strip()]
    n = len(endpoints)
    clients = [RpcClient.connect(ep) for ep in endpoints]
    per_series = max(args.series // n, 1)
    tokens = []
    for i, c in enumerate(clients):
        sub = dict(
            vars(args),
            agents="",
            listen=None,
            rate=args.rate / n,
            series=per_series,
            series_offset=args.series_offset + i * per_series,
        )
        tokens.append(c._call("lg_start", args=sub))
    agg = {"writes": 0, "reads": 0, "errors": 0, "elapsed_secs": 0.0}
    per_agent = []
    deadline = time.monotonic() + args.duration + 60
    pending = set(range(n))
    poll_failures = [0] * n
    while pending and time.monotonic() < deadline:
        time.sleep(0.3)
        for i in sorted(pending):
            try:
                st = clients[i]._call("lg_poll", token=tokens[i])
                poll_failures[i] = 0
            except Exception as exc:
                # a busy agent can time out one poll; only give up after
                # several CONSECUTIVE failures (then count it and keep
                # aggregating the survivors instead of crashing)
                poll_failures[i] += 1
                if poll_failures[i] >= 5:
                    pending.discard(i)
                    per_agent.append({"error": f"agent unreachable: {exc}"})
                    agg["errors"] += 1
                continue
            if st["done"]:
                pending.discard(i)
                r = st["result"] or {}
                per_agent.append(r)
                if "error" in r:
                    agg["errors"] += 1
                    continue
                agg["writes"] += r["writes"]
                agg["reads"] += r["reads"]
                agg["errors"] += r["errors"]
                agg["elapsed_secs"] = max(agg["elapsed_secs"], r["elapsed_secs"])
    for c in clients:
        c.close()
    if pending:
        agg["errors"] += len(pending)
    elapsed = agg["elapsed_secs"] or 1.0
    out = {
        **agg,
        "achieved_writes_per_sec": round(agg["writes"] / elapsed, 1),
        "target_writes_per_sec": args.rate,
        "series": args.series,
        "agents": n,
        "per_agent_writes_per_sec": [
            r.get("achieved_writes_per_sec") for r in per_agent
        ],
    }
    if args.tenants:
        out.update(merge_multitenant_results(per_agent, elapsed))
        out.update(target_ops_per_sec=args.rate, per_agent=per_agent)
    print(json.dumps(out), flush=True)
    return 0


def merge_multitenant_results(per_agent: list[dict], elapsed: float) -> dict:
    """Merge multitenant agent records into the coordinator's output
    line: per-tenant counts (ops/writes/reads/errors/rejected) SUM, and
    percentiles — which can't be re-derived from percentiles — take the
    WORST agent's value (conservative: a hidden slow agent must widen the
    headline p99, never vanish into an average); missed_ticks and
    rejected must survive aggregation or a heavily rejected tenant looks
    like a clean run."""
    merged: dict[str, dict] = {}
    missed = rejected = shed = total_ops = 0
    for r in per_agent:
        if "error" in r:
            continue
        missed += r.get("missed_ticks", 0)
        rejected += r.get("rejected", 0)
        shed += r.get("shed", 0)
        for name, t in (r.get("tenants") or {}).items():
            m = merged.setdefault(
                name,
                {
                    "ops": 0, "writes": 0, "reads": 0, "errors": 0,
                    "rejected": 0, "shed": 0,
                    "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                },
            )
            for k in ("ops", "writes", "reads", "errors", "rejected"):
                m[k] += t[k]
            m["shed"] += t.get("shed", 0)
            for k in ("p50_ms", "p95_ms", "p99_ms"):
                m[k] = max(m[k], t[k])
    for m in merged.values():
        m["ops_per_sec"] = round(m["ops"] / elapsed, 1)
        total_ops += m["ops"]
    return {
        "mode": "multitenant",
        "tenants": merged,
        "missed_ticks": missed,
        "rejected": rejected,
        "shed": shed,
        "sustained_ops_per_sec": round(total_ops / elapsed, 1),
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.listen is not None:
        return _run_agent(args)
    if args.agents:
        return _run_coordinator(args)
    if args.tenants:
        client_cls = make_tenant_client_factory(args)
        if client_cls is None:
            print("loadgen: --tenants needs --node or --coordinator",
                  file=sys.stderr)
            return 2
        print(json.dumps(run_multitenant(args, client_cls)), flush=True)
        return 0
    make_client = make_client_factory(args)
    if make_client is None:
        print("loadgen: need --node, --coordinator or --aggregator", file=sys.stderr)
        return 2
    print(json.dumps(run(args, make_client)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
