"""m3aggregator-equivalent service binary.

Reference: /root/reference/src/cmd/services/m3aggregator/main/main.go — the
aggregator process wires config → rawtcp ingest server → flush manager →
downstream handler. Run:

    python -m m3_tpu.services.aggregator --port 6000 \
        --forward 127.0.0.1:9000 --forward-namespace default

Flushed aggregates forward to a dbnode's RPC write_batch (suffixed IDs), or
count locally when no --forward is given. Prints ``LISTENING <host> <port>``
once serving.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from ..aggregator.aggregator import Aggregator
from ..aggregator.server import AggregatorIngestServer
from ..metrics.policy import StoragePolicy
from ..storage.series import NANOS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-aggregator", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-shards", type=int, default=16)
    p.add_argument("--policy", action="append", default=[], help="e.g. 10s:2d")
    p.add_argument("--flush-interval-secs", type=float, default=1.0)
    p.add_argument("--forward", default="", help="dbnode host:port for output")
    p.add_argument("--forward-namespace", default="default")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    forward_node = None
    if args.forward:
        from ..net.client import RemoteNode

        host, port = args.forward.rsplit(":", 1)
        forward_node = RemoteNode(host, int(port))

    flushed_count = [0]

    def handler(metrics):
        flushed_count[0] += len(metrics)
        if forward_node is not None:
            forward_node.write_batch(
                args.forward_namespace,
                [(m.suffixed_id, m.time_nanos, m.value) for m in metrics],
            )

    policies = tuple(StoragePolicy.parse(s) for s in args.policy) or ()
    agg = Aggregator(
        num_shards=args.num_shards,
        default_policies=policies,
        flush_handler=handler,
    )
    server = AggregatorIngestServer(agg, host=args.host, port=args.port)

    stop = threading.Event()
    flush_errors = [0]

    def flush_loop():
        while not stop.wait(args.flush_interval_secs):
            try:
                agg.flush(time.time_ns())
            except Exception as exc:
                # keep the loop alive (mediator-style resilience); drained
                # aggregates stay in agg._pending_emit and retry next pass
                flush_errors[0] += 1
                print(f"flush error ({flush_errors[0]}): {exc}", file=sys.stderr)

    flusher = threading.Thread(target=flush_loop, name="m3tpu-agg-flush", daemon=True)
    flusher.start()

    def shutdown(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    print(f"LISTENING {server.host} {server.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        stop.set()
        agg.flush(time.time_ns() + 10**12)  # drain on shutdown
        if forward_node is not None:
            forward_node.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
