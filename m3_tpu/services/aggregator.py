"""m3aggregator-equivalent service binary.

Reference: /root/reference/src/cmd/services/m3aggregator/main/main.go — the
aggregator process wires config → rawtcp ingest server → flush manager →
downstream handler. Run:

    python -m m3_tpu.services.aggregator --port 6000 \
        --forward 127.0.0.1:9000 --forward-namespace default

Flushed aggregates forward to a dbnode's RPC write_batch (suffixed IDs), or
count locally when no --forward is given. Prints ``LISTENING <host> <port>``
once serving.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from ..aggregator.aggregator import Aggregator
from ..aggregator.server import AggregatorIngestServer
from ..metrics.policy import StoragePolicy
from ..storage.series import NANOS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-aggregator", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-shards", type=int, default=16)
    p.add_argument("--policy", action="append", default=[], help="e.g. 10s:2d")
    p.add_argument("--flush-interval-secs", type=float, default=1.0)
    p.add_argument("--forward", default="", help="dbnode host:port for output")
    p.add_argument("--forward-namespace", default="default")
    p.add_argument(
        "--msg-consumer",
        default="",
        help="m3msg consumer endpoint host:port (the coordinator's "
        "--msg-listen): flushed aggregates ride the message bus with "
        "at-least-once acks instead of direct dbnode writes",
    )
    p.add_argument(
        "--msg-max-unacked",
        type=int,
        default=4096,
        help="m3msg backpressure watermark (0 = unbounded): when more "
        "than this many produced messages still await consumer acks, a "
        "flush first attempts one redelivery sweep and then PARKS the "
        "whole batch in the aggregator's pending queue for the next "
        "pass instead of growing the unacked queue without bound",
    )
    p.add_argument(
        "--kv-endpoint",
        default="",
        help="control-plane KV for replicated HA: leased leader election "
        "per --election-scope + shared flush times (followers keep warm "
        "state and take over without re-emitting windows)",
    )
    p.add_argument("--instance-id", default="agg0")
    p.add_argument("--election-scope", default="default")
    p.add_argument("--election-lease-secs", type=float, default=10.0)
    p.add_argument(
        "--selfmon-interval",
        type=float,
        default=0.0,
        help="self-scrape interval in seconds (0 disables): the "
        "aggregator's own metrics registry rides the m3msg bus to the "
        "coordinator (requires --msg-consumer) tagged __selfmon__, and "
        "lands in the coordinator's reserved _m3tpu namespace — the "
        "push-model twin of the coordinator's RPC pull (which can also "
        "scrape this process via --debug-port + --selfmon-peer)",
    )
    p.add_argument(
        "--debug-port",
        type=int,
        default=-1,
        help="serve health/metrics/profile RPC ops on this port (0 = "
        "ephemeral, -1 = disabled); prints DEBUG_LISTENING <host> <port> "
        "— the aggregator's Prometheus scrape + continuous-profiling "
        "surface (the ingest stream is one-way)",
    )
    p.add_argument(
        "--profile-hz",
        type=float,
        default=None,
        help="wall-clock stack-sampler rate (m3_tpu/profiling/), served "
        "on the debug port's `profile` op; default M3_TPU_PROFILE_HZ "
        "(19), 0 disables",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    forward_node = None
    producer = None
    if args.forward:
        from ..net.client import RemoteNode

        forward_node = RemoteNode.connect(args.forward)
    if args.msg_consumer:
        # aggregator flush → m3msg producer → coordinator ingest
        # (aggregator/handler/ + msg/producer; serve.go wiring)
        from ..metrics.encoding import AggregatedMessage, encode_aggregated_batch
        from ..msg.bus import ConsumerService, Producer, Topic
        from ..msg.transport import RemoteConsumer
        from ..utils.hash import shard_for

        host, port = args.msg_consumer.rsplit(":", 1)
        topic = Topic(
            "aggregated_metrics",
            num_shards=args.num_shards,
            consumer_services=[ConsumerService("coordinator")],
        )
        producer = Producer(topic)
        producer.register(
            RemoteConsumer("coordinator", "coordinator0", host, int(port))
        )

    flushed_count = [0]
    backpressure_parks = [0]

    def handler(metrics):
        if producer is not None and args.msg_max_unacked > 0:
            # backpressure BEFORE any produce, so the park is atomic for
            # the batch: Aggregator.flush re-queues it in _pending_emit
            # (or a follower mirror re-emits it) — nothing is half-sent
            if producer.num_unacked > args.msg_max_unacked:
                producer.retry_unacked()
                if producer.num_unacked > args.msg_max_unacked:
                    backpressure_parks[0] += 1
                    raise RuntimeError(
                        f"m3msg backpressure: {producer.num_unacked} "
                        f"unacked > --msg-max-unacked={args.msg_max_unacked}"
                    )
        flushed_count[0] += len(metrics)
        if producer is not None:
            by_shard: dict[int, list] = {}
            for m in metrics:
                by_shard.setdefault(shard_for(m.id, args.num_shards), []).append(
                    AggregatedMessage(
                        m.id, m.time_nanos, m.value, m.policy, m.agg_type
                    )
                )
            for shard, msgs in by_shard.items():
                producer.produce(shard, encode_aggregated_batch(msgs))
        if forward_node is not None:
            forward_node.write_batch(
                args.forward_namespace,
                [(m.suffixed_id, m.time_nanos, m.value) for m in metrics],
            )

    # replicated HA over the networked control plane (election_mgr.go +
    # follower_flush_mgr.go): leased election decides the emitter; shared
    # flush times let a takeover resume exactly where the leader stopped
    election = flush_times = None
    kv = None
    if args.kv_endpoint:
        from ..aggregator.election import ElectionManager, FlushTimesStore
        from ..cluster.kv_service import RemoteKVStore

        kv = RemoteKVStore.connect(args.kv_endpoint)
        election = ElectionManager(
            kv, args.election_scope, args.instance_id,
            lease_secs=args.election_lease_secs,
        )
        flush_times = FlushTimesStore(kv, scope=args.election_scope)

    policies = tuple(StoragePolicy.parse(s) for s in args.policy) or ()
    agg = Aggregator(
        num_shards=args.num_shards,
        default_policies=policies,
        flush_handler=handler,
        election=election,
        flush_times=flush_times,
    )
    server = AggregatorIngestServer(agg, host=args.host, port=args.port)

    debug_server = None
    if args.debug_port >= 0:
        from ..net.server import DebugService, RpcServer

        debug_server = RpcServer(
            DebugService({"role": "aggregator", "instance": args.instance_id}),
            host=args.host,
            port=args.debug_port,
            component="aggregator",
        )
        debug_server.start()

    selfmon = None
    if args.selfmon_interval > 0:
        if producer is None:
            print(
                "WARN --selfmon-interval needs --msg-consumer (no bus to "
                "push telemetry on); self-scrape disabled",
                file=sys.stderr,
            )
        else:
            from ..selfmon import MsgSink, SelfMonCollector

            selfmon = SelfMonCollector(
                MsgSink(producer, args.num_shards),
                interval=args.selfmon_interval,
                instance=args.instance_id,
                component="aggregator",
            ).start()

    # always-on continuous profiler: the aggregator has no storage, so
    # the device-memory accountant only tracks live jax buffers
    from ..profiling import start_sampler

    profiler = start_sampler(hz=args.profile_hz, instance=args.instance_id)

    stop = threading.Event()
    flush_errors = [0]

    def flush_loop():
        while not stop.wait(args.flush_interval_secs):
            try:
                agg.flush(time.time_ns())
                if producer is not None:
                    producer.retry_unacked()  # at-least-once redelivery sweep
            except Exception as exc:
                # keep the loop alive (mediator-style resilience); drained
                # aggregates stay in agg._pending_emit and retry next pass
                flush_errors[0] += 1
                print(f"flush error ({flush_errors[0]}): {exc}", file=sys.stderr)

    flusher = threading.Thread(target=flush_loop, name="m3tpu-agg-flush", daemon=True)
    flusher.start()

    def shutdown(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    print(f"LISTENING {server.host} {server.port}", flush=True)
    if debug_server is not None:
        print(f"DEBUG_LISTENING {debug_server.host} {debug_server.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        stop.set()
        if profiler is not None:
            profiler.stop()
        if selfmon is not None:
            selfmon.stop()
        agg.flush(time.time_ns() + 10**12)  # drain on shutdown
        if producer is not None:
            producer.retry_unacked()
        if forward_node is not None:
            forward_node.close()
        if debug_server is not None:
            debug_server.stop()
        if kv is not None:
            kv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
