"""Control-plane KV server binary (the framework's etcd).

Reference: /root/reference/src/cluster/kv/etcd/ + the embedded etcd a
dbnode seed node runs (src/dbnode/server/server.go:266-324). Run:

standalone (single node, durable via JSON backing):

    python -m m3_tpu.services.kvnode --port 2379 [--backing /path/state.json]

replicated (raft-lite quorum — survives any minority, leader included):

    python -m m3_tpu.services.kvnode --node-id kv0 --raft --data-dir /d0
    ... (one per replica; then configure each with the full member map via
    the raft_configure RPC, or pass --members kv0=h:p,kv1=h:p,kv2=h:p)

Prints ``LISTENING <host> <port>`` once serving. A raft node with
``--data-dir`` persists its log + snapshots and rejoins on restart.
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..cluster.kv import KVStore
from ..cluster.kv_service import KVServer
from ..cluster.raft import RaftKVService, RaftNode
from ..net.server import RpcServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="m3tpu-kvnode", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--backing", default=None, help="JSON file for durability (standalone)")
    p.add_argument("--raft", action="store_true", help="replicated mode")
    p.add_argument("--node-id", default="kv0")
    p.add_argument("--data-dir", default=None, help="raft log/snapshot dir")
    p.add_argument(
        "--members", default=None,
        help="full member map id=host:port,... (else send raft_configure)",
    )
    p.add_argument("--heartbeat-interval", type=float, default=0.1)
    p.add_argument("--election-timeout-lo", type=float, default=0.4)
    p.add_argument("--election-timeout-hi", type=float, default=0.8)
    args = p.parse_args(argv)

    if args.raft:
        node = RaftNode(
            args.node_id,
            KVStore(),
            data_dir=args.data_dir,
            heartbeat_interval=args.heartbeat_interval,
            election_timeout=(args.election_timeout_lo, args.election_timeout_hi),
        )
        server = RpcServer(
            RaftKVService(node), host=args.host, port=args.port, component="kv"
        )
        self_ep = f"{server.host}:{server.port}"
        if args.members:
            members = dict(kv.split("=", 1) for kv in args.members.split(","))
            # the address we actually bound wins over any configured one
            node.configure(members, self_endpoint=self_ep)
        elif node.members:  # recovered membership from a previous run
            node.configure(node.members, self_endpoint=self_ep)
    else:
        server = KVServer(KVStore(backing_path=args.backing), host=args.host, port=args.port)

    def shutdown(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    print(f"LISTENING {server.host} {server.port}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
