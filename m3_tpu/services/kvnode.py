"""Control-plane KV server binary (the framework's etcd).

Reference: /root/reference/src/cluster/kv/etcd/ + the embedded etcd a
dbnode seed node runs (src/dbnode/server/server.go:266-324). Run:

    python -m m3_tpu.services.kvnode --port 2379 [--backing /path/state.json]

Prints ``LISTENING <host> <port>`` once serving. With ``--backing`` the
store is durable across restarts (etcd persistence role).
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..cluster.kv import KVStore
from ..cluster.kv_service import KVServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="m3tpu-kvnode", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--backing", default=None, help="JSON file for durability")
    args = p.parse_args(argv)

    server = KVServer(KVStore(backing_path=args.backing), host=args.host, port=args.port)

    def shutdown(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    print(f"LISTENING {server.host} {server.port}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
