"""Coordinator service: HTTP API front end over storage + engine + downsampler.

Reference: /root/reference/src/query/server/query.go:177 (Run: storage,
downsampler, engine, HTTP router) and src/query/api/v1/handler/ — Prometheus
remote write (prometheus/remote/write.go:257, snappy+protobuf), remote read,
PromQL native range/instant (native/read.go:120), label endpoints
(native/complete_tags.go), admin namespace/placement/topic handlers, health.

Served with the stdlib threading HTTP server — the process seam where the
reference uses its router; handlers match the reference's routes.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..aggregator.downsampler import Downsampler
from ..block.core import make_tags
from ..cluster.kv import KVStore
from ..cluster.placement import PlacementService
from ..gen import prompb_pb2 as prompb
from ..metrics.types import MetricType
from ..msg.bus import ConsumerService, Topic, TopicService
from ..query.engine import Engine, Result
from ..query.m3_storage import M3Storage
from ..query.promql import Matcher
from ..storage.database import Database, NamespaceOptions
from ..utils.snappy import compress, decompress


NANOS = 1_000_000_000
MS = 1_000_000


class Coordinator:
    """The single-process coordinator: DB + engine + optional downsampler."""

    def __init__(
        self,
        db: Database | None = None,
        namespace: str = "default",
        downsampler: Downsampler | None = None,
        kv: KVStore | None = None,
        base_dir: str | None = None,
        query_limits=None,
        tenant_limits=None,
        scheduler=None,
    ) -> None:
        import tempfile

        if db is None:
            db = Database(base_dir or tempfile.mkdtemp(prefix="m3tpu-"), num_shards=4)
            db.create_namespace(namespace, NamespaceOptions())
        self.db = db
        self.namespace = namespace
        global_enforcer = None
        if query_limits is not None:
            from ..query.cost import GlobalEnforcer, QueryLimits

            # global ceiling defaults to 10x the per-query scope (x/cost)
            global_enforcer = GlobalEnforcer(
                QueryLimits(
                    max_series=query_limits.max_series * 10,
                    max_datapoints=query_limits.max_datapoints * 10,
                )
            )
        tenant_enforcers = None
        if tenant_limits is not None:
            # the per-tenant middle scope of the enforcer chain
            # (query → tenant → global): tenant_limits is a
            # tenants.TenantLimitSet (load_tenant_limits file format)
            from ..query.tenants import TenantEnforcers

            tenant_enforcers = TenantEnforcers.from_limit_set(
                tenant_limits, global_enforcer=global_enforcer
            )
        self.engine = Engine(
            M3Storage(db, namespace),
            limits=query_limits,
            global_enforcer=global_enforcer,
            tenant_enforcers=tenant_enforcers,
            scheduler=scheduler,
        )
        self.downsampler = downsampler
        self.kv = kv or KVStore()
        self.placement_svc = PlacementService(self.kv)
        self.topic_svc = TopicService(self.kv)
        # per-namespace engine cache (the `namespace` query param routes
        # PromQL to other namespaces — notably the reserved `_m3tpu`
        # self-monitoring namespace); engines share the cost limiters
        self._engines: dict[str, Engine] = {namespace: self.engine}
        self._engines_lock = threading.Lock()
        self.selfmon = None  # SelfMonCollector when start_selfmon() ran
        self.ruler = None  # ruler.Ruler when start_ruler() ran
        self.slo = None  # slo.SLOEngine when start_slo() ran
        self._ruler_groups = []  # file-sourced groups (start_ruler keeps
        # them so start_slo can re-publish file + generated together)
        self._selfmon_ns_ready = False
        # fleet-profile peer source (m3_tpu/profiling/): a zero-arg
        # callable yielding {instance_id: node} of `profile`-op-capable
        # stubs — main() wires the placement + static peers in; None
        # means /debug/pprof/fleet serves only this process
        self.peer_source = None
        self.instance_id = "coordinator0"

    def engine_for(self, namespace: str | None) -> Engine:
        if not namespace or namespace == self.namespace:
            return self.engine
        with self._engines_lock:
            eng = self._engines.get(namespace)
            if eng is not None:
                return eng
            eng = Engine(
                M3Storage(self.db, namespace),
                limits=self.engine.limits,
                global_enforcer=self.engine.global_enforcer,
                tenant_enforcers=self.engine.tenant_enforcers,
                # ONE admission scheduler across namespaces: the slots
                # bound the process, not each namespace separately
                scheduler=self.engine.scheduler,
            )
            # cache only namespaces the store actually knows: the param
            # comes off an unauthenticated HTTP query string, and caching
            # arbitrary strings would grow this dict without bound (an
            # unknown namespace still gets a transient engine — its query
            # fails with the store's own error, uncached)
            if namespace in self.db.namespaces:
                self._engines[namespace] = eng
            return eng

    # --- self-monitoring (m3_tpu/selfmon/) ---

    def start_selfmon(
        self, interval: float, peers=None, instance: str = "coordinator0"
    ):
        """Start the self-scrape collector: this process's registry (plus
        ``peers``: a zero-arg callable yielding {id: RemoteNode}) stored
        as series under the reserved namespace through the normal ingest
        path — queryable right back through this coordinator's PromQL
        surface with ``namespace=_m3tpu``."""
        from ..selfmon import RESERVED_NS, DatabaseSink, SelfMonCollector

        self._ensure_selfmon_namespace()
        self.selfmon = SelfMonCollector(
            DatabaseSink(self.db, RESERVED_NS),
            interval=interval,
            instance=instance,
            component="coordinator",
            peers=peers,
        )
        self.selfmon.start()
        return self.selfmon

    # --- ruler (m3_tpu/ruler/): recording + alerting over stored series ---

    def start_ruler(
        self,
        rules_path: str | None = None,
        webhooks=(),
        instance: str = "coordinator0",
        jitter: bool = True,
        default_rules: bool = True,
    ):
        """Start the rule engine: groups from ``rules_path`` (YAML/JSON)
        are validated, mirrored into the shared KV ruleset (all
        coordinators converge on one version; alert state checkpoints
        survive failover), and evaluated per group through the same
        per-namespace engine cache the HTTP query surface uses — so
        ``namespace: _m3tpu`` rules watch the fleet's own stored
        telemetry. ``webhooks``: notifier URLs (each gets the resilience
        plane's retry policy); a log notifier is always attached.

        ``default_rules`` merges in the built-in groups
        (ruler/defaults.py — the storage durability burn-rate group over
        ``m3tpu_storage_corruption_total``); a file group reusing a
        default group's name wins, so a deployment can override the
        defaults rule-for-rule or drop them with ``--no-default-rules``."""
        from ..ruler import Ruler, WebhookNotifier, groups_to_spec

        self.ruler = Ruler(
            engine_for=self.engine_for,
            db=self.db,
            kv=self.kv,
            notifiers=[WebhookNotifier(u) for u in webhooks],
            instance=instance,
            default_namespace=self.namespace,
            ensure_namespace=lambda ns: self._ensure_selfmon_namespace(),
            jitter=jitter,
        )
        groups = []
        if rules_path:
            from ..ruler import load_rules_file

            groups = load_rules_file(rules_path, self.namespace)
        if default_rules:
            from ..ruler.defaults import default_groups

            named = {g.name for g in groups}
            groups = groups + [
                g for g in default_groups() if g.name not in named
            ]
        if groups:
            self._ruler_groups = groups
            self.ruler.publish(groups_to_spec(self._ruler_groups))
        self.ruler.start()
        return self.ruler

    # --- SLO engine (m3_tpu/slo/): error budgets over the ruler's output ---

    def start_slo(
        self,
        slo_path: str,
        webhooks=(),
        instance: str = "coordinator0",
        jitter: bool = True,
    ):
        """Start the fleet SLO engine from an ``--slo-config`` spec file:
        the objectives compile into one generated ``slo`` rule group
        (ratio recordings + multi-window burn-rate alerts) published
        through the ruler alongside any file-sourced groups, and the
        engine's status/probe loops feed ``m3tpu_slo_*`` metrics plus the
        ``/api/v1/slo`` + ``/debug/slo`` surfaces.

        Requires a running self-scrape (the compiled rules read the
        fleet's own stored telemetry in ``_m3tpu``); starts the ruler if
        none is running yet."""
        from ..ruler import groups_to_spec
        from ..slo import SLO_GROUP, SLOEngine, load_slo_file

        if self.selfmon is None:
            raise RuntimeError(
                "the SLO engine consumes the fleet's own stored telemetry: "
                "start the self-scrape (--selfmon-interval) before "
                "--slo-config, or the compiled SLI rules evaluate over an "
                "empty _m3tpu namespace forever"
            )
        spec = load_slo_file(slo_path)
        if self.ruler is None:
            self.start_ruler(webhooks=webhooks, instance=instance, jitter=jitter)
        if any(g.name == SLO_GROUP for g in self._ruler_groups):
            raise ValueError(
                f"rule group name {SLO_GROUP!r} is reserved for the "
                "generated SLO group (--slo-config); rename the file group"
            )
        self.slo = SLOEngine(
            spec,
            engine_for=self.engine_for,
            db=self.db,
            ruler=self.ruler,
            namespace=self.namespace,
            instance=instance,
        )
        self.ruler.publish(
            groups_to_spec(list(self._ruler_groups) + self.slo.rule_groups())
        )
        self.slo.start()
        return self.slo

    # --- continuous profiling (m3_tpu/profiling/) ---

    def fleet_profile(self, seconds: float = 30.0) -> dict:
        """One whole-fleet folded-stack profile: this coordinator's own
        sampler plus every peer's ``profile`` wire op, merged by stack
        with per-instance counts (/debug/pprof/fleet). Dead peers are
        reported in ``errors``, never fatal."""
        from ..profiling import collect_fleet_profile, process_profile

        peers = {}
        source_error = None
        if self.peer_source is not None:
            try:
                peers = dict(self.peer_source())
            except Exception as exc:
                # a broken topology source must not make a local-only
                # profile look like a healthy single-node fleet
                source_error = f"{type(exc).__name__}: {exc}"
        out = collect_fleet_profile(
            self.instance_id, process_profile(seconds=seconds), peers, seconds
        )
        if source_error is not None:
            out["errors"]["peer_source"] = source_error
        return out

    def _ensure_selfmon_namespace(self) -> None:
        from ..selfmon import RESERVED_NS

        # memoized: this runs per ingested selfmon metric, and in cluster
        # mode the check below would otherwise cost a control-plane KV
        # round trip every time (SessionDatabase.namespaces is the static
        # constructor tuple, never containing the reserved ns)
        if self._selfmon_ns_ready:
            return
        if RESERVED_NS in self.db.namespaces:
            self._selfmon_ns_ready = True
            return
        if hasattr(self.db, "create_namespace"):
            # short retention: self telemetry is operational, not archival
            self.db.create_namespace(
                RESERVED_NS,
                NamespaceOptions(
                    retention_nanos=24 * 3600 * NANOS,
                    block_size_nanos=3600 * NANOS,
                ),
            )
            self._selfmon_ns_ready = True
            return
        # cluster mode (SessionDatabase): register in the control-plane
        # namespace registry — every watching dbnode creates it live
        from ..cluster.namespaces import NamespaceExistsError, NamespaceRegistry

        try:
            NamespaceRegistry(self.kv).add(
                RESERVED_NS, 24 * 3600 * NANOS, 3600 * NANOS
            )
        except NamespaceExistsError:
            pass  # another coordinator (or operator) won the race: same goal
        self._selfmon_ns_ready = True

    # --- ingest (downsamplerAndWriter ingest/write.go:138) ---

    def ingest_aggregated(self, msgs) -> int:
        """m3msg ingest (ingest/m3msg/ingest.go): aggregated metrics from
        the aggregator tier land in storage. Tag-wire metric IDs are
        decoded back to tags and written tagged (indexed) with the
        aggregation type as an extra label (the reference's suffix scheme,
        label-form so PromQL metric names stay valid); opaque IDs write
        untagged."""
        from ..selfmon import RESERVED_NS, SELFMON_MARKER, selfmon_writer
        from ..utils.serialize import decode_tags, is_tag_id

        n = 0
        for m in msgs:
            if is_tag_id(m.id):
                try:
                    tags = tuple(sorted(decode_tags(m.id)))
                except ValueError:
                    tags = None
                if tags is not None and SELFMON_MARKER in tags:
                    # bus-ingested self telemetry (an aggregator's MsgSink):
                    # strip the marker and route into the reserved
                    # namespace, unsuffixed — these are registry snapshots,
                    # not aggregated rollups
                    tags = tuple(t for t in tags if t != SELFMON_MARKER)
                    self._ensure_selfmon_namespace()
                    with selfmon_writer():
                        self.db.write_tagged(
                            RESERVED_NS, tags, m.time_nanos, m.value
                        )
                    n += 1
                    continue
                if tags is not None:
                    tags = tuple(tags) + ((b"agg", m.agg_type.type_string.encode()),)
                    self.db.write_tagged(self.namespace, tags, m.time_nanos, m.value)
                    n += 1
                    continue
            # opaque IDs: the aggregation type must still split series —
            # same suffix scheme as the direct-forward path (suffixed_id)
            sid = m.id + b"." + m.agg_type.type_string.encode()
            self.db.write(self.namespace, sid, m.time_nanos, m.value)
            n += 1
        return n

    def serve_msg_ingest(self, host: str = "127.0.0.1", port: int = 0):
        """Start the m3msg consumer endpoint (coordinator m3msg ingester,
        src/cmd/services/m3coordinator/ingest/m3msg/) — returns the
        ConsumerServer (its .port is the listen port)."""
        from ..metrics.encoding import decode_aggregated_batch
        from ..msg.transport import ConsumerServer

        def handler(message) -> bool:
            try:
                self.ingest_aggregated(decode_aggregated_batch(message.payload))
                return True
            except Exception:
                return False  # nack: the producer's retry sweep redelivers

        server = ConsumerServer(handler, host=host, port=port)
        server.start()
        return server

    def write_prom(self, req: prompb.WriteRequest) -> int:
        """Remote-write ingest; storage writes ride the BATCHED path
        end-to-end (client host queues → one write_tagged_batch RPC per
        host) when the backing db supports it."""
        count = 0
        rows = []
        for ts in req.timeseries:
            tags = make_tags([(l.name, l.value) for l in ts.labels])
            for s in ts.samples:
                rows.append((tags, s.timestamp * MS, s.value, MetricType.GAUGE))
                count += 1
        # mapping/rollup rules evaluate over the whole batch (cached
        # matcher, one aggregator lock) instead of per sample
        if self.downsampler is not None and rows:
            keeps = self.downsampler.write_batch(rows)
        else:
            keeps = [True] * len(rows)
        batch = [
            (tags, t_nanos, v, 1)
            for (tags, t_nanos, v, _), keep in zip(rows, keeps)
            if keep
        ]
        if batch:
            if hasattr(self.db, "write_tagged_batch"):
                errs = self.db.write_tagged_batch(self.namespace, batch)
                failed = [e for e in errs if e]
                if failed:
                    # entries that reached quorum stay written; the client
                    # retry re-upserts them idempotently
                    raise RuntimeError(
                        f"remote write partial failure: {len(failed)}/{len(errs)} "
                        f"samples (first: {failed[0]})"
                    )
            else:
                for tags, t_nanos, v, unit in batch:
                    self.db.write_tagged(self.namespace, tags, t_nanos, v)
        from ..query.tenants import charge_writes

        charge_writes(count)
        return count

    def read_prom(self, req: prompb.ReadRequest) -> prompb.ReadResponse:
        resp = prompb.ReadResponse()
        for q in req.queries:
            matchers = []
            for m in q.matchers:
                op = {0: "=", 1: "!=", 2: "=~", 3: "!~"}[m.type]
                matchers.append(Matcher(m.name, op, m.value))
            result = resp.results.add()
            raw = self.engine.storage.fetch(
                matchers, q.start_timestamp_ms * MS, (q.end_timestamp_ms + 1) * MS
            )
            for tags, times, vals in raw:
                ts = result.timeseries.add()
                for k, v in tags:
                    ts.labels.add(name=k.decode(), value=v.decode())
                for t, v in zip(times, vals):
                    ts.samples.add(value=float(v), timestamp=int(t) // MS)
        return resp

    def query_range(self, query: str, start_s: float, end_s: float, step_s: float,
                    namespace: str | None = None,
                    force_staged: bool = False) -> dict:
        # force_staged: the fused-pipeline parity probe (query/plan.py) —
        # device query plans are disabled for this evaluation so callers
        # can diff fused vs staged results bit for bit
        from ..query import plan as query_plan

        eng = self.engine_for(namespace)
        args = (query, int(start_s * NANOS), int(end_s * NANOS),
                int(step_s * NANOS))
        if force_staged:
            with query_plan.force_staged():
                r = eng.query_range(*args)
        else:
            r = eng.query_range(*args)
        return _prom_matrix(r, int(start_s * NANOS), int(step_s * NANOS))

    def query_instant(self, query: str, time_s: float,
                      namespace: str | None = None) -> dict:
        r = self.engine_for(namespace).query_instant(query, int(time_s * NANOS))
        return _prom_vector(r, time_s)

    def explain(self, query: str, start_s: float, end_s: float, step_s: float,
                namespace: str | None = None) -> dict:
        """Query EXPLAIN (Engine.explain): per-stage timings, scan
        counters, and the per-block resident-vs-streamed routing record."""
        return self.engine_for(namespace).explain(
            query, int(start_s * NANOS), int(end_s * NANOS), int(step_s * NANOS)
        )

    def _cost_parent(self):
        """The parent scope a fresh per-query Enforcer chains to: the
        active tenant's middle scope when tenant limits are configured,
        else the global ceiling (None when neither is)."""
        if self.engine.tenant_enforcers is not None:
            from ..query.tenants import current as current_tenant

            return self.engine.tenant_enforcers.scope_for(current_tenant())
        return self.engine.global_enforcer

    # --- graphite (src/query/api/v1/handler/graphite/render.go + find.go) ---

    def _graphite_engine(self, enforcer=None):
        from ..graphite.engine import GraphiteEngine

        ns = "graphite" if "graphite" in self.db.namespaces else self.namespace
        return GraphiteEngine(self.db, namespace=ns, enforcer=enforcer)

    def graphite_render(self, q: dict) -> list[dict]:
        import time as _time

        now_s = _time.time()
        start_s = _graphite_time(q.get("from", ["-1h"])[0], now_s)
        end_s = _graphite_time(q.get("until", ["now"])[0], now_s)
        step_s = _parse_step(q.get("step", ["10"])[0])
        if step_s <= 0:
            raise ValueError("step must be positive")
        steps = max(int((end_s - start_s) // step_s), 1)
        # the graphite path honors the same cost limits as PromQL: bound the
        # step grid up front, charge fetched output per target — through
        # the same query → tenant → global chain. The graphite engine has
        # no QueryStats record (stats.finish is the PromQL path's ledger
        # seam), so this surface charges the tenant ledger itself — every
        # query surface must attribute, or /debug/tenants lies for it.
        from ..query import tenants as _tenants
        from ..query.cost import QueryLimitError

        limits = self.engine.limits
        parent = self._cost_parent()
        enforcer = None
        rejected = errored = False
        try:
            if limits is not None or parent is not None:
                from ..query.cost import Enforcer, QueryLimits, limit_error

                if limits is not None and 0 < limits.max_datapoints < steps:
                    raise limit_error(
                        "query", "datapoints", steps, limits.max_datapoints
                    )
                enforcer = Enforcer(
                    limits if limits is not None else QueryLimits(), parent
                )
            # the enforcer rides inside the engine's fetch, so oversized
            # globs abort at fetch depth (like the PromQL path), not after
            # rendering
            engine = self._graphite_engine(enforcer=enforcer)
            out = []
            for target in q.get("target", []):
                series = engine.render(
                    target, int(start_s * NANOS), int(end_s * NANOS), int(step_s * NANOS)
                )
                for s in series:
                    pts = [
                        [None if np.isnan(v) else float(v), int(start_s + i * step_s)]
                        for i, v in enumerate(s.values)
                    ]
                    out.append({"target": s.name, "datapoints": pts})
            return out
        except Exception as exc:
            errored = True
            rejected = isinstance(exc, QueryLimitError)
            raise
        finally:
            if enforcer is not None:
                enforcer.release()
            _tenants.LEDGER.charge(
                _tenants.current() or _tenants.DEFAULT_TENANT,
                queries=1,
                series=enforcer.series if enforcer is not None else 0,
                datapoints=enforcer.datapoints if enforcer is not None else 0,
                limit_rejections=1 if rejected else 0,
                errors=1 if errored else 0,
            )

    def graphite_find(self, pattern: str) -> list[dict]:
        return self._graphite_engine().find(pattern)

    @staticmethod
    def _parse_prom_matchers(expr: str) -> list[Matcher]:
        """A match[] selector string → matchers (reuses the PromQL parser)."""
        from ..query.promql import VectorSelector, parse

        ast = parse(expr)
        if not isinstance(ast, VectorSelector):
            raise ValueError(f"match[] must be a series selector: {expr!r}")
        matchers = list(ast.matchers)
        if ast.name:
            matchers.append(Matcher("__name__", "=", ast.name))
        return matchers

    def _index_query(self, match_exprs: list[str]):
        from ..query.m3_storage import matchers_to_index_query

        if not match_exprs:
            return None
        from ..index.query import disj

        qs = [
            matchers_to_index_query(self._parse_prom_matchers(e))
            for e in match_exprs
        ]
        return qs[0] if len(qs) == 1 else disj(*qs)

    def series(self, match_exprs: list[str], start_nanos: int, end_nanos: int):
        """/api/v1/series (api/v1/handler/prometheus/native + remote in the
        reference): label sets of series matching any selector."""
        if not match_exprs:
            # prometheus requires at least one selector; an unbounded full
            # index dump would bypass the cost limits
            raise ValueError("series endpoint requires at least one match[]")
        q = self._index_query(match_exprs)
        limit = None
        if self.engine.limits is not None and self.engine.limits.max_series:
            limit = self.engine.limits.max_series
        result = self.db.query_ids(self.namespace, q, start_nanos, end_nanos, limit=limit)
        return [
            {k.decode(): v.decode() for k, v in doc.fields}
            for doc in result.docs
        ]

    def search(self, match_exprs: list[str], start_nanos: int, end_nanos: int,
               limit: int | None = None):
        """/api/v1/search (api/v1/handler/search.go): series IDs + tags
        matching the given selectors."""
        if not match_exprs:
            raise ValueError("search requires at least one match[]")
        q = self._index_query(match_exprs)
        result = self.db.query_ids(self.namespace, q, start_nanos, end_nanos, limit=limit)
        return [
            {
                "id": doc.id.decode("utf-8", "replace"),
                "tags": {k.decode(): v.decode() for k, v in doc.fields},
            }
            for doc in result.docs
        ]

    def write_influx(self, body: str, precision: str = "ns") -> int:
        """InfluxDB line-protocol ingest (handler/influxdb/write.go)."""
        from .influx import parse_body

        points = parse_body(body, precision=precision)
        rows = []
        for name, tags, t_nanos, value in points:
            # __name__ must win over any same-named line tag
            tag_pairs = make_tags({**tags, "__name__": name})
            rows.append((tag_pairs, t_nanos, value, MetricType.GAUGE))
        if self.downsampler is not None and rows:
            keeps = self.downsampler.write_batch(rows)
        else:
            keeps = [True] * len(rows)
        for (tag_pairs, t_nanos, value, _), keep in zip(rows, keeps):
            if keep:
                self.db.write_tagged(self.namespace, tag_pairs, t_nanos, value)
        from ..query.tenants import charge_writes

        charge_writes(len(points))
        return len(points)

    def labels(self, match_exprs: list[str] | None = None,
               start_nanos: int = 0, end_nanos: int = 2**62) -> list[str]:
        q = self._index_query(match_exprs or [])
        agg = self.db.aggregate_query(self.namespace, q, start_nanos, end_nanos)
        return sorted(k.decode() for k in agg)

    def label_values(self, name: str, match_exprs: list[str] | None = None,
                     start_nanos: int = 0, end_nanos: int = 2**62) -> list[str]:
        q = self._index_query(match_exprs or [])
        agg = self.db.aggregate_query(
            self.namespace, q, start_nanos, end_nanos, field_filter=[name.encode()]
        )
        return sorted(v.decode() for v in agg.get(name.encode(), ()))


def _prom_matrix(r: Result, start_nanos: int, step_nanos: int) -> dict:
    out = []
    vals = np.asarray(r.values)
    for i, meta in enumerate(r.metas):
        metric = {k.decode(): v.decode() for k, v in meta.tags}
        values = []
        for t in range(vals.shape[1]):
            v = vals[i, t]
            if np.isnan(v):
                continue
            values.append([(start_nanos + t * step_nanos) / NANOS, _fmt(v)])
        if values:
            out.append({"metric": metric, "values": values})
    return {"status": "success", "data": {"resultType": "matrix", "result": out}}


def _prom_vector(r: Result, time_s: float) -> dict:
    out = []
    vals = np.asarray(r.values)
    for i, meta in enumerate(r.metas):
        v = vals[i, -1]
        if np.isnan(v):
            continue
        metric = {k.decode(): v2.decode() for k, v2 in meta.tags}
        out.append({"metric": metric, "value": [time_s, _fmt(v)]})
    return {"status": "success", "data": {"resultType": "vector", "result": out}}


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


class _Handler(BaseHTTPRequestHandler):
    coordinator: Coordinator = None  # injected by serve()

    def log_message(self, *args) -> None:  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200) -> None:
        self._send(code, json.dumps(obj).encode())

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    def _tenant(self, q: dict) -> str:
        """The caller's tenant identity: ``M3-Tenant`` header first, then
        the ``tenant=`` query param, default anonymous — normalized so
        junk ids collapse into the capped overflow tenant."""
        from ..query.tenants import normalize

        return normalize(
            self.headers.get("M3-Tenant") or q.get("tenant", [None])[0]
        )

    def _deadline_scope(self, q: dict):
        """Client deadline propagation: the ``timeout=`` query param (or
        ``M3-Timeout`` header) in duration syntax (``500``, ``2.5``,
        ``30s``, ``1m``) becomes the request thread's ambient MONOTONIC
        deadline — QueryScheduler.admit bounds its queue wait by it
        (shed reason ``deadline``) and outbound RPC calls tighten their
        wall-clock budget and ``_deadline`` frame to it, so nobody works
        for a caller that already gave up. Unparseable or absent →
        no-op scope (only ``--sched-max-wait`` bounds the wait)."""
        from ..net.resilience import deadline_scope

        raw = self.headers.get("M3-Timeout") or q.get("timeout", [None])[0]
        if not raw:
            return deadline_scope(None)
        try:
            timeout_s = _parse_step(raw)
        except ValueError:
            return deadline_scope(None)
        import time as _time

        return deadline_scope(_time.monotonic() + timeout_s)

    def _debug_dump(self) -> bytes:
        """x/debug/debug.go zip dump: thread stacks, metrics, namespaces,
        placement, recent traces."""
        import io
        import sys
        import traceback
        import zipfile

        from ..utils.instrument import DEFAULT as METRICS
        from ..utils.trace import TRACER

        c = self.coordinator
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            stacks = []
            for tid, frame in sys._current_frames().items():
                stacks.append(f"--- thread {tid} ---")
                stacks.extend(traceback.format_stack(frame))
            z.writestr("stacks.txt", "\n".join(stacks))
            z.writestr("metrics.txt", METRICS.expose())
            z.writestr("traces.json", json.dumps(TRACER.dump(limit=512), indent=1))
            from ..query.stats import ACTIVE, RING

            z.writestr(
                "slow_queries.json", json.dumps(RING.dump(limit=128), indent=1)
            )
            z.writestr(
                "active_queries.json", json.dumps(ACTIVE.dump(), indent=1)
            )
            from ..query.tenants import LEDGER

            z.writestr("tenants.json", json.dumps(LEDGER.dump(), indent=1))
            # incident snapshot: the current folded-stack profile and the
            # device-memory split ride along, so one dump answers "where
            # was the time and the memory" next to slow_queries/tenants
            from ..profiling import collect_device_memory, process_profile

            z.writestr(
                "profile.json", json.dumps(process_profile(), indent=1)
            )
            z.writestr(
                "device_memory.json",
                json.dumps(collect_device_memory(c.db), indent=1),
            )
            if getattr(c.db, "resident_pool", None) is not None:
                # per-shard residency heat (resident/heat.py) + pool
                # stats: the rebalance signal next to the incident data
                z.writestr(
                    "resident.json",
                    json.dumps(c.db.resident_stats(), indent=1),
                )
            if hasattr(c.db, "index_stats"):
                # device index tier + postings cache: segment counts,
                # device bytes vs budget, eviction/routing counters
                # (m3_tpu/index/device/)
                z.writestr(
                    "index.json",
                    json.dumps(c.db.index_stats(), indent=1),
                )
            if c.ruler is not None:
                z.writestr(
                    "ruler.json",
                    json.dumps(
                        {"rules": c.ruler.rules_dict(),
                         "alerts": c.ruler.alerts_dict()},
                        indent=1,
                    ),
                )
            if c.slo is not None:
                z.writestr(
                    "slo.json", json.dumps(c.slo.debug_dict(), indent=1)
                )
            ns_info = {}
            if hasattr(c.db, "lock"):
                with c.db.lock:
                    namespaces = list(c.db.namespaces.items())
                for name, ns in namespaces:
                    counts = []
                    for s in ns.shards:
                        with s.lock:
                            counts.append(len(s.series))
                    ns_info[name] = {
                        "blockSizeNanos": ns.opts.block_size_nanos,
                        "retentionNanos": ns.opts.retention_nanos,
                        "numShards": len(ns.shards),
                        "numSeries": sum(counts),
                    }
            else:
                # cluster mode (SessionDatabase): the shards live on the
                # dbnodes — dump the known namespace names only
                ns_info = {name: {} for name in sorted(c.db.namespaces)}
            z.writestr("namespaces.json", json.dumps(ns_info, indent=1))
            p = c.placement_svc.get()
            z.writestr("placement.json", json.dumps(p.to_dict() if p else {}, indent=1))
        return buf.getvalue()

    def do_GET(self) -> None:
        from ..utils.trace import TRACER

        c = self.coordinator
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            # poller endpoints (health checks, metric scrapes, the trace
            # endpoints themselves) would evict useful spans from the ring
            from ..utils.trace import NOOP_SPAN

            span = (
                NOOP_SPAN
                if url.path in (
                    "/health", "/metrics", "/debug/traces",
                    "/debug/slow_queries", "/debug/dump",
                    "/debug/exemplars", "/debug/active_queries",
                    "/debug/tenants", "/debug/pprof/profile",
                    "/debug/pprof/fleet", "/api/v1/slo", "/debug/slo",
                )
                else TRACER.span("http.get", path=url.path)
            )
            # tenant identity (M3-Tenant header / tenant= param) rides a
            # thread-local for the whole request: QueryStats, the cost
            # chain's tenant scope, the ledger, and outbound RPC frames
            # all read it from here
            from ..query.tenants import tenant_context

            tenant = self._tenant(q)
            span.set_tag("tenant", tenant)
            with tenant_context(tenant), self._deadline_scope(q), span:
                if url.path == "/health":
                    self._json({"ok": True})
                elif url.path == "/metrics":
                    from ..utils.instrument import DEFAULT as METRICS

                    # content negotiation (openmetrics_spec): a scraper
                    # advertising openmetrics-text gets the 1.0 exposition
                    # (counter _total naming, exemplars on bucket lines,
                    # # EOF); everyone else keeps the 0.0.4 text format
                    accept = self.headers.get("Accept", "")
                    if "application/openmetrics-text" in accept:
                        self._send(
                            200,
                            METRICS.expose_openmetrics().encode(),
                            ctype="application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8",
                        )
                    else:
                        self._send(
                            200, METRICS.expose().encode(),
                            ctype="text/plain; version=0.0.4",
                        )
                elif url.path == "/api/v1/query_range":
                    self._json(
                        c.query_range(
                            q["query"][0],
                            float(q["start"][0]),
                            float(q["end"][0]),
                            _parse_step(q.get("step", ["15"])[0]),
                            namespace=q.get("namespace", [None])[0],
                            force_staged=q.get("force_staged", ["0"])[0]
                            in ("1", "true"),
                        )
                    )
                elif url.path == "/api/v1/query":
                    self._json(
                        c.query_instant(
                            q["query"][0],
                            float(q["time"][0]),
                            namespace=q.get("namespace", [None])[0],
                        )
                    )
                elif url.path == "/api/v1/explain":
                    self._json(
                        c.explain(
                            q["query"][0],
                            float(q["start"][0]),
                            float(q.get("end", q["start"])[0]),
                            _parse_step(q.get("step", ["15"])[0]),
                            namespace=q.get("namespace", [None])[0],
                        )
                    )
                elif url.path == "/api/v1/labels":
                    self._json(
                        {"status": "success",
                         "data": c.labels(q.get("match[]", []), *_prom_range(q))}
                    )
                elif url.path == "/api/v1/series":
                    self._json(
                        {"status": "success",
                         "data": c.series(q.get("match[]", []), *_prom_range(q))}
                    )
                elif (m := re.match(r"^/api/v1/label/([^/]+)/values$", url.path)) is not None:
                    self._json(
                        {"status": "success",
                         "data": c.label_values(
                             m.group(1), q.get("match[]", []), *_prom_range(q)
                         )}
                    )
                elif url.path == "/api/v1/search":
                    self._json(
                        {"status": "success",
                         "data": c.search(
                             q.get("match[]", []) or q.get("query", []),
                             *_prom_range(q),
                             limit=int(q["limit"][0]) if "limit" in q else None,
                         )}
                    )
                elif url.path == "/api/v1/services/m3db/placement":
                    p = c.placement_svc.get()
                    self._json(p.to_dict() if p else {}, 200 if p else 404)
                elif url.path == "/api/v1/rules":
                    # one route, two rule planes: the r2 aggregation
                    # rulesets (namespaces/rulesets keys, unchanged) plus
                    # the Prometheus rules-API shape (status/data.groups)
                    # for the ruler's recording/alerting groups
                    from ..rules.r2 import RuleStore, listing_dict

                    out = listing_dict(RuleStore(c.kv))
                    out["status"] = "success"
                    out["data"] = (
                        c.ruler.rules_dict() if c.ruler is not None
                        else {"groups": []}
                    )
                    self._json(out)
                elif url.path == "/api/v1/alerts":
                    self._json(
                        {
                            "status": "success",
                            "data": (
                                c.ruler.alerts_dict() if c.ruler is not None
                                else {"alerts": []}
                            ),
                        }
                    )
                elif (m := re.match(r"^/api/v1/rules/([^/]+)$", url.path)) is not None:
                    from ..rules.r2 import RuleStore, ruleset_to_dict

                    rs = RuleStore(c.kv).get(m.group(1))
                    if rs is None:
                        self._json({"error": "not found"}, 404)
                    else:
                        self._json(ruleset_to_dict(rs))
                elif url.path == "/api/v1/slo":
                    # live SLO status: per-objective budget remaining +
                    # burn rates joined to the firing burn alerts
                    self._json(
                        {
                            "status": "success",
                            "data": (
                                c.slo.status_dict() if c.slo is not None
                                else {"objectives": []}
                            ),
                        }
                    )
                elif url.path == "/debug/slo":
                    # status + the spec + the generated rule plane: the
                    # operator's alert → objective → rules walk
                    self._json(
                        c.slo.debug_dict() if c.slo is not None
                        else {"objectives": [], "spec": None}
                    )
                elif url.path == "/debug/traces":
                    limit = int(q.get("limit", ["256"])[0])
                    self._json({"spans": TRACER.dump(limit=limit)})
                elif url.path == "/debug/slow_queries":
                    from ..query.stats import RING

                    limit = int(q.get("limit", ["64"])[0])
                    self._json({"queries": RING.dump(limit=limit)})
                elif url.path == "/debug/active_queries":
                    # what is running RIGHT NOW: trace id, namespace,
                    # elapsed, current stage — joined by traceId to
                    # /debug/slow_queries and /debug/traces
                    from ..query.stats import ACTIVE

                    self._json(ACTIVE.dump())
                elif url.path == "/debug/tenants":
                    # who is spending what: per-tenant rolling-window +
                    # cumulative ledger columns (query/tenants.py), the
                    # live sibling of the stored m3tpu_tenant_* series
                    from ..query.tenants import LEDGER

                    self._json(LEDGER.dump())
                elif url.path == "/debug/exemplars":
                    # trace-ID exemplars per histogram bucket: join a slow
                    # bucket to its stitched trace (/debug/traces) and its
                    # /debug/slow_queries record by traceId. (Exemplars
                    # live here, not in the 0.0.4 text exposition, which
                    # has no grammar for them.)
                    from ..utils.instrument import DEFAULT as METRICS

                    out = {}
                    for name, fam in METRICS.collect().items():
                        rows = [
                            {"labels": ch["labels"],
                             "exemplars": ch["exemplars"]}
                            for ch in fam["children"]
                            if ch.get("exemplars")
                        ]
                        if rows:
                            out[name] = rows
                    self._json({"exemplars": out})
                elif url.path == "/debug/pprof/profile":
                    # this process's wall-clock folded-stack profile
                    # (m3_tpu/profiling/): flamegraph-ready folded text
                    # by default, the structured table with format=json
                    from ..profiling import folded_text, process_profile

                    prof = process_profile(
                        seconds=float(q.get("seconds", ["30"])[0])
                    )
                    if q.get("format", ["text"])[0] == "json":
                        self._json(prof)
                    else:
                        self._send(
                            200,
                            folded_text(prof["folded"]).encode(),
                            ctype="text/plain",
                        )
                elif url.path == "/debug/pprof/fleet":
                    # whole-fleet profile: own sampler + every peer's
                    # `profile` op over the placement, merged by stack
                    # with per-instance counts
                    from ..profiling import folded_text

                    prof = c.fleet_profile(
                        seconds=float(q.get("seconds", ["30"])[0])
                    )
                    if q.get("format", ["json"])[0] == "text":
                        self._send(
                            200,
                            folded_text(prof["folded"]).encode(),
                            ctype="text/plain",
                        )
                    else:
                        self._json(prof)
                elif url.path == "/debug/dump":
                    self._send(
                        200, self._debug_dump(), ctype="application/zip"
                    )
                elif url.path in ("/api/v1/graphite/render", "/render"):
                    self._json(c.graphite_render(q))
                elif url.path in ("/api/v1/graphite/metrics/find", "/metrics/find"):
                    self._json(c.graphite_find(q.get("query", ["*"])[0]))
                else:
                    self._json({"error": "not found"}, 404)
        except Exception as exc:  # surface handler errors as 4xx/5xx
            self._handler_error(exc)

    def _handler_error(self, exc: Exception) -> None:
        """Typed error mapping shared by GET/POST: a scheduler shed is
        503 (retry later, with errorType=shed + Retry-After), a cost
        limit is 422 (your query is too expensive), anything else 400."""
        from ..query.cost import QueryLimitError
        from ..query.scheduler import QueryShedError

        if isinstance(exc, QueryShedError):
            body = json.dumps(
                {
                    "status": "error",
                    "errorType": "shed",
                    "reason": exc.reason,
                    "error": str(exc),
                }
            ).encode()
            self.send_response(503)
            self.send_header("Retry-After", "1")
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        code = 422 if isinstance(exc, QueryLimitError) else 400
        self._json({"status": "error", "error": str(exc)}, code)

    def do_POST(self) -> None:
        from ..utils.trace import TRACER

        c = self.coordinator
        url = urlparse(self.path)
        try:
            from ..query.tenants import tenant_context

            q = parse_qs(url.query)
            tenant = self._tenant(q)
            span = TRACER.span("http.post", path=url.path)
            span.set_tag("tenant", tenant)
            with tenant_context(tenant), self._deadline_scope(q), span:
                if url.path in (
                    "/api/v1/graphite/render",
                    "/render",
                    "/api/v1/graphite/metrics/find",
                    "/metrics/find",
                ):
                    # Grafana's graphite datasource POSTs form-encoded bodies
                    form = parse_qs(self._body().decode())
                    form.update(parse_qs(url.query))
                    # header/query-param identity wins; a tenant supplied
                    # only in the form body (the Grafana POST shape) must
                    # still attribute — nested context, restored on exit
                    from ..query.tenants import DEFAULT_TENANT, normalize

                    form_tenant = form.get("tenant", [None])[0]
                    inner = (
                        tenant_context(normalize(form_tenant))
                        if tenant == DEFAULT_TENANT and form_tenant
                        else tenant_context(None)
                    )
                    with inner:
                        if url.path.endswith("find"):
                            self._json(
                                c.graphite_find(form.get("query", ["*"])[0])
                            )
                        else:
                            self._json(c.graphite_render(form))
                elif url.path == "/api/v1/prom/remote/write":
                    raw = decompress(self._body())
                    req = prompb.WriteRequest()
                    req.ParseFromString(raw)
                    n = c.write_prom(req)
                    self._send(200, b"")
                elif url.path == "/api/v1/prom/remote/read":
                    raw = decompress(self._body())
                    req = prompb.ReadRequest()
                    req.ParseFromString(raw)
                    resp = c.read_prom(req)
                    self._send(
                        200,
                        compress(resp.SerializeToString()),
                        ctype="application/x-protobuf",
                    )
                elif url.path == "/api/v1/influxdb/write":
                    q = parse_qs(url.query)
                    n = c.write_influx(
                        self._body().decode(),
                        precision=q.get("precision", ["ns"])[0],
                    )
                    self._send(204, b"")
                elif url.path == "/api/v1/json/write":
                    body = json.loads(self._body())
                    tags = make_tags(body["tags"])
                    c.db.write_tagged(
                        c.namespace, tags, int(body["timestamp"] * NANOS), float(body["value"])
                    )
                    from ..query.tenants import charge_writes

                    charge_writes(1)
                    self._json({"ok": True})
                elif url.path == "/api/v1/services/m3db/database/create":
                    body = json.loads(self._body())
                    name = body["namespaceName"]
                    retention = int(
                        _parse_step(body.get("retentionTime", "48h")) * NANOS
                    )
                    block_size = int(
                        _parse_step(body.get("blockSize", "2h")) * NANOS
                    )
                    # dynamic registry (namespace/dynamic.go): every dbnode
                    # watching the control plane creates the namespace live
                    from ..cluster.namespaces import NamespaceRegistry

                    from ..cluster.namespaces import NamespaceExistsError

                    try:
                        # conflict detection lives INSIDE add()'s CAS loop
                        # (a pre-check here would race concurrent creates)
                        NamespaceRegistry(c.kv).add(name, retention, block_size)
                    except NamespaceExistsError as exc:
                        # running nodes never re-shape a live namespace —
                        # accepting different options would diverge new/
                        # restarted replicas from live ones
                        self._json({"error": str(exc)}, 409)
                        return
                    if hasattr(c.db, "create_namespace") and name not in c.db.namespaces:
                        c.db.create_namespace(
                            name,
                            NamespaceOptions(
                                retention_nanos=retention,
                                block_size_nanos=block_size,
                            ),
                        )
                    self._json({"namespace": name}, 201)
                elif (m := re.match(r"^/api/v1/rules/([^/]+)$", url.path)) is not None:
                    from ..rules.r2 import RuleStore, ruleset_from_dict

                    rs = ruleset_from_dict(json.loads(self._body()))
                    RuleStore(c.kv).set(m.group(1), rs)
                    self._json({"namespace": m.group(1), "version": rs.version}, 200)
                elif url.path == "/api/v1/topic":
                    body = json.loads(self._body())
                    c.topic_svc.add(
                        Topic(
                            body["name"],
                            body.get("numberOfShards", 64),
                            [
                                ConsumerService(s["serviceName"], s.get("consumptionType", "shared"))
                                for s in body.get("consumerServices", [])
                            ],
                        )
                    )
                    self._json({"ok": True}, 201)
                else:
                    self._json({"error": "not found"}, 404)
        except Exception as exc:
            self._handler_error(exc)


def _prom_range(q: dict) -> tuple[int, int]:
    """start/end query params (epoch seconds) → nanos, unbounded defaults."""
    start = q.get("start", [None])[0]
    end = q.get("end", [None])[0]
    s = int(float(start) * NANOS) if start is not None else 0
    e = int(float(end) * NANOS) if end is not None else 2**62
    return s, e


def _graphite_time(s: str, now_s: float) -> float:
    """Graphite time spec: epoch seconds, 'now', or relative '-1h'/'-30min'
    (render.go / graphite-web from/until parsing)."""
    s = str(s).strip()
    if s in ("now", ""):
        return now_s
    if s.startswith("-") or s.startswith("+"):
        from ..graphite.functions import parse_interval

        return now_s + parse_interval(s.lstrip("+")) / NANOS
    return float(s)


def _parse_step(s: str) -> float:
    m = re.match(r"^(\d+(?:\.\d+)?)([smhd]?)$", s)
    if not m:
        raise ValueError(f"bad duration {s!r}")
    mult = {"": 1, "s": 1, "m": 60, "h": 3600, "d": 86400}[m.group(2)]
    return float(m.group(1)) * mult


# --- service binary (cmd/services/m3coordinator/main) ---

from dataclasses import dataclass as _dataclass, field as _dc_field


@_dataclass
class LimitsConfig:
    max_series: int = 0
    max_datapoints: int = 0


@_dataclass
class CoordinatorConfig:
    """YAML schema for the coordinator binary (utils/config.py loader)."""

    host: str = "127.0.0.1"
    port: int = 0
    namespace: str = "default"
    base_dir: str = ""
    num_shards: int = 4
    limits: LimitsConfig = _dc_field(default_factory=LimitsConfig)
    # path to a per-tenant limits file (query/tenants.load_tenant_limits
    # format): enables the tenant middle scope of the cost chain
    tenant_limits: str = ""


def main(argv=None) -> int:
    """Runnable coordinator process:

        python -m m3_tpu.services.coordinator --port 7201 --base-dir /data

    or with a YAML config (utils/config.py schema = CoordinatorConfig):

        python -m m3_tpu.services.coordinator --config coordinator.yml

    Prints ``LISTENING <host> <port>`` once serving.
    """
    import argparse
    import signal

    from ..query.cost import QueryLimits
    from ..utils.config import load_config

    p = argparse.ArgumentParser(prog="m3tpu-coordinator")
    p.add_argument("--config", default="")
    p.add_argument("--host", default=None)
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--base-dir", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument(
        "--tenant-limits",
        default=None,
        help="path to a per-tenant limits YAML/JSON file "
        "(query/tenants.load_tenant_limits format): adds the per-tenant "
        "middle scope to the cost-enforcer chain so one tenant's "
        "runaway scan 422s without starving the fleet",
    )
    p.add_argument(
        "--kv-endpoint",
        default="",
        help="host:port of the control-plane KV server: admin APIs "
        "(placement/topic/rules) operate on the shared control plane",
    )
    p.add_argument(
        "--cluster",
        action="store_true",
        help="route the data plane through the placement to dbnode "
        "processes (requires --kv-endpoint) instead of embedding storage",
    )
    p.add_argument(
        "--failure-detector",
        action="store_true",
        help="run the liveness→auto-replace loop in this coordinator "
        "(requires --kv-endpoint); spares via --spare",
    )
    p.add_argument("--spare", action="append", default=[])
    p.add_argument("--heartbeat-timeout", type=float, default=10.0)
    p.add_argument(
        "--msg-listen",
        action="store_true",
        help="serve an m3msg consumer endpoint for aggregated-metric "
        "ingest (prints MSG_LISTENING <host> <port>)",
    )
    p.add_argument(
        "--selfmon-interval",
        type=float,
        default=0.0,
        help="self-scrape interval in seconds (0 disables): this "
        "coordinator's registry — plus every placement dbnode in "
        "--cluster mode and every --selfmon-peer — is stored as series "
        "under the reserved _m3tpu namespace and queryable via "
        "/api/v1/query*?namespace=_m3tpu",
    )
    p.add_argument(
        "--selfmon-peer",
        action="append",
        default=[],
        help="host:port of an extra RPC-scrapable process (dbnode port, "
        "aggregator --debug-port) to pull into the self-scrape",
    )
    p.add_argument(
        "--sched-max-inflight",
        type=int,
        default=0,
        help="cost-aware query admission (query/scheduler.py): at most "
        "this many PromQL queries evaluate concurrently; excess queries "
        "queue by shed-priority (tenant pressure + estimated cost − age) "
        "and the worst are shed with typed 503s "
        "(m3tpu_query_shed_total{tenant,reason}). 0 disables admission",
    )
    p.add_argument(
        "--sched-max-queue",
        type=int,
        default=64,
        help="admission queue capacity (with --sched-max-inflight): past "
        "it the worst-priority entry is shed with reason=queue_full",
    )
    p.add_argument(
        "--sched-max-wait",
        type=float,
        default=5.0,
        help="max seconds a query may wait queued before a "
        "reason=deadline shed (with --sched-max-inflight)",
    )
    p.add_argument("--instance-id", default="coordinator0")
    p.add_argument(
        "--profile-hz",
        type=float,
        default=None,
        help="wall-clock stack-sampler rate (m3_tpu/profiling/): serves "
        "/debug/pprof/profile and the whole-fleet /debug/pprof/fleet "
        "merge; default M3_TPU_PROFILE_HZ (19), 0 disables",
    )
    p.add_argument(
        "--ruler-rules",
        default="",
        help="path to a YAML/JSON rule file (recording + alerting "
        "groups): starts the ruler, mirrors the ruleset into the KV "
        "control plane when one is configured, and serves "
        "/api/v1/rules + /api/v1/alerts",
    )
    p.add_argument(
        "--ruler-webhook",
        action="append",
        default=[],
        help="alert webhook receiver URL (repeatable); firing/resolved "
        "transitions POST the Alertmanager webhook payload with "
        "retries under the resilience plane's budget",
    )
    p.add_argument(
        "--no-default-rules",
        action="store_true",
        help="skip the built-in default rule groups (ruler/defaults.py: "
        "the storage durability burn-rate group over "
        "m3tpu_storage_corruption_total); a rules file reusing a default "
        "group's name also overrides it without this flag",
    )
    p.add_argument(
        "--slo-config",
        default="",
        help="path to a YAML/JSON SLO spec (m3_tpu/slo/spec.py schema): "
        "compiles the objectives into recording + multi-window burn-rate "
        "alerting rules over _m3tpu, runs freshness/durability probes, "
        "and serves /api/v1/slo + /debug/slo; requires "
        "--selfmon-interval, starts the ruler if --ruler-rules is absent",
    )
    args = p.parse_args(argv)

    cfg = load_config(CoordinatorConfig, args.config) if args.config else CoordinatorConfig()
    host = args.host if args.host is not None else cfg.host
    port = args.port if args.port is not None else cfg.port
    base_dir = args.base_dir if args.base_dir is not None else (cfg.base_dir or None)
    namespace = args.namespace if args.namespace is not None else cfg.namespace

    kv = None
    if args.kv_endpoint:
        from ..cluster.kv_service import RemoteKVStore

        kv = RemoteKVStore.connect(args.kv_endpoint)

    db = None
    if args.cluster:
        if kv is None:
            p.error("--cluster requires --kv-endpoint")
        from ..client.session_db import SessionDatabase

        db = SessionDatabase(kv, namespaces=(namespace,))
    elif base_dir:
        db = Database(base_dir, num_shards=cfg.num_shards)
        db.create_namespace(namespace, NamespaceOptions())
        db.bootstrap()
    limits = None
    if cfg.limits.max_series or cfg.limits.max_datapoints:
        limits = QueryLimits(
            max_series=cfg.limits.max_series,
            max_datapoints=cfg.limits.max_datapoints,
        )
    tenant_limits = None
    tenant_limits_path = (
        args.tenant_limits if args.tenant_limits is not None
        else cfg.tenant_limits
    )
    if tenant_limits_path:
        from ..query.tenants import load_tenant_limits

        tenant_limits = load_tenant_limits(tenant_limits_path)
    scheduler = None
    if args.sched_max_inflight > 0:
        from ..query.scheduler import QueryScheduler

        scheduler = QueryScheduler(
            max_inflight=args.sched_max_inflight,
            max_queue=args.sched_max_queue,
            max_queue_wait=args.sched_max_wait,
        )
    coord = Coordinator(
        db=db, namespace=namespace, query_limits=limits, kv=kv,
        tenant_limits=tenant_limits, scheduler=scheduler,
    )
    coord.instance_id = args.instance_id
    server, bound = serve(coord, port, host=host)

    # ONE peer source shared by the self-scrape pull and the fleet
    # profile merge: static --selfmon-peer endpoints plus (in --cluster
    # mode) every placement dbnode, re-evaluated per use so topology
    # changes are picked up live
    static_peers = {}
    if args.selfmon_peer:
        from ..net.client import RemoteNode

        for ep in args.selfmon_peer:
            static_peers[ep] = RemoteNode.connect(ep)

    def fleet_peers() -> dict:
        peers = dict(static_peers)
        if args.cluster and hasattr(coord.db, "remote_nodes"):
            peers.update(coord.db.remote_nodes())
        return peers

    coord.peer_source = fleet_peers
    if args.selfmon_interval > 0:
        coord.start_selfmon(
            args.selfmon_interval, peers=fleet_peers,
            instance=args.instance_id,
        )

    from ..profiling import start_sampler

    profiler = start_sampler(
        hz=args.profile_hz, instance=args.instance_id, db=coord.db
    )

    if args.ruler_rules:
        coord.start_ruler(
            rules_path=args.ruler_rules,
            webhooks=list(args.ruler_webhook),
            instance=args.instance_id,
            default_rules=not args.no_default_rules,
        )

    if args.slo_config:
        if args.selfmon_interval <= 0:
            p.error(
                "--slo-config requires --selfmon-interval: the compiled "
                "SLI rules evaluate over the fleet's own stored telemetry "
                "in _m3tpu, which only the self-scrape populates"
            )
        coord.start_slo(
            args.slo_config,
            webhooks=list(args.ruler_webhook),
            instance=args.instance_id,
        )

    detector = None
    if args.failure_detector:
        if kv is None:
            p.error("--failure-detector requires --kv-endpoint")
        from ..cluster.failure import FailureDetector
        from ..cluster.services import Services

        detector = FailureDetector(
            Services(kv, heartbeat_timeout=args.heartbeat_timeout),
            coord.placement_svc,
            grace=args.heartbeat_timeout / 2.0,
            spares=list(args.spare),
        )
        detector.start(interval=max(args.heartbeat_timeout / 4.0, 0.1))

    def shutdown(signum, frame):
        raise SystemExit(0)

    msg_server = None
    if args.msg_listen:
        msg_server = coord.serve_msg_ingest(host=host)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    print(f"LISTENING {host} {bound}", flush=True)
    if msg_server is not None:
        print(f"MSG_LISTENING {host} {msg_server.port}", flush=True)
    try:
        # serve() already runs the accept loop on a daemon thread; a second
        # serve_forever() here would race it on the same socket. Park until
        # a signal raises SystemExit.
        threading.Event().wait()
    finally:
        if detector is not None:
            detector.stop()
        if msg_server is not None:
            msg_server.stop()
        if profiler is not None:
            profiler.stop()
        if coord.slo is not None:
            coord.slo.stop()
        if coord.selfmon is not None:
            coord.selfmon.stop()
        if coord.ruler is not None:
            coord.ruler.stop()
        for node in static_peers.values():
            try:
                node.close()
            except Exception:
                # m3lint: disable=M3L007 -- best-effort socket teardown on shutdown; the process is exiting
                pass
        server.shutdown()
        coord.db.close()
        if kv is not None:
            kv.close()
    return 0



def serve(
    coordinator: Coordinator, port: int = 0, host: str = "127.0.0.1"
) -> tuple[ThreadingHTTPServer, int]:
    """Start the HTTP server on a background thread; returns (server, port)."""
    handler = type("BoundHandler", (_Handler,), {"coordinator": coordinator})
    srv = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]
if __name__ == "__main__":
    import sys

    sys.exit(main())
