"""m3dbnode-equivalent service binary: a runnable storage node process.

Reference: /root/reference/src/cmd/services/m3dbnode/main/main.go:42 — the
node process wires config → Database → bootstrap → RPC server → background
mediator. Run:

    python -m m3_tpu.services.dbnode --base-dir /var/lib/m3tpu --port 9000 \
        --node-id node0 --shards 0,1,2,3 --namespace default

Prints ``LISTENING <host> <port>`` on stdout once serving (process managers
and the multi-process test fixture wait for it).
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..net.server import NodeServer, NodeService
from ..storage.database import Database, NamespaceOptions
from ..storage.mediator import Mediator, MediatorOptions
from ..storage.series import NANOS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-dbnode", description=__doc__)
    p.add_argument("--base-dir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--node-id", default="node0")
    p.add_argument("--num-shards", type=int, default=8)
    p.add_argument("--shards", default="", help="csv of owned shard ids")
    p.add_argument("--namespace", action="append", default=[])
    p.add_argument("--block-size-secs", type=int, default=2 * 3600)
    p.add_argument("--retention-secs", type=int, default=2 * 24 * 3600)
    p.add_argument("--no-cold-writes", action="store_true")
    p.add_argument("--no-mediator", action="store_true")
    p.add_argument("--no-bootstrap", action="store_true")
    p.add_argument(
        "--kv-endpoint",
        default="",
        help="host:port of the control-plane KV server; enables dynamic "
        "topology: the node advertises itself, heartbeats, watches its "
        "placement, and peers-bootstraps gained shards",
    )
    p.add_argument("--heartbeat-timeout", type=float, default=10.0)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    db = Database(args.base_dir, num_shards=args.num_shards)
    opts = NamespaceOptions(
        retention_nanos=args.retention_secs * NANOS,
        block_size_nanos=args.block_size_secs * NANOS,
        cold_writes_enabled=not args.no_cold_writes,
    )
    for ns in args.namespace or ["default"]:
        db.create_namespace(ns, opts)

    # dynamic namespaces (namespace/dynamic.go): the control-plane registry
    # is applied BEFORE bootstrap so registered namespaces recover their
    # data, and watched after so admin-created namespaces appear live
    kv = None
    ns_registry = None
    if args.kv_endpoint:
        from ..cluster.kv_service import RemoteKVStore
        from ..cluster.namespaces import NamespaceRegistry

        kv = RemoteKVStore.connect(args.kv_endpoint)
        ns_registry = NamespaceRegistry(kv)

        def _apply_registry(reg: dict) -> None:
            for name, rec in reg.items():
                if name in db.namespaces:
                    continue
                db.create_namespace(
                    name,
                    NamespaceOptions(
                        retention_nanos=int(rec["retention_nanos"]),
                        block_size_nanos=int(rec["block_size_nanos"]),
                        cold_writes_enabled=bool(
                            rec.get("cold_writes_enabled", True)
                        ),
                    ),
                )

        _apply_registry(ns_registry.get_all())

    if not args.no_bootstrap:
        db.bootstrap()

    mediator = None
    if not args.no_mediator:
        mediator = Mediator(db, MediatorOptions())
        mediator.start()

    shards = {int(s) for s in args.shards.split(",") if s.strip()}
    service = NodeService(db, node_id=args.node_id, assigned_shards=shards)
    server = NodeServer(service, host=args.host, port=args.port)

    # dynamic topology via the networked control plane
    # (server.go: embedded etcd + topology watch + KV runtime reconfig)
    cluster_db = None
    hb_stop = None
    if args.kv_endpoint:
        import threading

        from ..cluster.placement import PlacementService
        from ..cluster.services import ServiceInstance, Services
        from ..storage.cluster_db import ClusterDatabase

        # live namespace adds (bootstrap already applied the current set)
        ns_registry.watch(_apply_registry)

        # KV-watched runtime knobs over the NETWORKED control plane
        # (server.go:1007-1268 runtime reconfig; kvconfig keys)
        from ..storage.runtime import RuntimeOptionsManager

        runtime_mgr = RuntimeOptionsManager(kv)
        # watch() replays the current KV options to the new listener; with
        # no KV value yet the defaults equal the Database's own
        runtime_mgr.watch(db.apply_runtime_options)

        services = Services(kv, heartbeat_timeout=args.heartbeat_timeout)
        endpoint = f"{server.host}:{server.port}"
        services.advertise("m3db", ServiceInstance(args.node_id, endpoint))
        hb_stop = threading.Event()

        def hb_loop() -> None:
            interval = max(args.heartbeat_timeout / 3.0, 0.05)
            while not hb_stop.wait(interval):
                try:
                    services.heartbeat("m3db", args.node_id)
                except Exception:
                    pass  # KV hiccups must not kill the node

        threading.Thread(target=hb_loop, daemon=True, name="heartbeat").start()
        cluster_db = ClusterDatabase(
            db, args.node_id, PlacementService(kv), node_service=service
        )
        cluster_db.start()

    def shutdown(signum, frame):
        # SystemExit propagates out of serve_forever's select loop; the
        # finally block below closes the database cleanly
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    print(f"LISTENING {server.host} {server.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        if hb_stop is not None:
            hb_stop.set()
        if cluster_db is not None:
            cluster_db.stop()
        if kv is not None:
            kv.close()
        if mediator is not None:
            mediator.stop()
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
