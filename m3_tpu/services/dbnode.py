"""m3dbnode-equivalent service binary: a runnable storage node process.

Reference: /root/reference/src/cmd/services/m3dbnode/main/main.go:42 — the
node process wires config → Database → bootstrap → RPC server → background
mediator. Run:

    python -m m3_tpu.services.dbnode --base-dir /var/lib/m3tpu --port 9000 \
        --node-id node0 --shards 0,1,2,3 --namespace default

Prints ``LISTENING <host> <port>`` on stdout once serving (process managers
and the multi-process test fixture wait for it).
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..net.server import NodeServer, NodeService
from ..storage.database import Database, NamespaceOptions
from ..storage.mediator import Mediator, MediatorOptions
from ..storage.series import NANOS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="m3tpu-dbnode", description=__doc__)
    p.add_argument("--base-dir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--node-id", default="node0")
    p.add_argument("--num-shards", type=int, default=8)
    p.add_argument("--shards", default="", help="csv of owned shard ids")
    p.add_argument("--namespace", action="append", default=[])
    p.add_argument("--block-size-secs", type=int, default=2 * 3600)
    p.add_argument("--retention-secs", type=int, default=2 * 24 * 3600)
    p.add_argument("--no-cold-writes", action="store_true")
    p.add_argument("--no-mediator", action="store_true")
    p.add_argument("--no-bootstrap", action="store_true")
    p.add_argument(
        "--cache-bytes",
        type=int,
        default=256 * 1024 * 1024,
        help="decoded-block cache byte budget (0 disables the cache); "
        "stats are served on the cache_stats debug op",
    )
    p.add_argument(
        "--resident-bytes",
        type=int,
        default=0,
        help="HBM-resident compressed pool byte budget (0 disables the "
        "mode): sealed blocks' m3tsz bytes stay device-resident and warm "
        "scans decode from HBM (m3_tpu/resident/); stats on the "
        "resident_stats debug op",
    )
    p.add_argument(
        "--resident-side-bytes",
        type=int,
        default=0,
        help="byte budget for the pool's per-chunk side planes (the "
        "chunk-parallel decoder's device-resident metadata). Default 0 "
        "sizes them to --resident-bytes — i.e. total pool HBM is up to "
        "2x --resident-bytes; set this explicitly to cap it",
    )
    p.add_argument(
        "--index-device-bytes",
        type=int,
        default=0,
        help="device byte budget for the HBM-resident inverted index "
        "(0 disables the tier): sealed index segments' term dictionaries "
        "and postings admit at seal time and term/regexp/set-algebra "
        "resolution runs as batched kernels (m3_tpu/index/device/); "
        "stats on the index_stats debug op",
    )
    p.add_argument(
        "--device-ingest",
        action="store_true",
        help="device-side ingest (m3_tpu/ingest/): write batches mirror "
        "into per-shard (series_lane, slot) column planes; sealed blocks "
        "device-encode through the batched m3tsz kernel (m3_tpu/ops/"
        "encode.py) and admit born-resident with zero admission upload",
    )
    p.add_argument(
        "--ingest-lanes",
        type=int,
        default=1024,
        help="series lanes per ingest window plane (--device-ingest)",
    )
    p.add_argument(
        "--ingest-slots",
        type=int,
        default=1024,
        help="datapoint slots per ingest lane (--device-ingest)",
    )
    p.add_argument(
        "--ingest-sync-batch",
        type=int,
        default=8192,
        help="staged rows per shard that trigger a batched column-plane "
        "sync to device (--device-ingest)",
    )
    p.add_argument(
        "--commitlog-sync",
        choices=["every", "interval", "none"],
        default="interval",
        help="commit-log durability mode (storage.database."
        "COMMITLOG_SYNC_MODES): 'every' fsyncs before acking each write "
        "(zero acked loss on a hard kill), 'interval' acks from the OS "
        "buffer and fsyncs on a cadence (default; loss bounded by the "
        "flush interval), 'none' leaves syncing to segment rotation "
        "(loss bounded by the open segment)",
    )
    p.add_argument(
        "--scrub-interval",
        type=float,
        default=0.0,
        help="background fileset scrub cadence in seconds (0 disables): "
        "digest-verifies sealed volumes and quarantines corruption "
        "(storage/repair.py Scrubber); counts ride "
        "m3tpu_storage_corruption_total",
    )
    p.add_argument(
        "--scrub-bytes-per-sec",
        type=int,
        default=32 * 1024 * 1024,
        help="scrub read-rate bound in bytes/sec (0 = unpaced)",
    )
    p.add_argument(
        "--scrub-iops",
        type=int,
        default=0,
        help="scrub file-open rate bound in opens/sec (0 = unpaced); "
        "paces alongside --scrub-bytes-per-sec — whichever budget is "
        "further behind wins, so many tiny filesets can't dodge pacing",
    )
    p.add_argument(
        "--quarantine-retention-secs",
        type=float,
        default=0.0,
        help="prune quarantined fileset volumes older than this many "
        "seconds at the end of each scrub pass (0 = keep forever); "
        "prunes count m3tpu_storage_quarantine_pruned_total and drop "
        "the quarantine gauge",
    )
    p.add_argument(
        "--selfmon-interval",
        type=float,
        default=0.0,
        help="self-scrape interval in seconds (0 disables): this node's "
        "metrics registry is stored as series in its own reserved _m3tpu "
        "namespace through the normal write path (m3_tpu/selfmon/)",
    )
    p.add_argument(
        "--selfmon-retention-secs",
        type=int,
        default=24 * 3600,
        help="retention of the reserved self-monitoring namespace",
    )
    p.add_argument(
        "--profile-hz",
        type=float,
        default=None,
        help="wall-clock stack-sampler rate (m3_tpu/profiling/): the "
        "always-on continuous profiler served on the `profile` debug op; "
        "default M3_TPU_PROFILE_HZ (19), 0 disables",
    )
    p.add_argument(
        "--kv-endpoint",
        default="",
        help="host:port of the control-plane KV server; enables dynamic "
        "topology: the node advertises itself, heartbeats, watches its "
        "placement, and peers-bootstraps gained shards",
    )
    p.add_argument("--heartbeat-timeout", type=float, default=10.0)
    p.add_argument(
        "--no-migration",
        action="store_true",
        help="disable warm residency migration on shard handoff (gained "
        "shards then rebuild purely from the decoded peers stream)",
    )
    p.add_argument(
        "--migration-chunk-bytes",
        type=int,
        default=1 << 20,
        help="byte-range size of one resumable migrate_fetch chunk",
    )
    p.add_argument(
        "--migration-chunk-timeout",
        type=float,
        default=5.0,
        help="per-chunk deadline of migration fetches; a dead source "
        "costs at most this long before the next replica resumes",
    )
    p.add_argument(
        "--max-inflight",
        type=int,
        default=0,
        help="load-shedding cap on concurrent in-flight RPCs (0 = uncapped; "
        "past the cap requests fast-fail with a typed retryable "
        "unavailable error instead of queueing into collapse); also "
        "settable via M3_TPU_RPC_MAX_INFLIGHT",
    )
    # embedded seed control plane (server.go:266-324 embedded etcd role):
    # this node ALSO runs a raft KV replica; N seed nodes form the quorum
    p.add_argument("--embed-kv", action="store_true",
                   help="run an embedded raft KV replica in this process")
    p.add_argument("--embed-kv-port", type=int, default=0)
    p.add_argument("--kv-node-id", default="",
                   help="raft member id (default: kv-<node-id>)")
    p.add_argument("--kv-members", default="",
                   help="full member map id=host:port,... (else the fixture "
                   "or operator sends raft_configure to each seed)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # embedded seed KV replica (server.go:266-324): starts SERVING first —
    # the quorum only forms once a majority of seeds are up, so everything
    # that needs the control plane is deferred until a leader exists
    kv_server = None
    kv_raft = None
    if args.embed_kv:
        import os as _os

        from ..cluster.kv import KVStore
        from ..cluster.raft import RaftKVService, RaftNode
        from ..net.server import RpcServer

        kv_raft = RaftNode(
            args.kv_node_id or f"kv-{args.node_id}",
            KVStore(),
            data_dir=_os.path.join(args.base_dir, "kv"),
        )
        kv_server = RpcServer(
            RaftKVService(kv_raft), port=args.embed_kv_port, component="kv"
        )
        kv_server.start()
        self_kv_ep = f"{kv_server.host}:{kv_server.port}"
        print(f"KV_LISTENING {kv_server.host} {kv_server.port}", flush=True)
        if args.kv_members:
            members = dict(kv.split("=", 1) for kv in args.kv_members.split(","))
            kv_raft.configure(members, self_endpoint=self_kv_ep)
        elif kv_raft.members:
            # RESTART of a configured seed: rejoin the recovered membership
            # immediately so the quorum (and the namespace registry below)
            # is available BEFORE bootstrap
            kv_raft.configure(kv_raft.members, self_endpoint=self_kv_ep)
        if not args.kv_endpoint:
            # the node's own control-plane client talks to its LOCAL seed
            # (leader redirects route writes; watches serve locally)
            args.kv_endpoint = self_kv_ep

    from ..cache import CacheOptions
    from ..index.device import IndexDeviceOptions
    from ..ingest import IngestOptions
    from ..resident import ResidentOptions

    db = Database(
        args.base_dir,
        num_shards=args.num_shards,
        cache_options=CacheOptions(
            enabled=args.cache_bytes > 0, max_bytes=max(args.cache_bytes, 0)
        ),
        resident_options=ResidentOptions(
            enabled=args.resident_bytes > 0,
            max_bytes=max(args.resident_bytes, 0),
            side_bytes=max(args.resident_side_bytes, 0),
        ),
        index_device_options=IndexDeviceOptions(
            enabled=args.index_device_bytes > 0,
            max_bytes=max(args.index_device_bytes, 0),
        ),
        ingest_options=(
            IngestOptions(lanes=args.ingest_lanes, slots=args.ingest_slots,
                          sync_batch=args.ingest_sync_batch)
            if args.device_ingest
            else None
        ),
        commitlog_sync=args.commitlog_sync,
    )
    opts = NamespaceOptions(
        retention_nanos=args.retention_secs * NANOS,
        block_size_nanos=args.block_size_secs * NANOS,
        cold_writes_enabled=not args.no_cold_writes,
    )
    for ns in args.namespace or ["default"]:
        db.create_namespace(ns, opts)
    if args.selfmon_interval > 0:
        # created BEFORE bootstrap so stored self telemetry recovers across
        # restarts like any namespace
        from ..selfmon import RESERVED_NS

        db.create_namespace(
            RESERVED_NS,
            NamespaceOptions(
                retention_nanos=args.selfmon_retention_secs * NANOS,
                block_size_nanos=min(
                    args.block_size_secs, 3600
                ) * NANOS,
            ),
        )

    # dynamic namespaces (namespace/dynamic.go): the control-plane registry
    # is applied BEFORE bootstrap so registered namespaces recover their
    # data, and watched after so admin-created namespaces appear live.
    # EMBEDDED-SEED mode defers ALL control-plane wiring until the quorum
    # has a leader (the quorum can't form until a majority of seed
    # processes are up) — registry namespaces then appear via the watch.
    kv = None
    ns_registry = None
    state: dict = {"cluster_db": None, "hb_stop": None}

    def _apply_registry(reg: dict) -> None:
        for name, rec in reg.items():
            if name in db.namespaces:
                continue
            db.create_namespace(
                name,
                NamespaceOptions(
                    retention_nanos=int(rec["retention_nanos"]),
                    block_size_nanos=int(rec["block_size_nanos"]),
                    cold_writes_enabled=bool(
                        rec.get("cold_writes_enabled", True)
                    ),
                ),
            )

    if args.kv_endpoint and not args.embed_kv:
        from ..cluster.kv_service import RemoteKVStore
        from ..cluster.namespaces import NamespaceRegistry

        kv = RemoteKVStore.connect(args.kv_endpoint)
        ns_registry = NamespaceRegistry(kv)
        _apply_registry(ns_registry.get_all())
    elif args.embed_kv and kv_raft.members:
        # a RECONFIGURED seed (restart or --kv-members): wait for the
        # quorum and apply the registry BEFORE bootstrap, so
        # registry-created namespaces recover their persisted data —
        # create_namespace after bootstrap would leave them empty
        import time as _t

        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline and kv_raft.leader_id is None:
            _t.sleep(0.05)
        if kv_raft.leader_id is not None:
            from ..cluster.kv_service import RemoteKVStore
            from ..cluster.namespaces import NamespaceRegistry

            kv = RemoteKVStore.connect(args.kv_endpoint)
            ns_registry = NamespaceRegistry(kv)
            try:
                _apply_registry(ns_registry.get_all())
            except Exception as exc:
                print(f"WARN registry fetch at bootstrap failed: {exc}", flush=True)

    if not args.no_bootstrap:
        db.bootstrap()

    mediator = None
    if not args.no_mediator:
        mediator = Mediator(db, MediatorOptions())
        mediator.start()

    scrubber = None
    if args.scrub_interval > 0:
        from ..storage.repair import Scrubber

        scrubber = Scrubber(
            db,
            interval=args.scrub_interval,
            bytes_per_sec=args.scrub_bytes_per_sec,
            iops=args.scrub_iops,
            quarantine_retention_secs=args.quarantine_retention_secs,
            phase_key=args.node_id,
        )
        scrubber.start()

    shards = {int(s) for s in args.shards.split(",") if s.strip()}
    service = NodeService(db, node_id=args.node_id, assigned_shards=shards)
    server = NodeServer(
        service, host=args.host, port=args.port,
        max_inflight=args.max_inflight or None,
    )

    selfmon = None
    if args.selfmon_interval > 0:
        from ..selfmon import RESERVED_NS, DatabaseSink, SelfMonCollector

        selfmon = SelfMonCollector(
            DatabaseSink(db, RESERVED_NS),
            interval=args.selfmon_interval,
            instance=args.node_id,
            component="dbnode",
        ).start()

    # always-on continuous profiler (m3_tpu/profiling/): folded stacks on
    # the `profile` op, device-memory split gauges refreshed on its
    # schedule; m3tpu_profile_* health rides the selfmon pipeline above
    from ..profiling import start_sampler

    profiler = start_sampler(hz=args.profile_hz, instance=args.node_id, db=db)

    def wire_control_plane() -> None:
        """Dynamic topology via the networked control plane (server.go:
        embedded etcd + topology watch + KV runtime reconfig)."""
        nonlocal kv, ns_registry
        import threading

        from ..cluster.placement import PlacementService
        from ..cluster.services import ServiceInstance, Services
        from ..storage.cluster_db import ClusterDatabase
        from ..storage.runtime import RuntimeOptionsManager

        if kv is None:
            from ..cluster.kv_service import RemoteKVStore
            from ..cluster.namespaces import NamespaceRegistry

            kv = RemoteKVStore.connect(args.kv_endpoint)
            ns_registry = NamespaceRegistry(kv)
            _apply_registry(ns_registry.get_all())

        # live namespace adds (bootstrap already applied the current set)
        ns_registry.watch(_apply_registry)

        # KV-watched runtime knobs (server.go:1007-1268 runtime reconfig)
        runtime_mgr = RuntimeOptionsManager(kv)
        runtime_mgr.watch(db.apply_runtime_options)

        services = Services(kv, heartbeat_timeout=args.heartbeat_timeout)
        endpoint = f"{server.host}:{server.port}"
        services.advertise("m3db", ServiceInstance(args.node_id, endpoint))
        hb_stop = state["hb_stop"] = threading.Event()

        from ..utils.instrument import DEFAULT as METRICS

        hb_errors = METRICS.counter(
            "heartbeat_errors_total",
            "control-plane heartbeats swallowed by KV hiccups (a "
            "persistently failing loop means this node looks dead to the "
            "failure detector)",
        )

        def hb_loop() -> None:
            interval = max(args.heartbeat_timeout / 3.0, 0.05)
            while not hb_stop.wait(interval):
                try:
                    services.heartbeat("m3db", args.node_id)
                except Exception:
                    # KV hiccups must not kill the node — but count every
                    # swallow so /metrics shows a heartbeat loop that is
                    # failing persistently (M3L007)
                    hb_errors.inc()

        threading.Thread(target=hb_loop, daemon=True, name="heartbeat").start()
        cluster_db = state["cluster_db"] = ClusterDatabase(
            db, args.node_id, PlacementService(kv), node_service=service,
            migration_enabled=not args.no_migration,
            migration_chunk_bytes=args.migration_chunk_bytes,
            migration_chunk_timeout=args.migration_chunk_timeout,
        )
        cluster_db.start()

    if args.kv_endpoint and not args.embed_kv:
        wire_control_plane()
    elif args.embed_kv:
        import threading as _threading
        import time as _time

        def _wire_when_quorum() -> None:
            deadline = _time.monotonic() + 300
            while _time.monotonic() < deadline:
                st = kv_raft.status()
                if st["leader"] is not None and st["members"]:
                    break
                _time.sleep(0.1)
            try:
                wire_control_plane()
            except Exception as exc:  # control plane down: node still serves
                print(f"WARN embedded control-plane wiring failed: {exc}",
                      flush=True)

        _threading.Thread(
            target=_wire_when_quorum, daemon=True, name="kv-seed-wire"
        ).start()

    def shutdown(signum, frame):
        # SystemExit propagates out of serve_forever's select loop; the
        # finally block below closes the database cleanly
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    print(f"LISTENING {server.host} {server.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        if profiler is not None:
            profiler.stop()
        if selfmon is not None:
            selfmon.stop()
        if state["hb_stop"] is not None:
            state["hb_stop"].set()
        if state["cluster_db"] is not None:
            state["cluster_db"].stop()
        if kv is not None:
            kv.close()
        if kv_raft is not None:
            kv_raft.stop()
        if kv_server is not None:
            kv_server.stop()
        if scrubber is not None:
            scrubber.stop()
        if mediator is not None:
            mediator.stop()
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
