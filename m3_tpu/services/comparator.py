"""Query-correctness comparator: a deterministic synthetic storage + HTTP
service for validating PromQL semantics against an independent oracle.

Reference: /root/reference/src/cmd/services/m3comparator/main/querier.go —
a service implementing the query storage API over reproducible synthetic
data so query engines can be diff'd result-for-result. Here
``SyntheticStorage`` plugs straight into the PromQL Engine (the role the
querier's gRPC surface plays for m3query), every series is a pure function
of (id hash, timestamp) so ANY implementation can regenerate the identical
datapoints, and ``compare_range`` diffs engine output against a
numpy-computed expectation.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

import numpy as np

from ..utils.hash import murmur3_32

NANOS = 1_000_000_000


def _series_seed(tags: tuple) -> int:
    blob = b";".join(b"=".join(kv) for kv in sorted(tags))
    return murmur3_32(blob)


def synthetic_value(seed: int, t_nanos: int) -> float:
    """The deterministic value function: ramp + sinusoid, parameters from
    the seed. Pure in (seed, t) — the comparator contract."""
    t = t_nanos / NANOS
    slope = 0.5 + (seed % 97) / 19.0
    amp = 10.0 + (seed % 31)
    period = 120.0 + (seed % 241)
    phase = (seed % 628) / 100.0
    return slope * (t % 86_400) + amp * math.sin(2 * math.pi * t / period + phase)


@dataclass
class SyntheticStorage:
    """Engine-compatible storage over generated series.

    ``num_series`` series named ``metric`` with host/job tags; samples on a
    fixed ``step`` grid, values from synthetic_value. Matchers support =,
    !=, =~, !~ over the generated tag sets (querier.go's matcher handling).
    """

    metric: str = "synthetic_metric"
    num_series: int = 10
    step_nanos: int = 10 * NANOS
    extra_metrics: tuple = ()
    series_tags: list = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.series_tags:
            names = (self.metric,) + tuple(self.extra_metrics)
            for name in names:
                for i in range(self.num_series):
                    self.series_tags.append(
                        (
                            (b"__name__", name.encode()),
                            (b"host", b"host-%02d" % i),
                            (b"job", b"job-%d" % (i % 3)),
                        )
                    )

    @staticmethod
    def _match(tags: tuple, matchers) -> bool:
        tag_map = {k.decode(): v.decode() for k, v in tags}
        for m in matchers:
            val = tag_map.get(m.name, "")
            if m.op == "=":
                ok = val == m.value
            elif m.op == "!=":
                ok = val != m.value
            elif m.op == "=~":
                ok = re.fullmatch(m.value, val) is not None
            elif m.op == "!~":
                ok = re.fullmatch(m.value, val) is None
            else:
                raise ValueError(f"bad matcher op {m.op}")
            if not ok:
                return False
        return True

    def samples(self, tags: tuple, start_nanos: int, end_nanos: int):
        seed = _series_seed(tags)
        first = -(-start_nanos // self.step_nanos) * self.step_nanos
        times = np.arange(first, end_nanos, self.step_nanos, dtype=np.int64)
        vals = np.asarray([synthetic_value(seed, int(t)) for t in times], np.float64)
        return times, vals

    def fetch(self, matchers, start_nanos, end_nanos):
        out = []
        for tags in self.series_tags:
            if self._match(tags, matchers):
                times, vals = self.samples(tags, start_nanos, end_nanos)
                out.append((tags, times, vals))
        return out


def make_engine(storage: SyntheticStorage | None = None):
    from ..query.engine import Engine

    return Engine(storage or SyntheticStorage())


def serve(storage: SyntheticStorage | None = None, host: str = "127.0.0.1", port: int = 0):
    """HTTP comparator service: the PromQL query API over synthetic data
    (the m3comparator process role). Returns (server, port)."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qs, urlparse

    from .coordinator import _prom_matrix, _prom_vector

    engine = make_engine(storage)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_GET(self):
            url = urlparse(self.path)
            q = parse_qs(url.query)
            try:
                if url.path == "/health":
                    body = {"ok": True}
                elif url.path == "/api/v1/query_range":
                    start = float(q["start"][0])
                    end = float(q["end"][0])
                    step = float(q.get("step", ["10"])[0])
                    r = engine.query_range(
                        q["query"][0], int(start * NANOS), int(end * NANOS),
                        int(step * NANOS),
                    )
                    body = _prom_matrix(r, int(start * NANOS), int(step * NANOS))
                elif url.path == "/api/v1/query":
                    t = float(q["time"][0])
                    body = _prom_vector(engine.query_instant(q["query"][0], int(t * NANOS)), t)
                else:
                    self._reply(404, {"error": "not found"})
                    return
                self._reply(200, body)
            except Exception as exc:
                self._reply(400, {"status": "error", "error": str(exc)})

        def _reply(self, code, obj):
            payload = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    srv = ThreadingHTTPServer((host, port), Handler)
    import threading

    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


# --- the comparison harness (the "compare engines" purpose) ---


def compare_range(
    engine_result,
    expected: dict,
    rtol: float = 1e-9,
) -> list[str]:
    """Diff an Engine query_range Result against expected {frozenset(tags):
    np.ndarray} values (NaN = absent). Returns human-readable mismatches
    (empty = match)."""
    problems = []
    got = {}
    for row, meta in zip(engine_result.values, engine_result.metas):
        key = frozenset((k.decode(), v.decode()) for k, v in meta.tags)
        got[key] = np.asarray(row, np.float64)
    for key in set(got) | set(expected):
        if key not in got:
            problems.append(f"missing series {sorted(key)}")
            continue
        if key not in expected:
            problems.append(f"unexpected series {sorted(key)}")
            continue
        g, e = got[key], np.asarray(expected[key], np.float64)
        if g.shape != e.shape:
            problems.append(f"shape {g.shape} != {e.shape} for {sorted(key)}")
            continue
        both = ~(np.isnan(g) & np.isnan(e))
        if not np.allclose(g[both], e[both], rtol=rtol, equal_nan=True):
            bad = np.nonzero(~np.isclose(g, e, rtol=rtol, equal_nan=True))[0]
            problems.append(
                f"values differ at steps {bad[:5].tolist()} for {sorted(key)}: "
                f"got {g[bad[:3]].tolist()} want {e[bad[:3]].tolist()}"
            )
    return problems
