"""r2ctl: standalone rule-management service (+ minimal operational UI).

Reference: /root/reference/src/ctl/ — the r2 REST service
(ctl/service/r2/, routes over namespaces + mapping/rollup rules) behind
the r2ctl UI. Here the same CRUD rides the framework's KV-backed RuleStore
(rules/r2.py) against a kvnode (or quorum) endpoint, so edits propagate to
every matcher watcher cluster-wide; "/" serves a small HTML view of every
namespace's ruleset (the operational-UI role — rule browsing without
tooling). Run:

    python -m m3_tpu.services.r2ctl --kv-endpoint 127.0.0.1:2379 --port 7201

Endpoints:
    GET    /                      HTML ruleset browser
    GET    /health
    GET    /api/v1/rules          all namespaces + rulesets
    GET    /api/v1/rules/{ns}     one ruleset
    POST   /api/v1/rules/{ns}     replace ruleset (JSON, bumps version)
    DELETE /api/v1/rules/{ns}     drop ruleset
"""

from __future__ import annotations

import argparse
import html
import json
import re
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..rules.r2 import RuleStore, listing_dict, ruleset_from_dict, ruleset_to_dict


def make_server(kv, host: str = "127.0.0.1", port: int = 0) -> ThreadingHTTPServer:
    store = RuleStore(kv)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code: int = 200) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _html(self, body: str, code: int = 200) -> None:
            raw = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length", "0"))
            return self.rfile.read(n)

        def do_GET(self):
            try:
                if self.path == "/health":
                    self._json({"ok": True, "role": "r2ctl"})
                elif self.path == "/":
                    self._html(_render_index(store))
                elif self.path == "/api/v1/rules":
                    self._json(listing_dict(store))
                elif (m := re.match(r"^/api/v1/rules/([^/]+)$", self.path)):
                    rs = store.get(m.group(1))
                    if rs is None:
                        self._json({"error": "not found"}, 404)
                    else:
                        self._json(ruleset_to_dict(rs))
                else:
                    self._json({"error": "not found"}, 404)
            except Exception as exc:
                self._json({"error": str(exc)}, 500)

        def do_POST(self):
            try:
                if (m := re.match(r"^/api/v1/rules/([^/]+)$", self.path)):
                    rs = ruleset_from_dict(json.loads(self._body()))
                    store.set(m.group(1), rs)
                    self._json({"namespace": m.group(1), "version": rs.version})
                else:
                    self._json({"error": "not found"}, 404)
            except Exception as exc:
                self._json({"error": str(exc)}, 400)

        def do_DELETE(self):
            try:
                if (m := re.match(r"^/api/v1/rules/([^/]+)$", self.path)):
                    if store.delete(m.group(1)):
                        self._json({"deleted": m.group(1)})
                    else:
                        self._json({"error": "not found"}, 404)
                else:
                    self._json({"error": "not found"}, 404)
            except Exception as exc:
                self._json({"error": str(exc)}, 400)

    return ThreadingHTTPServer((host, port), Handler)


def _render_index(store: RuleStore) -> str:
    rows = []
    for ns in store.namespaces():
        rs = store.get(ns)
        if rs is None:
            continue
        d = ruleset_to_dict(rs)
        rules = []
        for r in d.get("mappingRules", []):
            target = "drop" if r.get("drop") else ", ".join(r["policies"])
            rules.append(
                f"<li><b>map</b> {html.escape(r['name'])} — filter "
                f"<code>{html.escape(r['filter'])}</code> → {html.escape(target)}</li>"
            )
        for r in d.get("rollupRules", []):
            tgt = "; ".join(
                html.escape(t.get("newName", "")) for t in r.get("targets", [])
            )
            rules.append(
                f"<li><b>rollup</b> {html.escape(r['name'])} — filter "
                f"<code>{html.escape(r['filter'])}</code> → {tgt}</li>"
            )
        rows.append(
            f"<h2>{html.escape(ns)} <small>v{d.get('version')}</small></h2>"
            f"<ul>{''.join(rules) or '<li><i>no rules</i></li>'}</ul>"
        )
    return (
        "<!doctype html><title>r2ctl — rulesets</title>"
        "<h1>r2ctl: metric rulesets</h1>"
        + ("".join(rows) or "<p><i>no namespaces</i></p>")
        + "<p>API: GET/POST/DELETE /api/v1/rules/{namespace}</p>"
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="m3tpu-r2ctl", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--kv-endpoint", required=True,
                   help="kvnode host:port (or comma-separated quorum)")
    args = p.parse_args(argv)

    from ..cluster.kv_service import RemoteKVStore

    kv = RemoteKVStore.connect(args.kv_endpoint)
    server = make_server(kv, host=args.host, port=args.port)

    def shutdown(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    host, port = server.server_address
    print(f"LISTENING {host} {port}", flush=True)
    server.serve_forever()
    kv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
