"""InfluxDB line-protocol ingest (coordinator write path).

Reference: /root/reference/src/query/api/v1/handler/influxdb/write.go —
the coordinator accepts InfluxDB line protocol and maps each numeric field
to one tagged series: measurement + '_' + field key becomes __name__
(naming mirrors the reference's default promrewriter behavior), line tags
become label pairs. Integer fields carry a trailing 'i'; string and boolean
fields are droppable per the reference (only numeric values are storable).

Line protocol:  measurement[,tag=val...] field=value[,field2=value2] [ts]
with '\\ ', '\\,', '\\=' escapes in identifiers and double-quoted string
field values.
"""

from __future__ import annotations

import math

PRECISION_NANOS = {
    "ns": 1,
    "u": 1_000,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3_600 * 1_000_000_000,
}


class LineProtocolError(ValueError):
    pass


def _split_unescaped(s: str, sep: str) -> list[str]:
    """Split on sep outside escapes and double quotes."""
    out, cur, esc, quoted = [], [], False, False
    for ch in s:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            cur.append(ch)
            esc = True
        elif ch == '"':
            quoted = not quoted
            cur.append(ch)
        elif ch == sep and not quoted:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _unescape(s: str) -> str:
    out, esc = [], False
    for ch in s:
        if esc:
            out.append(ch)
            esc = False
        elif ch == "\\":
            esc = True
        else:
            out.append(ch)
    if esc:
        out.append("\\")
    return "".join(out)


def parse_line(line: str):
    """One line → (measurement, tags dict, fields dict, timestamp|None).

    Numeric fields come back as float; string/bool fields are returned too
    (callers decide what to drop). Raises LineProtocolError on bad syntax.
    """
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    parts = _split_unescaped(line, " ")
    parts = [p for p in parts if p != ""]
    if len(parts) < 2 or len(parts) > 3:
        raise LineProtocolError(f"expected 2-3 space-separated sections: {line!r}")
    head, field_part = parts[0], parts[1]
    ts = None
    if len(parts) == 3:
        try:
            ts = int(parts[2])
        except ValueError:
            raise LineProtocolError(f"bad timestamp: {parts[2]!r}")

    head_parts = _split_unescaped(head, ",")
    measurement = _unescape(head_parts[0])
    if not measurement:
        raise LineProtocolError("empty measurement")
    tags: dict[str, str] = {}
    for tp in head_parts[1:]:
        kv = _split_unescaped(tp, "=")
        if len(kv) != 2:
            raise LineProtocolError(f"bad tag: {tp!r}")
        tags[_unescape(kv[0])] = _unescape(kv[1])

    fields: dict[str, object] = {}
    for fp in _split_unescaped(field_part, ","):
        kv = _split_unescaped(fp, "=")
        if len(kv) != 2:
            raise LineProtocolError(f"bad field: {fp!r}")
        key = _unescape(kv[0])
        raw = kv[1]
        if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
            fields[key] = _unescape(raw[1:-1])
        elif raw in ("t", "T", "true", "True", "TRUE"):
            fields[key] = True
        elif raw in ("f", "F", "false", "False", "FALSE"):
            fields[key] = False
        elif raw.endswith(("i", "u")) and _is_int(raw[:-1]):
            try:
                fields[key] = float(int(raw[:-1]))
            except OverflowError:
                raise LineProtocolError(f"integer field overflows: {raw!r}")
        else:
            try:
                val = float(raw)
            except ValueError:
                raise LineProtocolError(f"bad field value: {raw!r}")
            if not math.isfinite(val):
                # line protocol has no literal for nan/inf; '1e999' etc.
                # overflow to inf and must be rejected, not ingested
                raise LineProtocolError(f"non-finite field value: {raw!r}")
            fields[key] = val
    if not fields:
        raise LineProtocolError("no fields")
    return measurement, tags, fields, ts


def _is_int(s: str) -> bool:
    if s.startswith(("-", "+")):
        s = s[1:]
    return s.isdigit() and bool(s)


def parse_body(body: str, precision: str = "ns", now_nanos: int | None = None):
    """Parse a write body → list of (name, tags, t_nanos, value) datapoints.

    Non-numeric fields are dropped (reference behavior); each numeric field
    yields one datapoint named measurement_field.
    """
    mult = PRECISION_NANOS.get(precision)
    if mult is None:
        raise LineProtocolError(f"bad precision {precision!r}")
    out = []
    for line in body.splitlines():
        parsed = parse_line(line)
        if parsed is None:
            continue
        measurement, tags, fields, ts = parsed
        if ts is None:
            if now_nanos is None:
                import time

                now_nanos = time.time_ns()
            t_nanos = now_nanos
        else:
            t_nanos = ts * mult
        for key, val in fields.items():
            if isinstance(val, bool) or not isinstance(val, float):
                continue
            name = f"{measurement}_{key}" if key != "value" else measurement
            out.append((name, tags, t_nanos, val))
    return out
