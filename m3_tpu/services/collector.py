"""Collector agent: JSON report API → aggregator client.

Reference: /root/reference/src/collector/ — a collection agent applications
report metrics to (reporter/m3aggregator/reporter.go): it matches each
metric against the rule matcher and ships the results to the aggregation
tier. Here: a small HTTP service accepting JSON counter/gauge/timer
reports, running them through an optional rules matcher for storage
policies, and forwarding over the aggregator's rawtcp-role socket protocol
(aggregator/server.AggregatorClient).

Report body (POST /report)::

    {"metrics": [
      {"type": "counter", "id": "requests", "value": 3},
      {"type": "gauge",   "id": "temp", "value": 21.5},
      {"type": "timer",   "id": "latency", "values": [0.1, 0.2]}
    ]}
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..aggregator.server import AggregatorClient
from ..metrics.encoding import UnaggregatedMessage
from ..metrics.types import MetricType, Untimed
from ..rules.rules import decode_tags_id, encode_tags_id

_TYPES = {
    "counter": MetricType.COUNTER,
    "gauge": MetricType.GAUGE,
    "timer": MetricType.TIMER,
}


class Collector:
    """Parses reports and forwards them (reporter.go ReportCounter/
    ReportGauge/ReportBatchTimer)."""

    def __init__(self, client: AggregatorClient, matcher=None,
                 match_namespace: str = "default") -> None:
        self.client = client
        self.matcher = matcher  # optional rules/matcher.Matcher
        self.match_namespace = match_namespace
        self.reported = 0
        self.dropped = 0

    def report(self, metrics: list[dict], now_nanos: int | None = None) -> int:
        now = now_nanos if now_nanos is not None else time.time_ns()
        sent = 0
        for m in metrics:
            mtype = _TYPES.get(m.get("type", ""))
            if mtype is None:
                raise ValueError(f"bad metric type {m.get('type')!r}")
            mid = m["id"].encode() if isinstance(m["id"], str) else bytes(m["id"])
            tags = m.get("tags")
            if tags:
                mid = encode_tags_id(
                    tuple(
                        (k.encode(), v.encode()) for k, v in sorted(tags.items())
                    )
                    + ((b"__name__", mid),)
                )
            if mtype == MetricType.COUNTER:
                metric = Untimed(id=mid, type=mtype, counter_value=int(m["value"]))
            elif mtype == MetricType.GAUGE:
                metric = Untimed(id=mid, type=mtype, gauge_value=float(m["value"]))
            else:
                metric = Untimed(
                    id=mid, type=mtype,
                    batch_timer_values=tuple(float(v) for v in m["values"]),
                )
            policies = ()
            if self.matcher is not None:
                try:
                    tag_pairs = decode_tags_id(mid)
                except Exception:
                    tag_pairs = ((b"__name__", mid),)
                result = self.matcher.match(self.match_namespace, tag_pairs, now)
                if result.drop:
                    self.dropped += 1
                    continue
                policies = result.policies
            self.client.send(
                UnaggregatedMessage(metric, now, policies=policies)
            )
            sent += 1
        self.reported += sent
        return sent


def serve(collector: Collector, host: str = "127.0.0.1", port: int = 0):
    """HTTP report endpoint (collector's JSON report API role)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            try:
                if self.path != "/report":
                    self._reply(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n))
                sent = collector.report(body.get("metrics", []))
                self._reply(200, {"sent": sent})
            except Exception as exc:
                self._reply(400, {"error": str(exc)})

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"ok": True, "reported": collector.reported})
            else:
                self._reply(404, {"error": "not found"})

        def _reply(self, code, obj):
            payload = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]
