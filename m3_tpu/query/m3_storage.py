"""Storage adapters: matchers → index query → decoded series; fanout.

Reference: /root/reference/src/query/storage/m3/storage.go:182
(FetchCompressed: resolve namespaces, FetchTagged, wrap into blocks) and
src/query/storage/fanout/storage.go:48-156 (merge across clusters by
retention/resolution attributes).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..index.query import AllQuery, conj, neg, regexp, term
from ..storage.database import Database
from .promql import Matcher


def matchers_to_index_query(matchers: list[Matcher]):
    """models.Matchers → idx.Query (storage/index/convert)."""
    qs = []
    for m in matchers:
        name = m.name.encode()
        value = m.value.encode()
        if m.op == "=":
            qs.append(term(name, value))
        elif m.op == "!=":
            qs.append(neg(term(name, value)))
        elif m.op == "=~":
            qs.append(regexp(name, value))
        elif m.op == "!~":
            qs.append(neg(regexp(name, value)))
        else:
            raise ValueError(f"bad matcher op {m.op}")
    if not qs:
        return AllQuery()
    if len(qs) == 1:
        return qs[0]
    return conj(*qs)


@dataclass
class M3Storage:
    """Engine Storage over one Database namespace."""

    db: Database
    namespace: str

    def fetch(self, matchers, start_nanos, end_nanos):
        from . import stats

        q = matchers_to_index_query(matchers)
        out = []
        total_bytes = 0
        # per-query cache accounting from the node-wide cache counter delta —
        # approximate under concurrent queries (deltas interleave), exact in
        # the common single-query case; the alternative (threading a stats
        # handle through every Shard read) isn't worth the hot-path cost
        cache = getattr(self.db, "block_cache", None)
        before = cache.stats() if cache is not None else None
        # array surface: decoded arrays come straight from the decoded-block
        # cache (m3_tpu/cache/) on repeat queries — no per-point Datapoint
        # materialization on the scan-and-aggregate hot path
        for sid, tags, (times, vals) in self.db.fetch_tagged_arrays(
            self.namespace, q, start_nanos, end_nanos
        ):
            times = np.asarray(times, np.int64)
            vals = np.asarray(vals, np.float64)
            total_bytes += times.nbytes + vals.nbytes
            out.append((tags, times, vals))
        if before is not None:
            after = cache.stats()
            stats.add(
                bytes_=total_bytes,
                cache_hits=after["hits"] - before["hits"],
                cache_misses=after["misses"] - before["misses"],
            )
        else:
            stats.add(bytes_=total_bytes)
        return out


@dataclass
class ClusterNamespace:
    """One queryable namespace + its retention/resolution attributes
    (storage/m3/types.go ClusterNamespace + Attributes)."""

    storage: object  # Engine Storage (e.g. M3Storage)
    retention_nanos: int
    resolution_nanos: int = 0  # 0 = raw samples
    aggregated: bool = False  # False = the unaggregated namespace


def resolve_cluster_namespaces(
    namespaces: list[ClusterNamespace], now_nanos: int, start_nanos: int
) -> list[ClusterNamespace]:
    """storage/m3/cluster_resolver.go resolveClusterNamespacesForQuery:

    1. the unaggregated namespace wins if its retention covers the query
       start;
    2. otherwise the FINEST-resolution aggregated namespace that covers it;
    3. otherwise nothing covers — fall back to the longest-retention
       namespace (partial data beats none).
    """
    if not namespaces:
        return []
    covers = lambda ns: now_nanos - ns.retention_nanos <= start_nanos
    unagg = [ns for ns in namespaces if not ns.aggregated]
    if unagg and covers(unagg[0]):
        return [unagg[0]]
    covering = sorted(
        (ns for ns in namespaces if ns.aggregated and covers(ns)),
        key=lambda ns: ns.resolution_nanos,
    )
    if covering:
        return [covering[0]]
    return [max(namespaces, key=lambda ns: ns.retention_nanos)]


@dataclass
class FanoutStorage:
    """Retention/resolution-aware fanout (fanout/storage.go:48 +
    cluster_resolver): pick the namespace(s) whose attributes fit the query
    range, fetch, and dedupe exact-id overlaps preferring the
    finer-resolution source."""

    namespaces: list  # list[ClusterNamespace]
    clock: object = None  # () -> nanos; injectable for tests

    def _now(self) -> int:
        if self.clock is not None:
            return self.clock()
        import time

        return time.time_ns()

    def resolve(self, start_nanos: int) -> list[ClusterNamespace]:
        return resolve_cluster_namespaces(self.namespaces, self._now(), start_nanos)

    def fetch(self, matchers, start_nanos, end_nanos):
        seen: dict = {}
        order = []
        for ns in self.resolve(start_nanos):
            for tags, times, vals in ns.storage.fetch(matchers, start_nanos, end_nanos):
                if tags in seen:
                    continue
                seen[tags] = (tags, times, vals)
                order.append(tags)
        return [seen[t] for t in order]
