"""Storage adapters: matchers → index query → decoded series; fanout.

Reference: /root/reference/src/query/storage/m3/storage.go:182
(FetchCompressed: resolve namespaces, FetchTagged, wrap into blocks) and
src/query/storage/fanout/storage.go:48-156 (merge across clusters by
retention/resolution attributes).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..index.query import AllQuery, conj, neg, regexp, term
from ..storage.database import Database
from ..utils.instrument import DEFAULT as METRICS
from .promql import Matcher

# read-through re-admission is opportunistic: the streamed result is
# already in hand when it runs, so an admission failure (device OOM near
# the pool budget, fileset torn down underfoot) must never fail the query
_M_READMIT_FAILURES = METRICS.counter(
    "resident_readmission_failures_total",
    "read-through re-admissions that failed (query still served by the "
    "streamed result already computed)",
)


def matchers_to_index_query(matchers: list[Matcher]):
    """models.Matchers → idx.Query (storage/index/convert)."""
    qs = []
    for m in matchers:
        name = m.name.encode()
        value = m.value.encode()
        if m.op == "=":
            qs.append(term(name, value))
        elif m.op == "!=":
            qs.append(neg(term(name, value)))
        elif m.op == "=~":
            qs.append(regexp(name, value))
        elif m.op == "!~":
            qs.append(neg(regexp(name, value)))
        else:
            raise ValueError(f"bad matcher op {m.op}")
    if not qs:
        return AllQuery()
    if len(qs) == 1:
        return qs[0]
    return conj(*qs)


class _EmptyTotals:
    """ScanAggregates stand-in for a scan that matched no lanes."""

    total_sum = 0.0
    total_count = 0
    total_min = float("nan")
    total_max = float("nan")


_EMPTY_TOTALS = _EmptyTotals()


@dataclass
class M3Storage:
    """Engine Storage over one Database namespace."""

    db: Database
    namespace: str

    @property
    def planner(self):
        """Lazy device query planner (query/plan.py) — one per adapter,
        owning the LRU plan cache for this namespace."""
        p = self.__dict__.get("_planner")
        if p is None:
            from .plan import Planner

            p = self.__dict__["_planner"] = Planner(self.db, self.namespace)
        return p

    def fetch_grid(self, matchers, start_nanos, end_nanos, grid, lookback_nanos):
        """One-dispatch fused fetch+consolidate (query/plan.py): matchers
        resolve, decode, and consolidate onto the engine's step grid
        inside ONE device program; the host only reconstructs f64 values
        (the same finalize arithmetic as the staged path — bit-identical
        results) and attaches tags. Returns a consolidated
        ``(metas, values f64[S, T])`` or None to run the staged path —
        every ineligibility cause lands in EXPLAIN routing.

        ``grid`` is the engine's consolidation timestamp vector (i64
        nanos); ``[start_nanos, end_nanos)`` the raw fetch window
        (lookback included by the caller)."""
        from . import stats
        from .plan import Ineligible

        try:
            matched, values, datapoints, err_rows = self.planner.run(
                matchers, start_nanos, end_nanos, grid, lookback_nanos
            )
        except Ineligible as e:
            stats.add_routing(b"*", None, "staged", f"plan:{e.reason}")
            if e.reason in ("force-staged", "plan-disabled"):
                # deliberate bypasses (the parity probe, the kill
                # switch) are not degradations: they must not pollute
                # the fallback counters an operator alerts on
                return None
            self.planner.fallbacks += 1
            from .plan import _M_FALLBACKS

            _M_FALLBACKS.inc()
            stats.add(plan_fallbacks=1)
            # release plans stamped against state that has since moved
            # (their pinned device tables + index arrays would otherwise
            # linger until LRU displacement)
            self.planner.evict_stale()
            return None
        except Exception:
            # the staged path is always correct: a device-plan fault
            # degrades, loudly, never fails the query
            from .plan import _M_ERRORS, _M_FALLBACKS

            _M_ERRORS.inc()
            _M_FALLBACKS.inc()
            self.planner.fallbacks += 1
            stats.add(plan_fallbacks=1)
            stats.add_routing(b"*", None, "staged", "plan:device-error")
            return None
        matched, metas = matched
        if len(err_rows):
            # lanes the device decoder bailed on (annotated streams):
            # batched host re-read per block, consolidated with the same
            # rule — EXPLAIN shows the hybrid per series
            values = self._stitch_grid_rows(
                matched, err_rows, values, start_nanos, end_nanos, grid,
                lookback_nanos,
            )
        st = stats.current()
        if st is not None and st.record_routing:
            err_set = set(int(i) for i in err_rows)
            for i, doc in enumerate(matched):
                stats.add_routing(
                    doc.id, None, "fused",
                    "annotated-err-lane (host stitch)" if i in err_set
                    else "device-plan",
                )
        nb = int(values.size) * 16  # times+values equivalent of the staged read
        stats.add(resident_hits=1, bytes_=nb, resident_bytes=nb)
        return metas, values, datapoints

    def _stitch_grid_rows(self, matched, err_rows, values, start_nanos,
                          end_nanos, grid, lookback_nanos):
        """Host-consolidate the err rows from batched codec re-reads —
        through the ONE shared 'last' consolidation rule
        (engine.consolidate_row), so the hybrid rows cannot drift from
        the staged path's."""
        from .engine import consolidate_row

        err_docs = [matched[int(i)] for i in err_rows]
        arrays = self.host_stitch_arrays(err_docs, start_nanos, end_nanos)
        values = np.array(values, copy=True)
        for i, doc in zip(err_rows, err_docs):
            t, v = arrays[doc.id]
            values[int(i)] = consolidate_row(t, v, grid, lookback_nanos)
        return values

    def host_stitch_arrays(self, docs, start_nanos, end_nanos) -> dict:
        """Batched host-codec re-read for lanes the device decoder bailed
        on: ``doc.id -> (times i64, values f64)`` sliced to [start, end).

        Streams are collected with ONE FilesetReader pass per fileset —
        grouped by block, not one series at a time — so a handful of
        annotated lanes can't serialize the fallback into per-series
        reader/lock round trips. Decode then runs the same array path
        Shard.read_arrays uses (native read, iterator fallback); callers
        use this only where no buffer overlays the range (the residency
        and plan gates exclude overlays), so fileset streams are the
        whole truth."""
        from ..codec.iterator import MultiReaderIterator
        from ..codec.native_read import read_segments_arrays
        from ..storage.fs import FilesetID

        ns = self.db.namespaces[self.namespace]
        bsz = ns.opts.block_size_nanos
        per_series: dict[bytes, list] = {}
        by_shard: dict[int, list] = {}
        for doc in docs:
            per_series[doc.id] = []
            by_shard.setdefault(ns.shard_for(doc.id).id, []).append(doc.id)
        for shard_id, sids in by_shard.items():
            shard = ns.shards[shard_id]
            # fileset order mirrors Shard._segments_locked (oldest-first
            # listing order) so per-series segment order — and therefore
            # decoded output — is identical to read_arrays
            for fid in shard.filesets():
                if (
                    fid.block_start + bsz <= start_nanos
                    or fid.block_start >= end_nanos
                ):
                    continue
                reader = shard.reader_or_none(FilesetID(
                    self.namespace, shard_id, fid.block_start, fid.volume
                ))
                if reader is None:
                    continue  # retention race or quarantined mid-query
                for sid in sids:
                    stream = reader.stream(sid)
                    if stream:
                        per_series[sid].append(stream)
        out = {}
        for doc in docs:
            segs = per_series[doc.id]
            arrs = read_segments_arrays(segs, start_nanos, end_nanos)
            if arrs is not None:
                out[doc.id] = (
                    np.asarray(arrs[0], np.int64),
                    np.asarray(arrs[1], np.float64),
                )
                continue
            dps = [
                dp
                for dp in MultiReaderIterator(segs)
                if start_nanos <= dp.timestamp < end_nanos
            ]
            out[doc.id] = (
                np.asarray([dp.timestamp for dp in dps], np.int64),
                np.asarray([dp.value for dp in dps], np.float64),
            )
        return out

    def fetch(self, matchers, start_nanos, end_nanos):
        from . import stats

        q = matchers_to_index_query(matchers)
        # decode-from-HBM fast path (m3_tpu/resident/): when every matched
        # block is resident and no live buffer overlays the range, series
        # selection is a device gather of page rows + ONE batched decode —
        # replacing the per-series host select/decode loop below (the
        # VERDICT round-5 host-bound select/pack gap). The index resolves
        # ONCE: the resident plan and any fallback share `docs`. Cache
        # before-stats are captured up front so the pooled fallback's
        # decode work is accounted like the plain path's.
        cache = getattr(self.db, "block_cache", None)
        before = cache.stats() if cache is not None else None
        pool = getattr(self.db, "resident_pool", None)
        rows = None
        if pool is None or not pool.enabled:
            stats.add_routing(b"*", None, "streamed", "resident pool disabled")
        elif len(pool) == 0:
            stats.add_routing(b"*", None, "streamed", "resident pool empty")
        if pool is not None and pool.enabled:
            # an EMPTY pool still takes this branch: the streamed fallback
            # below re-admits sealed complete blocks (read-through), which
            # is exactly how a fully-evicted pool refills under demand
            docs = self.db.query_ids(
                self.namespace, q, start_nanos, end_nanos
            ).docs
            resident = self._fetch_resident(docs, start_nanos, end_nanos)
            if resident is not None:
                nb = sum(t.nbytes + v.nbytes for _, t, v in resident)
                # resident_bytes feeds the tenant ledger's streamed-vs-
                # resident split (bytes_scanned - resident_bytes = streamed)
                stats.add(resident_hits=1, bytes_=nb, resident_bytes=nb)
                return resident
            # fall back through the normal array surface, reusing the
            # plan's index resolution (fetch_tagged_arrays also restores
            # the storage.fetch_tagged span this path must keep emitting)
            rows = self.db.fetch_tagged_arrays(
                self.namespace, q, start_nanos, end_nanos, docs=docs
            )
            # read-through re-admission: a streamed hit on sealed,
            # complete blocks pulls them back into the pool so the hot
            # set stays resident under eviction churn
            self._maybe_readmit(docs, start_nanos, end_nanos)
        if pool is not None:
            stats.add(resident_misses=1)
        out = []
        total_bytes = 0
        # per-query cache accounting from the node-wide cache counter delta —
        # approximate under concurrent queries (deltas interleave), exact in
        # the common single-query case; the alternative (threading a stats
        # handle through every Shard read) isn't worth the hot-path cost.
        # Array surface: decoded arrays come straight from the decoded-block
        # cache (m3_tpu/cache/) on repeat queries — no per-point Datapoint
        # materialization on the scan-and-aggregate hot path.
        if rows is None:
            rows = self.db.fetch_tagged_arrays(
                self.namespace, q, start_nanos, end_nanos
            )
        for sid, tags, (times, vals) in rows:
            times = np.asarray(times, np.int64)
            vals = np.asarray(vals, np.float64)
            total_bytes += times.nbytes + vals.nbytes
            out.append((tags, times, vals))
        if before is not None:
            after = cache.stats()
            stats.add(
                bytes_=total_bytes,
                cache_hits=after["hits"] - before["hits"],
                cache_misses=after["misses"] - before["misses"],
            )
        else:
            stats.add(bytes_=total_bytes)
        return out

    # ---------- residency routing ----------

    def _resident_plan(self, docs, start_nanos, end_nanos):
        """(doc, resident BlockKeys) per matched doc when the query is
        fully servable from the pool, else None. A series is servable when
        every overlapping fileset block is either resident or
        complete-admitted with the series absent, and no buffered data
        overlaps the range. ``docs`` come from the caller's single
        query_ids resolution (shared with the fallback path)."""
        from . import stats

        pool = getattr(self.db, "resident_pool", None)
        if pool is None or not pool.enabled:
            return None
        ns = self.db.namespaces[self.namespace]
        plan = []
        for doc in docs:
            shard = ns.shard_for(doc.id)
            keys, buffered = shard.scan_block_keys(doc.id, start_nanos, end_nanos)
            if buffered:
                # EXPLAIN routing: record the decision that forced the
                # whole query onto the streamed path (entries recorded so
                # far would be misleading half-truths — only the cause and
                # the final outcome are reported)
                stats.add_routing(doc.id, None, "streamed", "buffered-overlay")
                pool.heat.charge(shard.id, misses=1)
                return None
            doc_keys = []
            for key in keys:
                if key in pool:
                    doc_keys.append(key)
                elif pool.is_complete(
                    key.namespace, key.shard_id, key.block_start, key.volume
                ):
                    continue  # fileset fully admitted: series absent from it
                else:
                    stats.add_routing(
                        doc.id, key.block_start, "streamed",
                        "not-resident (evicted or never admitted)",
                    )
                    pool.heat.charge(key.shard_id, misses=1)
                    return None  # evicted / never admitted: stream instead
            plan.append((doc, doc_keys))
        # routing + hit heat are recorded by _record_resident_routing
        # AFTER the resident scan succeeds — the chunked plan can still
        # fail (raced eviction, side-plane mismatch), and EXPLAIN must
        # never claim "resident-chunked" for a query the streamed
        # fallback actually served
        return plan

    def _record_resident_routing(self, plan) -> None:
        """EXPLAIN + per-shard heat for a resident scan that SUCCEEDED:
        the resident decoder is the chunk-parallel kernel reading side
        planes straight from the pool — EXPLAIN shows which decode path
        served every (series, block), aggregated per shard so the hot
        path charges heat once per shard, not once per lane."""
        from . import stats

        pool = self.db.resident_pool
        lanes_per_shard: dict[int, int] = {}
        for doc, doc_keys in plan:
            for key in doc_keys:
                stats.add_routing(doc.id, key.block_start, "resident",
                                  "resident-chunked")
                lanes_per_shard[key.shard_id] = (
                    lanes_per_shard.get(key.shard_id, 0) + 1
                )
        for shard_id, lanes in lanes_per_shard.items():
            pool.heat.charge(shard_id, hits=lanes)

    def _maybe_readmit(self, docs, start_nanos, end_nanos) -> int:
        """Read-through re-admission (carried from PR 3): when a scan
        fell back to the streamed path because sealed, complete blocks
        were NOT resident (evicted, or sealed by a previous process past
        the bootstrap budget), pull exactly those filesets back into the
        pool so the hot set tracks demand under eviction churn.
        "Budget permitting" is literal: re-admissions fill FREE space
        only and never evict published entries — a working set larger
        than the budget would otherwise LRU-ping-pong, each scan's
        re-admissions evicting the previous scan's. Buffered series are
        skipped: their blocks would stream again regardless
        (buffer-overlay rule). Counted in
        m3tpu_resident_readmissions_total."""
        pool = getattr(self.db, "resident_pool", None)
        if pool is None or not pool.enabled:
            return 0
        if not pool.has_free_capacity():
            # re-admissions never evict published entries, so a full
            # pool can't take anything — skip the block walk AND the
            # fileset disk re-reads (a working set larger than the
            # budget would otherwise pay both on every streamed query)
            return 0
        from ..storage.fs import FilesetID

        ns = self.db.namespaces[self.namespace]
        todo: dict[tuple, object] = {}
        for doc in docs:
            shard = ns.shard_for(doc.id)
            keys, buffered = shard.scan_block_keys(doc.id, start_nanos, end_nanos)
            if buffered:
                continue
            for key in keys:
                if key in pool or pool.is_complete(
                    key.namespace, key.shard_id, key.block_start, key.volume
                ):
                    continue
                if pool.never_completable(
                    key.namespace, key.shard_id, key.block_start, key.volume
                ):
                    # a lane over the pool's page-span limit makes this
                    # fileset permanently un-completable: re-admitting it
                    # on every streamed query would re-upload the whole
                    # fileset for nothing
                    continue
                if pool.budget_deferred(
                    key.namespace, key.shard_id, key.block_start, key.volume
                ):
                    # a past re-admission of this fileset was rejected
                    # for budget and no pages have freed since — the
                    # retry is a guaranteed rejection, skip the disk
                    # re-read until eviction/invalidation makes room
                    continue
                todo[(key.shard_id, key.block_start, key.volume)] = shard
        admitted = 0
        for (shard_id, block_start, volume), shard in todo.items():
            try:
                admitted += shard.readmit_fileset(
                    FilesetID(self.namespace, shard_id, block_start, volume)
                )
            except Exception:
                # the streamed result this query will serve is already
                # computed — a failed opportunistic re-admission (device
                # OOM near the pool budget is the likely case, and on the
                # donated-scatter path admit_block resets the pool) must
                # not turn it into a query error; remaining filesets are
                # skipped rather than hammering a struggling device
                _M_READMIT_FAILURES.inc()
                break
        return admitted

    def _fetch_resident(self, docs, start_nanos, end_nanos):
        """Batched decode-from-HBM fetch: [(tags, times, values)] exact
        (finalize_decode reconstructs bit-exact f64), or None to fall back.
        Lanes the device decoder bails on (annotated streams) re-read
        through the host array path per series."""
        from ..resident.scan import resident_fetch_arrays
        from . import stats as query_stats

        from ..utils.trace import NOOP_SPAN, TRACER

        plan = self._resident_plan(docs, start_nanos, end_nanos)
        if plan is None:
            return None
        flat_keys = [key for _, doc_keys in plan for key in doc_keys]
        decoded = ([], np.zeros(0, bool))
        # this path replaces db.fetch_tagged_arrays, so it emits the same
        # storage.fetch_tagged span — trace shape in /debug/traces must
        # not vary with residency state
        span = (
            TRACER.span("storage.fetch_tagged", namespace=self.namespace)
            if TRACER.active()
            else NOOP_SPAN
        )
        with span:
            if flat_keys:
                decoded = resident_fetch_arrays(self.db.resident_pool, flat_keys)
                if decoded is None:
                    # raced an eviction (or side-plane/chunk-shape
                    # mismatch): streamed fallback serves the query, and
                    # EXPLAIN says so
                    query_stats.add_routing(
                        b"*", None, "streamed",
                        "resident-plan-failed (raced eviction)",
                    )
                    return None
            self._record_resident_routing(plan)
            arrays, err = decoded
            out = []
            pos = 0
            err_docs = []
            err_slots: list[int] = []
            with query_stats.stage("decode"):
                for doc, doc_keys in plan:
                    lanes = arrays[pos : pos + len(doc_keys)]
                    lane_err = err[pos : pos + len(doc_keys)]
                    pos += len(doc_keys)
                    if lane_err.any():
                        # host re-read keeps Datapoint fidelity for lanes
                        # the device can't decode; blocks are disjoint so
                        # a full per-series host read replaces all its
                        # lanes — collected here, read BATCHED per block
                        # below so one bad lane doesn't serialize the
                        # fallback into per-series reader round trips
                        err_docs.append(doc)
                        err_slots.append(len(out))
                        out.append(None)
                        continue
                    if lanes:
                        times = np.concatenate([t for t, _ in lanes])
                        vals = np.concatenate([v for _, v in lanes])
                    else:
                        times = np.zeros(0, np.int64)
                        vals = np.zeros(0, np.float64)
                    lo = int(np.searchsorted(times, start_nanos, side="left"))
                    hi = int(np.searchsorted(times, end_nanos, side="left"))
                    out.append((doc.fields, times[lo:hi], vals[lo:hi]))
                if err_docs:
                    stitched = self.host_stitch_arrays(
                        err_docs, start_nanos, end_nanos
                    )
                    for slot, doc in zip(err_slots, err_docs):
                        t, v = stitched[doc.id]
                        out[slot] = (doc.fields, t, v)
            span.set_tag("series", len(out))
        return out

    def scan_totals(self, matchers, start_nanos, end_nanos) -> dict:
        """Direct scan-and-aggregate over raw samples (the paper's
        flagship path as a query surface): index-resolve the matchers,
        then either decode-from-HBM (all matched blocks resident) or
        upload-and-decode (streamed fallback) — both through the same
        kernel and reduction shapes, so the two paths agree bit for bit.

        Granularity is BLOCK-aligned: totals cover every datapoint of
        blocks overlapping [start, end) — the compressed streams decode
        whole (that is what makes the scan one kernel launch); callers
        needing exact range edges use fetch(). Returns {"sum", "count",
        "min", "max", "series", "path"} with path "resident"|"streamed".
        """
        from ..resident.scan import resident_scan_totals, streamed_scan_totals
        from ..storage.fs import CHUNK_K
        from . import stats

        q = matchers_to_index_query(matchers)
        ns = self.db.namespaces[self.namespace]
        # ONE index resolution, shared by the resident plan and fallback
        docs = self.db.query_ids(self.namespace, q, start_nanos, end_nanos).docs
        n_series = len(docs)
        plan = self._resident_plan(docs, start_nanos, end_nanos)
        aggs = None
        path = "streamed"
        stream_for = None  # lane idx -> stream bytes (err-lane stitching)
        if plan is not None:
            flat_keys = [key for _, doc_keys in plan for key in doc_keys]
            aggs = (
                resident_scan_totals(self.db.resident_pool, flat_keys)
                if flat_keys
                else _EMPTY_TOTALS
            )
            if aggs is None:
                stats.add_routing(
                    b"*", None, "streamed",
                    "resident-plan-failed (raced eviction)",
                )
            else:
                path = "resident"
                stats.add(resident_hits=1)
                self._record_resident_routing(plan)

                def stream_for(i, _keys=flat_keys):
                    from ..storage.fs import FilesetID

                    key = _keys[i]
                    shard = ns.shards[key.shard_id]
                    reader = shard.reader_or_none(
                        FilesetID(
                            key.namespace, key.shard_id, key.block_start, key.volume
                        )
                    )
                    return (reader.stream(key.series_id) or b"") if reader else b""

        if aggs is None:
            pool = getattr(self.db, "resident_pool", None)
            if pool is not None:
                stats.add(resident_misses=1)
            segments: list[bytes] = []
            chunk_ks: set[int] = set()
            streamed_per_shard: dict[int, int] = {}
            for doc in docs:
                shard = ns.shard_for(doc.id)
                for stream, _bound, chunk_k in shard.scan_segments(
                    doc.id, start_nanos, end_nanos
                ):
                    segments.append(stream)
                    chunk_ks.add(chunk_k)
                    streamed_per_shard[shard.id] = (
                        streamed_per_shard.get(shard.id, 0) + len(stream)
                    )
            if pool is not None:
                # per-shard streamed-fallback bytes: the transfer cost
                # residency would have removed, attributed to the shard
                # whose blocks weren't resident (resident/heat.py)
                for shard_id, nbytes in streamed_per_shard.items():
                    pool.heat.charge(shard_id, streamed_bytes=nbytes)
            # decode with the filesets' chunk size so the streamed twin's
            # chunk decomposition (and hence f32 reduction order) matches
            # the resident path bit for bit; mixed chunk sizes can't have
            # a resident counterpart anyway (plan_chunked refuses them),
            # so any k decodes them correctly — use the default
            k = chunk_ks.pop() if len(chunk_ks) == 1 else CHUNK_K
            aggs = (
                streamed_scan_totals(segments, k=k)
                if segments
                else _EMPTY_TOTALS
            )
            stream_for = lambda i, _segs=segments: _segs[i]
            self._maybe_readmit(docs, start_nanos, end_nanos)
        err = getattr(aggs, "series_err", None)
        if err is not None and np.asarray(err).any():
            # lanes the device decoder bailed on (annotated streams):
            # recompute them through the host codec and rebuild the
            # totals — both paths stitch identically, so silently
            # truncated counts never leave this function
            from ..parallel.scan import stitch_host_errors

            aggs = stitch_host_errors(aggs, stream_for)
        count = int(aggs.total_count)
        stats.add(series=n_series, datapoints=count)
        return {
            "sum": float(aggs.total_sum),
            "count": count,
            "min": float(aggs.total_min),
            "max": float(aggs.total_max),
            "series": n_series,
            "path": path,
            # both paths now decode through the chunk-parallel kernels
            # (side planes paged into the pool; streamed twin prescans) —
            # tools/check_resident.py asserts the resident scan reports it
            "decoder": "chunked",
        }


@dataclass
class ClusterNamespace:
    """One queryable namespace + its retention/resolution attributes
    (storage/m3/types.go ClusterNamespace + Attributes)."""

    storage: object  # Engine Storage (e.g. M3Storage)
    retention_nanos: int
    resolution_nanos: int = 0  # 0 = raw samples
    aggregated: bool = False  # False = the unaggregated namespace


def resolve_cluster_namespaces(
    namespaces: list[ClusterNamespace], now_nanos: int, start_nanos: int
) -> list[ClusterNamespace]:
    """storage/m3/cluster_resolver.go resolveClusterNamespacesForQuery:

    1. the unaggregated namespace wins if its retention covers the query
       start;
    2. otherwise the FINEST-resolution aggregated namespace that covers it;
    3. otherwise nothing covers — fall back to the longest-retention
       namespace (partial data beats none).
    """
    if not namespaces:
        return []
    covers = lambda ns: now_nanos - ns.retention_nanos <= start_nanos
    unagg = [ns for ns in namespaces if not ns.aggregated]
    if unagg and covers(unagg[0]):
        return [unagg[0]]
    covering = sorted(
        (ns for ns in namespaces if ns.aggregated and covers(ns)),
        key=lambda ns: ns.resolution_nanos,
    )
    if covering:
        return [covering[0]]
    return [max(namespaces, key=lambda ns: ns.retention_nanos)]


@dataclass
class FanoutStorage:
    """Retention/resolution-aware fanout (fanout/storage.go:48 +
    cluster_resolver): pick the namespace(s) whose attributes fit the query
    range, fetch, and dedupe exact-id overlaps preferring the
    finer-resolution source.

    The fan-in is concurrent and HEDGED ("The Tail at Scale", the same
    discipline as the client session's replica fan-outs): each resolved
    namespace fetches on its own daemon worker, and when a source has
    been in flight longer than its own per-(source, op) p95 a single
    budget-gated backup twin is issued — first leg per source wins, the
    loser is abandoned, and a loser's late error never surfaces. Local
    single-namespace queries stay inline (there is no independent
    replica behind an in-process storage worth paying a thread for).
    Counters ride the existing ``m3tpu_session_hedges_*`` family under
    ``op="fanout_fetch"``."""

    namespaces: list  # list[ClusterNamespace]
    clock: object = None  # () -> nanos; injectable for tests
    hedge_enabled: bool = True
    # floor under the p95 straggler trigger (seconds): ordinary jitter
    # must not burn hedge budget on sources answering in microseconds
    hedge_min_delay: float = 0.010
    _OP = "fanout_fetch"

    def __post_init__(self) -> None:
        from ..net.resilience import HedgeBudget, LatencyEstimator

        self.latency = LatencyEstimator()
        self.hedge_budget = HedgeBudget()
        self._pool = None

    def _now(self) -> int:
        if self.clock is not None:
            return self.clock()
        import time

        return time.time_ns()

    def resolve(self, start_nanos: int) -> list[ClusterNamespace]:
        return resolve_cluster_namespaces(self.namespaces, self._now(), start_nanos)

    def _ns_key(self, ns: ClusterNamespace) -> str:
        """Stable latency-estimator identity for one source: remote
        coordinators by URL, local storages by position + resolution."""
        url = getattr(ns.storage, "base_url", None)
        if url:
            return str(url)
        try:
            pos = self.namespaces.index(ns)
        except ValueError:
            pos = -1
        return f"local/{pos}/{ns.resolution_nanos}"

    def fetch(self, matchers, start_nanos, end_nanos):
        resolved = self.resolve(start_nanos)
        if len(resolved) == 1 and getattr(
            resolved[0].storage, "base_url", None
        ) is None:
            results = {
                0: resolved[0].storage.fetch(matchers, start_nanos, end_nanos)
            }
        else:
            results = self._hedged_fetch(
                resolved, matchers, start_nanos, end_nanos
            )
        seen: dict = {}
        order = []
        for i in range(len(resolved)):
            for tags, times, vals in results[i]:
                if tags in seen:
                    continue
                seen[tags] = (tags, times, vals)
                order.append(tags)
        return [seen[t] for t in order]

    def _hedged_fetch(self, resolved, matchers, start_nanos, end_nanos):
        import time
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as futures_wait

        from ..client.session import _DaemonPool, _session_hedges

        if self._pool is None:
            self._pool = _DaemonPool(max_workers=8)
        pool = self._pool
        n = len(resolved)
        keys = [self._ns_key(ns) for ns in resolved]
        futs: dict = {}  # Future -> source index
        hedge_futs: set = set()  # backup legs
        legs = [1] * n
        attempted = [False] * n
        unresolved: set[int] = set()  # issued hedges with no outcome yet
        results: dict[int, list] = {}
        errors: dict[int, BaseException] = {}
        now = time.monotonic()
        started = [now] * n
        for i, ns in enumerate(resolved):
            futs[pool.submit(ns.storage.fetch, matchers, start_nanos, end_nanos)] = i
        pending = set(futs)
        while pending and (len(results) + len(errors)) < n:
            # wake exactly when the earliest unhedged source crosses its
            # straggler threshold (or on the first completion)
            now = time.monotonic()
            fire = None
            if self.hedge_enabled:
                for i in range(n):
                    if attempted[i] or i in results or i in errors:
                        continue
                    p95 = self.latency.p95(keys[i], self._OP)
                    if p95 is None:
                        continue
                    at = started[i] + max(p95, self.hedge_min_delay)
                    if fire is None or at < fire:
                        fire = at
            timeout = None if fire is None else max(fire - now, 0.0)
            done, pending = futures_wait(
                pending, timeout=timeout, return_when=FIRST_COMPLETED
            )
            for fut in done:
                i = futs[fut]
                is_hedge = fut in hedge_futs
                exc = fut.exception()
                if exc is None:
                    if i in results:
                        continue  # loser twin: never double-merged
                    results[i] = fut.result()
                    self.latency.record(
                        keys[i], self._OP, time.monotonic() - started[i]
                    )
                    self.hedge_budget.on_success()
                    if i in unresolved:
                        unresolved.discard(i)
                        _session_hedges(
                            "won" if is_hedge else "wasted", self._OP
                        ).inc()
                else:
                    legs[i] -= 1
                    if is_hedge and i in unresolved:
                        unresolved.discard(i)
                        _session_hedges("wasted", self._OP).inc()
                    # a leg's error surfaces only when the source has no
                    # other live leg and no delivered result
                    if i not in results and legs[i] <= 0:
                        errors[i] = exc
            if not pending or (len(results) + len(errors)) >= n:
                break
            if not self.hedge_enabled:
                continue
            # at most ONE budget-gated backup per wake, to the straggler
            now = time.monotonic()
            for i in range(n):
                if attempted[i] or i in results or i in errors:
                    continue
                p95 = self.latency.p95(keys[i], self._OP)
                if p95 is None:
                    continue
                if now - started[i] <= max(p95, self.hedge_min_delay):
                    continue
                attempted[i] = True
                if not self.hedge_budget.try_spend():
                    break
                fut = pool.submit(
                    resolved[i].storage.fetch, matchers, start_nanos, end_nanos
                )
                futs[fut] = i
                hedge_futs.add(fut)
                pending.add(fut)
                legs[i] += 1
                unresolved.add(i)
                _session_hedges("issued", self._OP).inc()
                break
        # fan-in over: hedges with no outcome (both legs abandoned or
        # still in flight) were pure extra load
        for _ in range(len(unresolved)):
            _session_hedges("wasted", self._OP).inc()
        if errors:
            raise errors[min(errors)]
        return results
