"""Storage adapters: matchers → index query → decoded series; fanout.

Reference: /root/reference/src/query/storage/m3/storage.go:182
(FetchCompressed: resolve namespaces, FetchTagged, wrap into blocks) and
src/query/storage/fanout/storage.go:48-156 (merge across clusters by
retention/resolution attributes).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..index.query import AllQuery, conj, neg, regexp, term
from ..storage.database import Database
from .promql import Matcher


def matchers_to_index_query(matchers: list[Matcher]):
    """models.Matchers → idx.Query (storage/index/convert)."""
    qs = []
    for m in matchers:
        name = m.name.encode()
        value = m.value.encode()
        if m.op == "=":
            qs.append(term(name, value))
        elif m.op == "!=":
            qs.append(neg(term(name, value)))
        elif m.op == "=~":
            qs.append(regexp(name, value))
        elif m.op == "!~":
            qs.append(neg(regexp(name, value)))
        else:
            raise ValueError(f"bad matcher op {m.op}")
    if not qs:
        return AllQuery()
    if len(qs) == 1:
        return qs[0]
    return conj(*qs)


@dataclass
class M3Storage:
    """Engine Storage over one Database namespace."""

    db: Database
    namespace: str

    def fetch(self, matchers, start_nanos, end_nanos):
        q = matchers_to_index_query(matchers)
        out = []
        for sid, tags, dps in self.db.fetch_tagged(self.namespace, q, start_nanos, end_nanos):
            times = np.asarray([dp.timestamp for dp in dps], np.int64)
            vals = np.asarray([dp.value for dp in dps], np.float64)
            out.append((tags, times, vals))
        return out


@dataclass
class FanoutStorage:
    """Merge series from multiple storages (fanout/storage.go): exact-id
    duplicates resolved by preferring the higher-resolution (first) source."""

    storages: list

    def fetch(self, matchers, start_nanos, end_nanos):
        seen: dict = {}
        order = []
        for st in self.storages:
            for tags, times, vals in st.fetch(matchers, start_nanos, end_nanos):
                if tags in seen:
                    continue
                seen[tags] = (tags, times, vals)
                order.append(tags)
        return [seen[t] for t in order]
