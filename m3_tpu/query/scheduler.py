"""Cost-aware admission control and load shedding for the query engine.

Reference: DAGOR ("Overload Control for Scaling WeChat Microservices",
SoCC 2018) — overload is handled by *priority-aware* admission rather
than a hard inflight cap: when the engine is saturated, the queries
least worth running (misbehaving tenants, expensive scans, fresh
arrivals) are shed with a typed error the client can distinguish from a
failure, while cheap well-behaved work keeps flowing. The RPC plane
already has a blunt per-process cap (net/server.py ``max_inflight``);
this layer is the graceful version in front of ``Engine.query_range``.

Priority here is a SHED score — higher means shed first:

    score = tenant_pressure * pressure_weight     # dominant term
          + cost / (cost + cost_scale)            # expensive sheds first
          - age_seconds * aging_rate              # anti-starvation

``tenant_pressure`` is the tenant's in-window misbehavior ratio from the
process ledger (query/tenants.LEDGER): limit_rejections /
(limit_rejections + queries + 1) — a tenant that keeps tripping its
limits absorbs the sheds instead of the well-behaved ones. Cost is grid steps x a matched-series estimate remembered from
the query's own past runs (there is no cheap index-cardinality API, and
in cluster mode the coordinator has no local index at all — the memo is
the honest estimator; see ROADMAP residuals).

Sheds surface as :class:`QueryShedError` (coordinator maps it to HTTP
503) and are counted twice on purpose: the process-wide
``m3tpu_query_shed_total{tenant,reason}`` with the bounded ``reason``
vocabulary {queue_full, overload, deadline}, and the tenant ledger's
``sheds`` field so ruler rules like ``tenant:shed:rate5m`` see them.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..utils.instrument import DEFAULT as METRICS

# the bounded shed-reason vocabulary (M3L005: "reason" is allowlisted on
# the promise that it stays an enum, never request-derived)
SHED_QUEUE_FULL = "queue_full"
SHED_OVERLOAD = "overload"
SHED_DEADLINE = "deadline"

_SHED_HELP = "queries shed by the admission scheduler instead of run"


class QueryShedError(RuntimeError):
    """Typed load-shed rejection: the query was refused BEFORE any
    evaluation work ran (same retryable contract as net/resilience's
    UnavailableError). ``reason`` is one of the SHED_* constants;
    ``tenant`` is the normalized tenant that absorbed the shed."""

    def __init__(self, reason: str, tenant: str) -> None:
        super().__init__(f"query shed ({reason}) for tenant {tenant}")
        self.reason = reason
        self.tenant = tenant


def tenant_pressure(tenant: str) -> float:
    """The tenant's in-window misbehavior ratio in [0, 1): how much of
    its recent traffic tripped cost limits. Reads the process ledger's
    rolling window; an unseen tenant scores 0 (innocent until measured).

    Deliberately NOT counting the tenant's own sheds: sheds feeding the
    score that causes sheds is a positive feedback loop — one unlucky
    queue-full eviction would snowball against an innocent tenant. Limit
    rejections are externally caused (the tenant exceeded ITS configured
    cap), so they are a stable misbehavior signal."""
    from .tenants import LEDGER

    totals = LEDGER.window_totals(tenant)
    if not totals:
        return 0.0
    bad = float(totals.get("limit_rejections", 0))
    good = float(totals.get("queries", 0))
    return bad / (bad + good + 1.0)


class CostMemo:
    """Bounded LRU memo of a query's last observed matched-series count,
    the honest cost estimator available to a coordinator with no local
    index: estimate = grid_steps x remembered series (default 1 series
    for a never-seen query — optimistic, so new queries are not shed on
    a guess)."""

    def __init__(self, capacity: int = 1024) -> None:
        self.capacity = int(capacity)
        self._memo: OrderedDict[str, int] = OrderedDict()
        self._lock = threading.Lock()

    def observe(self, query: str, series: int) -> None:
        if series <= 0:
            return
        with self._lock:
            self._memo[query] = int(series)
            self._memo.move_to_end(query)
            while len(self._memo) > self.capacity:
                self._memo.popitem(last=False)

    def series_estimate(self, query: str) -> int:
        with self._lock:
            n = self._memo.get(query)
            if n is not None:
                self._memo.move_to_end(query)
        return n if n is not None else 1

    def estimate(self, query: str, grid_steps: int) -> float:
        return float(max(1, grid_steps)) * float(self.series_estimate(query))


class _Waiter:
    """One queued admission request. State transitions under the
    scheduler's condition: queued -> admitted | shed."""

    __slots__ = ("tenant", "cost", "enqueued_at", "base_score", "state", "reason")

    def __init__(self, tenant: str, cost: float, base_score: float,
                 now: float) -> None:
        self.tenant = tenant
        self.cost = cost
        self.enqueued_at = now
        self.base_score = base_score
        self.state = "queued"
        self.reason = ""


class QueryScheduler:
    """Bounded priority admission in front of ``Engine.query_range``.

    Fast path: below ``max_inflight`` with an empty queue, admission is
    one lock acquire. Under pressure queries wait (bounded by their
    deadline or ``max_queue_wait``) in a priority queue; each release
    admits the LOWEST shed-score waiter. Shedding happens at three
    points, each with its typed reason:

    - ``queue_full``: the queue is at capacity — the WORST-scoring entry
      (which may be the newcomer) is evicted;
    - ``overload``: the queue is past ``overload_watermark`` of capacity
      and the newcomer's tenant-pressure term alone exceeds the best
      queued entry's total score — fast-fail the misbehaving tenant
      before it queues (DAGOR's business-priority gate);
    - ``deadline``: the entry's wait budget expired while queued.

    ``record`` (a query/stats.QueryStats) gets ``queue_state`` /
    ``priority`` stamped through the lifecycle so /debug/active_queries
    shows queued/running/shed with the score that decided it.
    """

    def __init__(
        self,
        max_inflight: int = 8,
        max_queue: int = 64,
        overload_watermark: float = 0.75,
        max_queue_wait: float = 5.0,
        pressure_weight: float = 8.0,
        cost_scale: float = 100_000.0,
        aging_rate: float = 0.5,
        clock=time.monotonic,
    ) -> None:
        self.max_inflight = max(1, int(max_inflight))
        self.max_queue = max(1, int(max_queue))
        self.overload_watermark = float(overload_watermark)
        self.max_queue_wait = float(max_queue_wait)
        self.pressure_weight = float(pressure_weight)
        self.cost_scale = float(cost_scale)
        self.aging_rate = float(aging_rate)
        self._clock = clock
        self.costs = CostMemo()
        self._cond = threading.Condition()
        self._inflight = 0
        self._queue: list[_Waiter] = []
        self._depth_gauge = METRICS.gauge(
            "query_sched_queue_depth", "queries waiting for admission"
        )
        self._inflight_gauge = METRICS.gauge(
            "query_sched_inflight", "queries admitted and running"
        )
        self._queued_total = METRICS.counter(
            "query_sched_queued_total",
            "queries that waited in the admission queue (vs fast-path)",
        )

    # -- scoring --

    def score(self, tenant: str, cost: float, age: float = 0.0) -> float:
        """The shed score (higher = shed first); see module docstring."""
        return (
            tenant_pressure(tenant) * self.pressure_weight
            + cost / (cost + self.cost_scale)
            - age * self.aging_rate
        )

    def _waiter_score(self, w: _Waiter, now: float) -> float:
        return w.base_score - (now - w.enqueued_at) * self.aging_rate

    # -- admission --

    def admit(self, query: str, grid_steps: int, record=None,
              deadline: float | None = None) -> None:
        """Block until admitted or raise :class:`QueryShedError`. The
        caller MUST pair a successful return with :meth:`release` (the
        engine does so in its query_range finally). ``deadline`` is a
        monotonic-clock instant bounding the queue wait; None uses
        ``max_queue_wait``."""
        from . import tenants

        tenant = tenants.current() or tenants.DEFAULT_TENANT
        cost = self.costs.estimate(query, grid_steps)
        base = self.score(tenant, cost)
        if record is not None:
            record.priority = base
        with self._cond:
            if self._inflight < self.max_inflight and not self._queue:
                self._inflight += 1
                self._inflight_gauge.set(float(self._inflight))
                return
            now = self._clock()
            # DAGOR-style fast gate: past the watermark, a tenant whose
            # pressure term ALONE already outranks everything queued is
            # shed before it can occupy a slot. Zero-pressure (innocent)
            # tenants never trip this — they queue and compete; the
            # max(…, 0.0) floor keeps an aged-negative queue from
            # turning a barely-measured tenant into a shed.
            pressure_term = tenant_pressure(tenant) * self.pressure_weight
            if (
                len(self._queue) >= self.overload_watermark * self.max_queue
                and pressure_term > 0.0
                and pressure_term > max(
                    max(self._waiter_score(w, now) for w in self._queue), 0.0
                )
            ):
                self._shed(record, tenant, SHED_OVERLOAD)
            me = _Waiter(tenant, cost, base, now)
            self._queue.append(me)
            self._queued_total.inc()
            if record is not None:
                record.queue_state = "queued"
            if len(self._queue) > self.max_queue:
                victim = max(self._queue, key=lambda w: self._waiter_score(w, now))
                victim.state = "shed"
                victim.reason = SHED_QUEUE_FULL
                self._queue.remove(victim)
                self._cond.notify_all()
                if victim is me:
                    self._shed(record, tenant, SHED_QUEUE_FULL)
            self._depth_gauge.set(float(len(self._queue)))
            limit = deadline if deadline is not None else now + self.max_queue_wait
            while me.state == "queued":
                remaining = limit - self._clock()
                if remaining <= 0:
                    me.state = "shed"
                    me.reason = SHED_DEADLINE
                    if me in self._queue:
                        self._queue.remove(me)
                    break
                self._cond.wait(remaining)
            self._depth_gauge.set(float(len(self._queue)))
            if me.state == "shed":
                self._shed(record, tenant, me.reason)
            # admitted by a releaser (who already took the inflight slot
            # on our behalf)
            if record is not None:
                record.queue_state = "running"

    def release(self) -> None:
        """Return an admission slot and admit the best waiter, if any."""
        with self._cond:
            self._inflight -= 1
            now = self._clock()
            while self._inflight < self.max_inflight and self._queue:
                best = min(self._queue, key=lambda w: self._waiter_score(w, now))
                self._queue.remove(best)
                best.state = "admitted"
                self._inflight += 1
            self._inflight_gauge.set(float(self._inflight))
            self._depth_gauge.set(float(len(self._queue)))
            self._cond.notify_all()

    def observe(self, query: str, series: int) -> None:
        """Feed a completed query's matched-series count back into the
        cost memo (the engine calls this after a successful eval)."""
        self.costs.observe(query, series)

    # -- shed bookkeeping --

    def _shed(self, record, tenant: str, reason: str) -> None:
        from .tenants import LEDGER

        if record is not None:
            record.queue_state = "shed"
        METRICS.counter(
            "query_shed_total", _SHED_HELP,
            labels={"tenant": tenant, "reason": reason},
        ).inc()
        LEDGER.charge(tenant, sheds=1)
        raise QueryShedError(reason, tenant)

    # -- introspection (for /debug + tests) --

    def snapshot(self) -> dict:
        with self._cond:
            now = self._clock()
            return {
                "inflight": self._inflight,
                "maxInflight": self.max_inflight,
                "queued": [
                    {
                        "tenant": w.tenant,
                        "cost": w.cost,
                        "ageSeconds": now - w.enqueued_at,
                        "score": self._waiter_score(w, now),
                    }
                    for w in self._queue
                ],
            }
