"""Device query plans: a query is ONE XLA program.

PROFILE.md's recurring villain is the host round trip — 8–15 ms of
dispatch RTT dominating every sub-ms kernel — and the staged executor
pays it 4–6 times per query because `query/m3_storage.py` stitches the
stages with host-side types: the device index resolves doc ids to the
host, the host walks per-doc block keys, the resident pool plans a
gather, the decode dispatches, and consolidation runs a per-series
Python loop. Every piece already lives on device; this module composes
them inside ONE jit program per query *shape*:

    term binary-search match  (index/device/kernels.match_terms_traced)
      → postings bitmaps + bitwise AST set algebra (same kernels)
      → matched-doc compaction (cumsum over the doc bitmap)
      → per-lane page-table gather  (plan tables uploaded once per
        (segment, block set) and cached)
      → resident chunked decode  (parallel/scan assembly +
        ops/chunked.decode_chunked_lanes, straight from the pool's
        pages + packed side planes)
      → step-grid consolidation  (vectorized binary search over the
        decoded timestamps, u64-pair compares)

The program returns the CONSOLIDATED grid as raw (hi, lo) value pairs
plus validity masks; the host then runs the exact same float64
reconstruction the staged path uses (ops/decode.finalize_decode math)
and hands the grid to the unchanged engine pipeline (temporal
functions, aggregations). Bit-identity with the staged path is
therefore structural: both paths reconstruct values with the same f64
arithmetic and pick grid samples with the same upper-bound rule — the
property suite asserts exact equality, not tolerance.

Plan cache: an LRU keyed by (namespace, matchers, block set, grid
shape). Entries carry the uploaded plan-vector tables and revalidate
per execution against pool eviction/invalidation counters, shard
fileset epochs, and index-segment identity — a segment swap, volume
bump, or resident eviction invalidates the plan (regression-tested).
Ineligible queries fall back to the staged executor transparently with
an EXPLAIN routing reason per cause (host-regexp leaf, non-resident
block, buffer overlay, multi-segment index, ...).

Knobs:

    M3_TPU_QUERY_PLAN          "0" disables planning entirely
    M3_TPU_QUERY_PLAN_CACHE    LRU entries (default 64)
"""

from __future__ import annotations

import functools
import os
import threading
from collections import OrderedDict
from contextlib import contextmanager

import numpy as np

from ..index.device.kernels import pad_pow2
from ..utils.instrument import DEFAULT as METRICS
from ..utils.instrument import KernelProfiler

_M_HITS = METRICS.counter(
    "query_plan_hits_total",
    "fetches served by a cached device query plan (one fused dispatch)",
)
_M_MISSES = METRICS.counter(
    "query_plan_misses_total",
    "device query plans built (cache miss: first sighting, or a stamp "
    "mismatch after segment swap / volume bump / eviction)",
)
_M_FALLBACKS = METRICS.counter(
    "query_plan_fallbacks_total",
    "fetches that degraded to the staged executor (EXPLAIN records the "
    "routing reason per cause)",
)
_M_COMPILES = METRICS.counter(
    "query_plan_compiles_total",
    "fused plan programs compiled (one per distinct query/plan shape)",
)
_M_ERRORS = METRICS.counter(
    "query_plan_errors_total",
    "device plan executions that raised and fell back staged (the "
    "staged path is always correct; errors are counted, never surfaced)",
)
_M_COALESCED = METRICS.counter(
    "query_plan_coalesced_total",
    "fetches served by joining another concurrent query's in-flight "
    "device scan (N concurrent identical fetches -> 1 dispatch)",
)

# the fused program's dispatch seam: compile attribution + sampled
# wall-time under the SAME profiler contract as every other kernel, and
# the per-query device_dispatches counter ticks here — exactly once per
# plan-served fetch
PROF = KernelProfiler("query_plan")

_SENTINEL_GRID = 8  # minimum padded grid length


def plan_enabled() -> bool:
    return os.environ.get("M3_TPU_QUERY_PLAN", "1") != "0"


def _cache_cap() -> int:
    try:
        return max(int(os.environ.get("M3_TPU_QUERY_PLAN_CACHE", "64")), 1)
    except ValueError:
        return 64


# ---------------------------------------------------------------------------
# force-staged probe (the bit-identity surface CI diffs against)
# ---------------------------------------------------------------------------

_FORCE = threading.local()


@contextmanager
def force_staged():
    """Disable device plans for this thread's queries (the parity probe:
    tools/check_pipeline.py runs every query twice, fused and
    force-staged, and asserts bit-identical results)."""
    prev = getattr(_FORCE, "on", False)
    _FORCE.on = True
    try:
        yield
    finally:
        _FORCE.on = prev


def staged_forced() -> bool:
    return getattr(_FORCE, "on", False)


class Ineligible(Exception):
    """Query/plan state the fused pipeline does not cover — the caller
    records ``reason`` in EXPLAIN routing and runs the staged path."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


# ---------------------------------------------------------------------------
# AST shape extraction
# ---------------------------------------------------------------------------


def _ast_shape(q, arrays, leaves: list, ranges: list):
    """Index query AST -> a hashable shape tree whose leaves reference
    slots in ``leaves`` (exact-match values, one row each) and
    ``ranges`` ((lo, hi) global term ranges, host-narrowed). Static
    per-leaf postings-slab bounds ride the tree so the program builder
    can bake them. Raises Ineligible for nodes the device cannot model
    (general regexps keep their automaton on the host)."""
    from ..index.device.segment import classify_regexp
    from ..index.query import (
        AllQuery,
        ConjunctionQuery,
        DisjunctionQuery,
        FieldQuery,
        NegationQuery,
        RegexpQuery,
        TermQuery,
    )

    def field_slab(field: bytes):
        _, _, ds, de = arrays.fields.get(field, (0, 0, 0, 0))
        from ..index.device import kernels

        return ds, kernels.pad_pow2(de - ds)

    def leaf(field: bytes, values: list):
        slot = len(leaves)
        leaves.extend((field, v) for v in values)
        ds, slab = field_slab(field)
        return ("terms", slot, len(values), ds, slab)

    def rng(field: bytes, lo: int, hi: int):
        ridx = len(ranges)
        ranges.append((lo, hi))
        ds, slab = field_slab(field)
        return ("range", ridx, ds, slab)

    def walk(node):
        if isinstance(node, TermQuery):
            return leaf(node.field, [node.value])
        if isinstance(node, RegexpQuery):
            kind, val = classify_regexp(node.pattern)
            if kind == "literal":
                return leaf(node.field, [val])
            if kind == "alternation":
                return leaf(node.field, list(val))
            if kind == "prefix" and arrays.dot_safe:
                start, count = arrays.fields.get(node.field, (0, 0, 0, 0))[:2]
                lo, hi = _prefix_bounds(arrays, val, start, start + count)
                return rng(node.field, lo, hi)
            raise Ineligible("host-regexp-leaf")
        if isinstance(node, FieldQuery):
            start, count = arrays.fields.get(node.field, (0, 0, 0, 0))[:2]
            return rng(node.field, start, start + count)
        if isinstance(node, AllQuery):
            return ("all",)
        if isinstance(node, ConjunctionQuery):
            pos = [walk(s) for s in node.queries
                   if not isinstance(s, NegationQuery)]
            negs = [walk(s.query) for s in node.queries
                    if isinstance(s, NegationQuery)]
            return ("and", tuple(pos), tuple(negs))
        if isinstance(node, DisjunctionQuery):
            return ("or", tuple(walk(s) for s in node.queries))
        if isinstance(node, NegationQuery):
            return ("not", walk(node.query))
        raise Ineligible(f"unsupported-node:{type(node).__name__}")

    return walk(q)


def _prefix_bounds(arrays, prefix: bytes, lo: int, hi: int):
    """Host prefix narrow over the key-matrix mirror — identical to
    DeviceSegment._prefix_range (shared compare in kernels.py)."""
    from ..index.device import kernels
    from ..index.segment import prefix_upper

    width = 4 * arrays.k_words
    if len(prefix) > width:
        return lo, lo
    pk, pl = kernels.build_term_keys([prefix], arrays.k_words)
    lo = kernels.host_lower_bound(
        arrays.host_keys, arrays.host_lens, lo, hi, pk[0], int(pl[0])
    )
    up = prefix_upper(prefix)
    if up is not None and len(up) <= width:
        uk, ul = kernels.build_term_keys([up], arrays.k_words)
        hi = kernels.host_lower_bound(
            arrays.host_keys, arrays.host_lens, lo, hi, uk[0], int(ul[0])
        )
    return lo, hi


# ---------------------------------------------------------------------------
# the fused program (built once per shape, cached)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _build_program(ast, dims):
    """ONE jitted program for a (query shape, plan shapes) class. ``ast``
    is the hashable shape tree (leaf slots + static slab bounds baked
    in); ``dims`` the static dimension tuple. Runtime VALUES (query
    keys, range bounds, pool buffers, plan tables, grid) are inputs, so
    one compilation serves every query of the same shape."""
    import jax
    import jax.numpy as jnp

    from ..index.device.kernels import (
        bitmap_from_term_range_traced,
        bitmap_from_terms_traced,
        match_terms_traced,
    )
    from ..ops import u64
    from ..ops.chunked import decode_chunked_lanes
    from ..parallel.scan import _assemble_resident_lanes_traced

    (n_words, n_docs_pad, cap, n_blocks, c, k, cw, lp, sl,
     page_words, spc, t_grid) = dims
    t_pts = n_blocks * c * k

    def program(term_keys, term_lens, post_idx, post_data, all_words,
                q_keys, q_lens, q_lo, q_hi, r_lo, r_hi,
                pool_words, side_words,
                t_pages, t_sides, t_chunks, t_bits, t_bhi, t_blo,
                g_hi, g_lo, flo, fhi, lb):
        i32 = jnp.int32

        # ---- stage 1: batched term match (every exact leaf, one search)
        if q_keys.shape[0]:
            gis = match_terms_traced(
                term_keys, term_lens, q_lo, q_hi, q_keys, q_lens
            )
        else:
            gis = jnp.zeros(0, i32)

        # ---- stage 2: bitmap algebra compiled from the AST shape
        def eval_node(node):
            tag = node[0]
            if tag == "terms":
                _, slot, n, ds, slab = node
                rows = gis[slot : slot + n]
                b_pad = pad_pow2(n)
                if b_pad != n:
                    rows = jnp.concatenate(
                        [rows, jnp.full(b_pad - n, -1, i32)]
                    )
                return bitmap_from_terms_traced(
                    post_idx, post_data, rows, jnp.int32(ds), n_words, slab
                )
            if tag == "range":
                _, ridx, ds, slab = node
                return bitmap_from_term_range_traced(
                    post_idx, post_data, r_lo[ridx], r_hi[ridx],
                    jnp.int32(ds), n_words, slab,
                )
            if tag == "all":
                return all_words
            if tag == "and":
                _, pos, negs = node
                if pos:
                    acc = eval_node(pos[0])
                    for s in pos[1:]:
                        acc = acc & eval_node(s)
                else:
                    acc = all_words
                for s in negs:
                    acc = acc & ~eval_node(s)
                return acc
            if tag == "or":
                acc = jnp.zeros(n_words, jnp.uint32)
                for s in node[1]:
                    acc = acc | eval_node(s)
                return acc
            if tag == "not":
                return all_words & ~eval_node(node[1])
            raise AssertionError(node)

        bitmap = eval_node(ast)

        # ---- stage 3: matched-doc compaction (doc bitmap -> dense slots)
        bits = (
            (bitmap[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
        ).reshape(-1)[:n_docs_pad] != 0
        ncum = jnp.cumsum(bits.astype(i32))
        n_matched = ncum[-1] if n_docs_pad else jnp.int32(0)
        slot = ncum - 1
        sent = n_docs_pad  # sentinel row: the all-zero lane block
        sel = (
            jnp.full(cap + 1, sent, i32)
            .at[jnp.where(bits, slot, cap)]
            .set(jnp.arange(n_docs_pad, dtype=i32), mode="drop")[:cap]
        )

        # ---- stage 4: per-lane plan gather + resident assembly + decode
        lane_rows = (
            sel[:, None] * n_blocks + jnp.arange(n_blocks, dtype=i32)[None, :]
        ).reshape(-1)
        kw = _assemble_resident_lanes_traced(
            pool_words, side_words,
            t_pages[lane_rows], t_sides[lane_rows], t_chunks[lane_rows],
            t_bits[lane_rows], t_bhi[lane_rows], t_blo[lane_rows],
            c=c, cw=cw, w=page_words, spc=spc,
        )
        res = decode_chunked_lanes(**kw, k=k)

        rs = lambda x: x.reshape(cap, t_pts)
        ts = (rs(res.ts_hi), rs(res.ts_lo))
        vhi, vlo = rs(res.val_hi), rs(res.val_lo)
        pif, mlt = rs(res.point_is_float), rs(res.mult)
        valid = rs(res.valid)
        err = jnp.any(res.err.reshape(cap, n_blocks * c), axis=1)

        # ---- stage 5: consolidation onto the step grid
        # range mask mirrors the staged fetch window [fetch_lo, fetch_hi)
        valid = valid & ~u64.lt_u(ts, flo) & u64.lt_u(ts, fhi)
        counts = jnp.sum(valid.astype(i32), axis=1)
        # forward-fill valid points over invalid slots (log-time select
        # chain; NO scatter — XLA CPU lowers 2D scatters to scalar
        # loops). Timestamps are ascending over each row's valid points,
        # so the filled row is monotone non-decreasing end to end:
        # leading invalid slots carry (0, has=False), later invalid
        # slots duplicate their predecessor — exactly what an upper
        # bound needs (it lands after the duplicate run and the gather
        # reads the run's fill value, i.e. the last valid point).
        # fill only the search keys + a source-index plane; values gather
        # once at the end through the filled index (3 filled arrays
        # instead of 6)
        src = jnp.broadcast_to(
            jnp.arange(t_pts, dtype=i32)[None, :], (cap, t_pts)
        )
        have = valid
        fill = [
            jnp.where(valid, x, jnp.zeros_like(x))
            for x in (ts[0], ts[1], src)
        ]
        sh = 1
        while sh < t_pts:
            prev_have = jnp.pad(have, ((0, 0), (sh, 0)))[:, :t_pts]
            take = ~have & prev_have
            fill = [
                jnp.where(take, jnp.pad(x, ((0, 0), (sh, 0)))[:, :t_pts], x)
                for x in fill
            ]
            have = have | prev_have
            sh *= 2
        fth, ftl, fsrc = fill
        # vectorized upper bound per (series, grid step): first index
        # with filled-ts > t_j — np.searchsorted(times, grid, "right")
        gh = jnp.broadcast_to(g_hi[None, :], (cap, t_grid))
        gl = jnp.broadcast_to(g_lo[None, :], (cap, t_grid))
        lo_i = jnp.zeros((cap, t_grid), i32)
        hi_i = jnp.full((cap, t_grid), t_pts, i32)
        for _ in range(max(int(t_pts).bit_length(), 1)):
            active = lo_i < hi_i
            mid = (lo_i + hi_i) // 2
            midc = jnp.clip(mid, 0, max(t_pts - 1, 0))
            tm = (
                jnp.take_along_axis(fth, midc, axis=1),
                jnp.take_along_axis(ftl, midc, axis=1),
            )
            gt = u64.lt_u((gh, gl), tm)  # ts[mid] > t_j
            hi_i = jnp.where(active & gt, mid, hi_i)
            lo_i = jnp.where(active & ~gt, mid + 1, lo_i)
        idx = lo_i - 1
        idc = jnp.clip(idx, 0, max(t_pts - 1, 0))
        ok = (idx >= 0) & jnp.take_along_axis(have, idc, axis=1)
        st = (
            jnp.take_along_axis(fth, idc, axis=1),
            jnp.take_along_axis(ftl, idc, axis=1),
        )
        age = u64.sub((gh, gl), st)
        ok = ok & u64.lt_u(age, lb)
        pick = jnp.take_along_axis(fsrc, idc, axis=1)
        g_vh = jnp.take_along_axis(vhi, pick, axis=1)
        g_vl = jnp.take_along_axis(vlo, pick, axis=1)
        g_pf = jnp.take_along_axis(pif.astype(i32), pick, axis=1)
        g_ml = jnp.take_along_axis(mlt, pick, axis=1)
        return (bitmap, n_matched, counts, err, g_vh, g_vl, g_pf, g_ml, ok)

    _M_COMPILES.inc()
    return jax.jit(program)


def _finalize_grid(vhi, vlo, pif, mult, ok) -> np.ndarray:
    """Consolidated pair grid -> float64 values, with the EXACT
    reconstruction arithmetic of ops/decode.finalize_decode (f64 bit
    view for float-mode points, int64/10^mult for int-mode) so the fused
    grid matches the staged consolidate output bit for bit."""
    # m3lint: disable=M3L010 -- host-side dtype view: inputs were already finalized to host ndarrays by _execute's single readback; no device sync here
    raw = (np.asarray(vhi, np.uint64) << np.uint64(32)) | np.asarray(
        vlo, np.uint64
    )
    float_vals = raw.view(np.float64)
    int_vals = raw.astype(np.int64).astype(np.float64)
    # m3lint: disable=M3L010 -- host-side dtype view of already-host mult (see raw above)
    scale = np.power(10.0, np.asarray(mult, np.int64))
    # m3lint: disable=M3L010 -- host-side dtype view of already-host pif (see raw above)
    values = np.where(np.asarray(pif, bool) != 0, float_vals, int_vals / scale)
    # m3lint: disable=M3L010 -- host-side dtype view of already-host ok (see raw above)
    return np.where(np.asarray(ok, bool), values, np.nan)


# ---------------------------------------------------------------------------
# plan entries + planner
# ---------------------------------------------------------------------------


class _PlanEntry:
    """One cached plan: the compiled program, the uploaded plan-vector
    tables for its (segment, block set), pre-built query-key inputs, and
    the validity stamp it revalidates against per execution."""

    __slots__ = (
        "ast", "dims", "fn", "seg", "arrays", "inputs", "tables",
        "cap", "stamp", "chunk_k", "matched",
    )


class _Flight:
    """One in-flight coalesced device scan: the leader executes, every
    follower that arrives while it runs blocks on ``event`` and shares
    the result (or the exception — an Ineligible leader means every
    follower is ineligible the same way and runs staged itself)."""

    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None


class Planner:
    """Per-storage device query planner with an LRU plan cache."""

    def __init__(self, db, namespace: str) -> None:
        self.db = db
        self.namespace = namespace
        self._cache: "OrderedDict[tuple, _PlanEntry]" = OrderedDict()
        self._lock = threading.Lock()
        # scan coalescing (singleflight): identical concurrent fetches
        # keyed by (plan key, window, grid) share ONE gathered dispatch
        self._flights: dict[tuple, _Flight] = {}
        # cache stats for /debug surfaces
        self.hits = 0
        self.misses = 0
        self.fallbacks = 0
        self.coalesced = 0

    def evict_stale(self) -> int:
        """Drop cached plans whose pool/fileset stamp no longer holds —
        called by the fallback path so entries built against evicted or
        invalidated state release their pinned device tables (and the
        index-segment arrays they keep alive) instead of lingering until
        LRU displacement. Segment-identity staleness is covered too: a
        swapped segment's plan was stamped with pool/epoch state that
        moved with the swap's invalidations. O(cache), cache is small."""
        pool = getattr(self.db, "resident_pool", None)
        namespaces = getattr(self.db, "namespaces", None)
        if pool is None or namespaces is None or self.namespace not in namespaces:
            return 0
        ns = namespaces[self.namespace]
        live = (
            pool.evictions, pool.invalidations,
            tuple(sh.fileset_epoch for sh in ns.shards),
        )
        with self._lock:
            stale = [
                k for k, e in self._cache.items() if e.stamp[2:] != live
            ]
            for k in stale:
                del self._cache[k]
        return len(stale)

    def run(self, matchers, fetch_lo: int, fetch_hi: int, grid: np.ndarray,
            lookback_nanos: int):
        """Serve one fetch through a device plan. Returns
        (metas, values_f64 [S, T], datapoints) or raises Ineligible with
        the routing reason (the caller records it and runs staged).
        ``grid`` is the engine's consolidation timestamp vector.

        Concurrent identical fetches COALESCE: while one thread's scan is
        in flight, any other thread arriving with the same (plan key,
        window, grid) joins it instead of dispatching its own — N
        concurrent queries over the same resident blocks cost ONE device
        dispatch (the in-flight execution is the batching window; a
        joiner records plan_coalesced and zero deviceDispatches)."""
        if not plan_enabled():
            raise Ineligible("plan-disabled")
        if staged_forced():
            raise Ineligible("force-staged")
        db = self.db
        namespaces = getattr(db, "namespaces", None)
        if namespaces is None or self.namespace not in namespaces:
            raise Ineligible("remote-storage")
        pool = getattr(db, "resident_pool", None)
        if pool is None or not pool.enabled:
            raise Ineligible("resident-pool-disabled")
        ns = namespaces[self.namespace]
        if ns.index is None:
            raise Ineligible("no-index")
        seg, arrays = self._single_device_segment(ns.index, fetch_lo, fetch_hi)
        blocks = self._block_set(ns, pool, fetch_lo, fetch_hi)
        if not blocks:
            raise Ineligible("no-sealed-blocks")
        for shard in ns.shards:
            if shard.has_buffered_overlap(fetch_lo, fetch_hi):
                raise Ineligible("buffer-overlay")

        from .m3_storage import matchers_to_index_query

        q = matchers_to_index_query(matchers)
        t_grid = pad_pow2(len(grid), _SENTINEL_GRID)
        key = (
            self.namespace,
            tuple((m.name, m.op, m.value) for m in matchers),
            tuple(blocks),
            t_grid,
        )
        from . import stats

        fkey = key + (fetch_lo, fetch_hi, grid.tobytes(), lookback_nanos)
        with self._lock:
            fl = self._flights.get(fkey)
            leader = fl is None
            if leader:
                fl = self._flights[fkey] = _Flight()
        if not leader:
            # join the in-flight identical scan: this query dispatches
            # nothing (device_dispatches ticks on the leader's thread)
            fl.event.wait()
            if fl.error is not None:
                if isinstance(fl.error, Ineligible):
                    # a fresh instance per thread: the reason is shared,
                    # the traceback must not be
                    raise Ineligible(fl.error.reason)
                raise fl.error
            self.coalesced += 1
            _M_COALESCED.inc()
            stats.add(plan_coalesced=1)
            matched, values, datapoints, err_rows = fl.result
            # own values array per follower: the err-lane stitch and
            # downstream transforms may write rows
            return matched, np.array(values, copy=True), datapoints, err_rows
        try:
            result = self._run_leader(
                key, q, seg, arrays, ns, pool, blocks, t_grid,
                fetch_lo, fetch_hi, grid, lookback_nanos,
            )
            fl.result = result
            return result
        except BaseException as exc:
            fl.error = exc
            raise
        finally:
            with self._lock:
                self._flights.pop(fkey, None)
            fl.event.set()

    def _run_leader(self, key, q, seg, arrays, ns, pool, blocks, t_grid,
                    fetch_lo: int, fetch_hi: int, grid: np.ndarray,
                    lookback_nanos: int):
        from . import stats

        with self._lock:
            entry = self._cache.get(key)
            if entry is not None:
                self._cache.move_to_end(key)
        if entry is not None and self._valid(entry, seg, arrays, ns, pool):
            self.hits += 1
            _M_HITS.inc()
            stats.add(plan_hits=1)
            return self._execute(
                entry, ns, fetch_lo, fetch_hi, grid, lookback_nanos
            )
        entry = self._build(q, seg, arrays, ns, pool, blocks, t_grid)
        with self._lock:
            self._cache[key] = entry
            self._cache.move_to_end(key)
            while len(self._cache) > _cache_cap():
                self._cache.popitem(last=False)
        self.misses += 1
        _M_MISSES.inc()
        stats.add(plan_misses=1)
        return self._execute(entry, ns, fetch_lo, fetch_hi, grid,
                             lookback_nanos)

    # -- eligibility pieces ------------------------------------------------

    @staticmethod
    def _single_device_segment(index, fetch_lo: int, fetch_hi: int):
        """The range's ONE sealed, device-resident index segment (the v1
        plan scope; more segments or mutable docs degrade staged)."""
        with index.lock:
            segs = []
            mutable_docs = 0
            for bs in sorted(index.blocks):
                if bs + index.block_size <= fetch_lo or bs >= fetch_hi:
                    continue
                blk = index.blocks[bs]
                mutable_docs += len(blk.mutable)
                segs.extend(blk.sealed)
        if mutable_docs:
            raise Ineligible("mutable-index-block")
        if not segs:
            raise Ineligible("no-index-segment")
        if len(segs) > 1:
            raise Ineligible("multi-segment")
        seg = segs[0]
        arrays = getattr(seg, "_arrays", None)
        if arrays is None:
            raise Ineligible("index-not-resident")
        return seg, arrays

    def _block_set(self, ns, pool, fetch_lo: int, fetch_hi: int):
        """Sorted ((shard, block_start, volume)) of every sealed fileset
        overlapping the range — each must be complete-admitted so a
        page-table miss means 'series absent', never 'not resident'."""
        out = []
        bsz = ns.opts.block_size_nanos
        for shard in ns.shards:
            newest: dict[int, int] = {}
            for fid in shard.filesets():
                if fid.block_start + bsz <= fetch_lo or fid.block_start >= fetch_hi:
                    continue
                cur = newest.get(fid.block_start)
                if cur is None or fid.volume > cur:
                    newest[fid.block_start] = fid.volume
            for bs, vol in newest.items():
                if not pool.is_complete(self.namespace, shard.id, bs, vol):
                    raise Ineligible("non-resident-block")
                out.append((shard.id, bs, vol))
        return sorted(out, key=lambda t: (t[1], t[0]))

    def _stamp(self, seg, arrays, ns, pool):
        return (
            id(seg), id(arrays),
            pool.evictions, pool.invalidations,
            tuple(sh.fileset_epoch for sh in ns.shards),
        )

    def _valid(self, entry, seg, arrays, ns, pool) -> bool:
        return entry.stamp == self._stamp(seg, arrays, ns, pool)

    # -- build -------------------------------------------------------------

    def _build(self, q, seg, arrays, ns, pool, blocks, t_grid) -> _PlanEntry:
        import jax.numpy as jnp

        from ..cache.block_cache import BlockKey
        from ..index.device import kernels
        from ..ops.chunked import window_words

        # stamp BEFORE the page-table walk: an eviction racing the walk
        # would otherwise free (and let a re-admission reuse) pages this
        # plan just copied into its tables while the stamp still matched
        # current counters — the in-lease re-check in _execute must see
        # a stamp OLDER than any such churn and refuse to serve
        stamp = self._stamp(seg, arrays, ns, pool)
        leaves: list = []
        ranges: list = []
        ast = _ast_shape(q, arrays, leaves, ranges)

        docs = list(seg.docs)
        n_docs = len(docs)
        if n_docs == 0:
            raise Ineligible("empty-segment")
        block_starts = sorted({bs for _, bs, _ in blocks})
        vols = {(sh, bs): vol for sh, bs, vol in blocks}
        n_blocks = len(block_starts)

        # per-(doc, block) lane plan vectors; one trailing all-zero doc
        # row block is the compaction sentinel (padding slots decode
        # nothing). The doc axis pads to the bitmap's natural 32-aligned
        # width so the bit unpack and the compaction agree on capacity.
        n_docs_pad = arrays.n_words * 32
        rows = (n_docs_pad + 1) * n_blocks
        chunk_k = 0
        max_span = 0
        max_pages = 1
        max_side = 1
        lane_entries: list = [None] * rows
        for d, doc in enumerate(docs):
            shard = ns.shard_for(doc.id)
            for b, bs in enumerate(block_starts):
                vol = vols.get((shard.id, bs))
                if vol is None:
                    continue  # this shard has no fileset for the block
                e = pool.get(
                    BlockKey(self.namespace, shard.id, bytes(doc.id), bs, vol)
                )
                if e is None:
                    # complete-admitted fileset without the series: the
                    # series is absent from the block — empty lane
                    continue
                if e.n_chunks <= 0 or not e.side_pages:
                    raise Ineligible("missing-side-planes")
                if chunk_k == 0:
                    chunk_k = e.chunk_k
                elif e.chunk_k != chunk_k:
                    raise Ineligible("mixed-chunk-k")
                lane_entries[d * n_blocks + b] = (e, bs)
                max_span = max(max_span, e.max_span_bits)
                max_pages = max(max_pages, len(e.pages))
                max_side = max(max_side, len(e.side_pages))
        if chunk_k == 0:
            raise Ineligible("no-resident-lanes")

        o = pool.options
        cw = window_words(max_span)
        extra = -(-cw // o.page_words) + 1
        lp = max_pages + extra
        sl = max_side
        c = max(
            (e.n_chunks for e, _ in filter(None, lane_entries)), default=1
        )
        t_pages = np.zeros((rows, lp), np.int32)
        t_sides = np.zeros((rows, sl), np.int32)
        t_chunks = np.zeros(rows, np.int32)
        t_bits = np.zeros(rows, np.int32)
        t_bhi = np.zeros(rows, np.uint32)
        t_blo = np.zeros(rows, np.uint32)
        for i, le in enumerate(lane_entries):
            if le is None:
                continue
            e, bs = le
            pool._check_entry(e)
            t_pages[i, : len(e.pages)] = e.pages
            t_sides[i, : len(e.side_pages)] = e.side_pages
            t_chunks[i] = e.n_chunks
            t_bits[i] = e.num_bits
            t_bhi[i] = (int(bs) >> 32) & 0xFFFFFFFF
            t_blo[i] = int(bs) & 0xFFFFFFFF

        # query-key inputs (values are fixed per entry: matchers carry
        # them, and the entry is keyed by matchers)
        bq = len(leaves)
        bq_pad = kernels.pad_pow2(bq) if bq else 0
        values = [v for _, v in leaves] + [b""] * (bq_pad - bq)
        if bq:
            q_keys, q_lens = kernels.build_query_keys(values, arrays.k_words)
        else:
            q_keys = np.zeros((0, arrays.k_words), np.uint32)
            q_lens = np.zeros(0, np.int32)
        q_lo = np.zeros(bq_pad, np.int32)
        q_hi = np.zeros_like(q_lo)
        for i, (field, _v) in enumerate(leaves):
            start, count = arrays.fields.get(field, (0, 0, 0, 0))[:2]
            q_lo[i], q_hi[i] = start, start + count
        r_lo = np.asarray([lo for lo, _ in ranges] or [0], np.int32)
        r_hi = np.asarray([hi for _, hi in ranges] or [0], np.int32)

        entry = _PlanEntry()
        entry.ast = ast
        entry.seg = seg
        entry.arrays = arrays
        # today cap == n_docs_pad (decode capacity = bitmap width); cap
        # is the seam an adaptive-capacity policy would shrink for
        # persistently sparse matches
        entry.cap = n_docs_pad
        entry.chunk_k = chunk_k
        entry.stamp = stamp
        entry.dims = (
            arrays.n_words, n_docs_pad, entry.cap, n_blocks, c, chunk_k,
            cw, lp, sl, o.page_words, o.side_page_chunks, t_grid,
        )
        entry.inputs = (
            jnp.asarray(q_keys), jnp.asarray(q_lens),
            jnp.asarray(q_lo), jnp.asarray(q_hi),
            jnp.asarray(r_lo), jnp.asarray(r_hi),
        )
        entry.tables = (
            jnp.asarray(t_pages), jnp.asarray(t_sides),
            jnp.asarray(t_chunks), jnp.asarray(t_bits),
            jnp.asarray(t_bhi), jnp.asarray(t_blo),
        )
        entry.fn = _build_program(ast, entry.dims)
        # matched-doc cache: the matched set is a pure function of the
        # segment arrays and the matcher values, both frozen while the
        # stamp holds — so the per-doc tag materialization (the cost that
        # dominated large fan-outs host-side) is paid ONCE per plan, not
        # per query
        entry.matched = None
        return entry

    # -- execute -----------------------------------------------------------

    def _execute(self, entry, ns, fetch_lo: int, fetch_hi: int,
                 grid: np.ndarray, lookback_nanos: int):
        from ..index.device import kernels

        pool = self.db.resident_pool
        t_grid = entry.dims[-1]
        g = np.zeros(t_grid, np.int64)
        g[: len(grid)] = grid
        if len(grid):
            g[len(grid):] = grid[-1]  # padded steps discarded below
        gu = g.astype(np.uint64)
        g_hi = (gu >> np.uint64(32)).astype(np.uint32)
        g_lo = (gu & np.uint64(0xFFFFFFFF)).astype(np.uint32)

        def pair(v: int):
            v = int(v) & ((1 << 64) - 1)
            return (
                np.uint32(v >> 32),
                np.uint32(v & 0xFFFFFFFF),
            )

        with pool.read_lease():
            # buffer snapshots under the lease (same discipline as the
            # staged resident scan); the plan tables reference page
            # indices, so the validity stamp re-checks INSIDE the lease:
            # an eviction + re-admission racing between run()'s check and
            # this snapshot could otherwise hand reused pages to stale
            # table rows. Under the lease the snapshot is immutable
            # (admissions take the functional-copy path), so a stamp that
            # holds here holds for the whole dispatch.
            with pool._lock:
                if pool._words is None or pool._side is None:
                    raise Ineligible("resident-pool-empty")
                words, side = pool._words, pool._side
            if entry.stamp != self._stamp(
                entry.seg, entry.arrays, ns, pool
            ):
                raise Ineligible("raced-invalidation")
            with PROF.dispatch((entry.ast, entry.dims)) as d:
                outs = d.done(entry.fn(
                    entry.arrays.term_keys, entry.arrays.term_lens,
                    entry.arrays.post_idx, entry.arrays.post_data,
                    entry.arrays.all_words,
                    *entry.inputs,
                    words, side,
                    *entry.tables,
                    g_hi, g_lo, pair(fetch_lo), pair(fetch_hi),
                    pair(lookback_nanos),
                ))
        (bitmap, n_matched, counts, err, g_vh, g_vl, g_pf, g_ml, ok) = (
            # m3lint: disable=M3L010 -- sanctioned end-of-query host finalize: the ONE device->host readback after the fused program dispatch
            np.asarray(x) for x in outs
        )
        n = int(n_matched)
        if n > entry.cap:
            # more matches than the compiled capacity (a doc-count jump
            # since build): fall back for THIS query; the stamp check
            # rebuilds at the larger size next time
            raise Ineligible("plan-capacity")
        if entry.matched is not None and len(entry.matched[0]) == n:
            matched = entry.matched
        else:
            from ..block.core import SeriesMeta

            doc_ids = kernels.bitmap_to_docids(bitmap)[:n]
            docs = entry.seg.docs
            matched_docs = [docs[int(i)] for i in doc_ids]
            matched = (
                matched_docs,
                [SeriesMeta(tags=d.fields) for d in matched_docs],
            )
            entry.matched = matched
        t = len(grid)
        values = _finalize_grid(
            g_vh[:n, :t], g_vl[:n, :t], g_pf[:n, :t], g_ml[:n, :t],
            ok[:n, :t],
        )
        datapoints = int(counts[:n].sum())
        err_rows = np.nonzero(err[:n])[0]
        return matched, values, datapoints, err_rows
