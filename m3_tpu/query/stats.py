"""Per-query cost accounting: stage timings + scan counters + slow-query ring.

Reference shape: Monarch-style per-query accounting grafted onto the
reference's query instrumentation (src/query/executor emits per-phase tally
timers; src/x/debug serves recent state). One ``QueryStats`` record rides a
thread-local through engine → storage adapter → database for the duration of
a query, capturing:

- per-stage wall seconds: ``parse``, ``index_resolve``, ``fetch``,
  ``decode``, ``exec`` (fetch CONTAINS index_resolve + decode when storage
  is local — stages are attributed, not disjoint; ``exec`` is total minus
  fetch minus parse);
- series / datapoints / bytes scanned, decoded-block cache hit/miss counts.

Completed records land in a bounded ring served by the coordinator's
``/debug/slow_queries`` route and feed the ``m3tpu_query_*`` histogram/
counter families, so BENCH rounds can attribute a latency regression to the
stage that actually moved.

Configuration:

    M3_TPU_SLOW_QUERY_CAPACITY   ring capacity (default 256)
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..utils.instrument import DEFAULT as METRICS

# buckets matched to query latencies (sub-ms cached instant queries up to
# multi-second cold range scans)
QUERY_DURATION_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


@dataclass
class QueryStats:
    """One query's cost record (mutable while the query runs)."""

    query: str = ""
    start_unix_nanos: int = 0
    duration_secs: float = 0.0
    stages: dict = field(default_factory=dict)  # stage -> seconds
    # live-introspection fields (the /debug/active_queries surface): the
    # namespace the owning engine serves, and which stage the query is in
    # RIGHT NOW (set/restored by the ``stage()`` context; None between
    # stages) — only meaningful while the query is in flight
    namespace: str = ""
    current_stage: str | None = None
    # who is charged for this query (query/tenants.py): stamped from the
    # thread's tenant context at start(); "" renders as anonymous
    tenant: str = ""
    # admission-scheduler surface (query/scheduler.py): where the query is
    # in its lifecycle — "queued" (waiting for an admission slot),
    # "running", "hedged" (running, and the client fan-out issued a hedged
    # backup replica request for it), or "shed" (rejected by the
    # scheduler) — plus the priority score the scheduler computed for it
    # (higher = shed sooner)
    queue_state: str = "running"
    priority: float = 0.0
    # the enforcer-chain scope that 422'd the query (query/tenant/global),
    # None when no cost limit tripped — a rejection must leave a record
    # trail, not just an HTTP status
    limit_exceeded: str | None = None
    series_scanned: int = 0
    datapoints_scanned: int = 0
    bytes_scanned: int = 0
    # the subset of bytes_scanned served from HBM residency (the rest
    # streamed) — the ledger's streamed-vs-resident split
    resident_bytes: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    # HBM-residency routing (m3_tpu/resident/): fetches served by the
    # decode-from-HBM path vs streamed fallbacks while the pool was on
    resident_hits: int = 0
    resident_misses: int = 0
    # device index routing (m3_tpu/index/device/): per-SEGMENT counts —
    # hits answered by the device executor, misses that fell back to the
    # host executor (evicted / not admitted / device error)
    index_device_hits: int = 0
    index_device_misses: int = 0
    # one-dispatch fused query pipeline (query/plan.py): fetches served
    # by a cached device plan (hits), plans (re)built this query
    # (misses), and fetches that degraded to the staged path (fallbacks,
    # EXPLAIN records the reason per cause)
    plan_hits: int = 0
    plan_misses: int = 0
    plan_fallbacks: int = 0
    # scan coalescing (query/plan.py singleflight): fetches served by
    # JOINING another concurrent query's in-flight device scan — this
    # query paid zero dispatches for them
    plan_coalesced: int = 0
    # profiled device-kernel dispatches charged to this query (the
    # KernelProfiler seam, utils/instrument.set_dispatch_counter): the
    # fused pipeline's acceptance metric — a warm plan-served query is
    # exactly ONE dispatch
    device_dispatches: int = 0
    trace_id: str | None = None  # links the record to its /debug/traces tree
    error: str | None = None
    # EXPLAIN support: when record_routing is on (Engine.explain sets it),
    # the storage adapter appends one entry per (series, block) routing
    # decision — {"series", "block", "path", "reason"} with path
    # "resident"|"streamed". Bounded by ROUTING_CAP; overflow is counted,
    # never silent.
    record_routing: bool = False
    routing: list = field(default_factory=list)
    routing_dropped: int = 0

    def add_stage(self, name: str, secs: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + secs

    def to_dict(self) -> dict:
        out = {
            "query": self.query,
            "namespace": self.namespace,
            "tenant": self.tenant,
            "queueState": self.queue_state,
            "priority": self.priority,
            "limitExceeded": self.limit_exceeded,
            "startUnixNanos": self.start_unix_nanos,
            "durationSecs": self.duration_secs,
            "stages": dict(self.stages),
            "seriesScanned": self.series_scanned,
            "datapointsScanned": self.datapoints_scanned,
            "bytesScanned": self.bytes_scanned,
            "cacheHits": self.cache_hits,
            "cacheMisses": self.cache_misses,
            "residentHits": self.resident_hits,
            "residentMisses": self.resident_misses,
            "indexDeviceHits": self.index_device_hits,
            "indexDeviceMisses": self.index_device_misses,
            "planHits": self.plan_hits,
            "planMisses": self.plan_misses,
            "planFallbacks": self.plan_fallbacks,
            "planCoalesced": self.plan_coalesced,
            "deviceDispatches": self.device_dispatches,
            "traceId": self.trace_id,
            "error": self.error,
        }
        if self.record_routing:
            out["routing"] = list(self.routing)
            out["routingDropped"] = self.routing_dropped
        objectives = slo_objectives_for(self.tenant)
        if objectives is not None:
            out["sloObjectives"] = objectives
        return out


# routing entries per EXPLAIN record: enough to show every block of a
# real dashboard query, small enough that a 10M-series selector can't
# balloon the record (the drop count says how much is missing)
ROUTING_CAP = 256


def add_routing(series_id, block_start, path: str, reason: str = "") -> None:
    """Record one resident-vs-streamed routing decision against this
    thread's active EXPLAIN record (no-op for normal queries — one
    attribute check — so the storage adapter calls it unconditionally)."""
    st = current()
    if st is None or not st.record_routing:
        return
    if len(st.routing) >= ROUTING_CAP:
        st.routing_dropped += 1
        return
    if isinstance(series_id, bytes):
        series_id = series_id.decode("utf-8", "replace")
    st.routing.append(
        {
            "series": series_id,
            "block": block_start,
            "path": path,
            "reason": reason,
        }
    )


# SLO-objective join seam: the SLO engine (m3_tpu/slo/engine.py)
# installs a callable ``(tenant) -> [objective names]`` so debug query
# rows (/debug/slow_queries, /debug/active_queries) can say which SLOs a
# query counts against. A settable seam, not an import — the query layer
# must not depend on the SLO package.
_SLO_RESOLVER = None


def set_slo_resolver(fn) -> None:
    global _SLO_RESOLVER
    _SLO_RESOLVER = fn


def slo_objectives_for(tenant: str) -> list | None:
    """Objective names the tenant's queries count against, or None when
    no SLO engine is running (debug rows omit the field entirely then —
    absent means 'no SLO plane', [] means 'none apply')."""
    resolver = _SLO_RESOLVER
    if resolver is None:
        return None
    try:
        return list(resolver(tenant))
    except Exception:
        return None


_local = threading.local()


def current() -> QueryStats | None:
    """The query record active on this thread (None outside a query)."""
    return getattr(_local, "stats", None)


def start(query: str) -> QueryStats | None:
    """Begin a record for this thread's query; returns None when a record
    is already active (nested evaluation — e.g. federation re-entry —
    accumulates into the outer query's record instead of shadowing it)."""
    if current() is not None:
        return None
    st = QueryStats(query=query, start_unix_nanos=time.time_ns())
    from ..utils.trace import TRACER
    from . import tenants

    ctx = TRACER.current_context()
    if ctx is not None:
        st.trace_id = f"{ctx['trace_id']:016x}"
    st.tenant = tenants.current() or tenants.DEFAULT_TENANT
    _local.stats = st
    ACTIVE.register(st)
    return st


def finish(st: QueryStats, duration_secs: float, error: str | None = None) -> None:
    """Seal + publish a record: ring, histograms, counters."""
    _local.stats = None
    ACTIVE.unregister(st)
    st.current_stage = None
    st.duration_secs = duration_secs
    st.error = error
    fetch = st.stages.get("fetch", 0.0)
    parse = st.stages.get("parse", 0.0)
    st.add_stage("exec", max(duration_secs - fetch - parse, 0.0))
    RING.record(st)
    METRICS.counter("query_total", "completed queries").inc()
    if error is not None:
        METRICS.counter("query_errors_total", "failed queries").inc()
    # availability SLI events (m3_tpu/slo): served-vs-failed per tenant.
    # Sheds are counted (with reason) by the scheduler; 422 cost
    # rejections are the CALLER's query being over budget, not the
    # service being down — they count in neither class.
    if st.queue_state != "shed" and st.limit_exceeded is None:
        from . import tenants as _tenants

        tenant = st.tenant or _tenants.DEFAULT_TENANT
        if error is None:
            METRICS.counter(
                "query_completed_total",
                "queries served successfully (availability SLI good events)",
                labels={"tenant": tenant},
            ).inc()
        else:
            METRICS.counter(
                "query_failed_total",
                "queries that failed serving (availability SLI bad events; "
                "sheds counted separately in query_shed_total)",
                labels={"tenant": tenant},
            ).inc()
    # the trace id rides as an exemplar: a slow query_duration_seconds
    # bucket links to its stitched tree (/debug/traces) and its
    # /debug/slow_queries record via the shared id
    METRICS.histogram(
        "query_duration_seconds", "query wall time", buckets=QUERY_DURATION_BUCKETS
    ).observe(duration_secs, trace_id=st.trace_id, tenant=st.tenant or None)
    for stage, secs in st.stages.items():
        METRICS.histogram(
            "query_stage_duration_seconds",
            "per-stage query wall time",
            labels={"stage": stage},
            buckets=QUERY_DURATION_BUCKETS,
        ).observe(secs, trace_id=st.trace_id)
    METRICS.counter("query_series_scanned_total").inc(st.series_scanned)
    METRICS.counter("query_datapoints_scanned_total").inc(st.datapoints_scanned)
    METRICS.counter("query_bytes_scanned_total").inc(st.bytes_scanned)
    if st.resident_hits:
        METRICS.counter(
            "query_resident_hits_total", "fetches served from HBM residency"
        ).inc(st.resident_hits)
    if st.resident_misses:
        METRICS.counter(
            "query_resident_misses_total",
            "fetches that fell back to the streamed path with the pool on",
        ).inc(st.resident_misses)
    if st.index_device_hits:
        METRICS.counter(
            "query_index_device_hits_total",
            "index segments resolved by the device executor",
        ).inc(st.index_device_hits)
    if st.index_device_misses:
        METRICS.counter(
            "query_index_device_misses_total",
            "index segments that fell back to the host executor with the "
            "device tier on",
        ).inc(st.index_device_misses)
    # per-tenant attribution (query/tenants.py): every completed query
    # charges its scan work — and any cost-limit rejection — against the
    # tenant stamped at start(); decode device-seconds are charged
    # separately by the KernelProfiler attribution hook (sampled)
    from . import tenants

    tenants.LEDGER.charge(
        st.tenant or tenants.DEFAULT_TENANT,
        queries=1,
        series=st.series_scanned,
        datapoints=st.datapoints_scanned,
        bytes_streamed=max(st.bytes_scanned - st.resident_bytes, 0),
        bytes_resident=st.resident_bytes,
        cache_hits=st.cache_hits,
        cache_misses=st.cache_misses,
        limit_rejections=1 if st.limit_exceeded else 0,
        errors=1 if error is not None else 0,
    )


def add(
    series: int = 0,
    datapoints: int = 0,
    bytes_: int = 0,
    cache_hits: int = 0,
    cache_misses: int = 0,
    resident_hits: int = 0,
    resident_misses: int = 0,
    resident_bytes: int = 0,
    index_device_hits: int = 0,
    index_device_misses: int = 0,
    plan_hits: int = 0,
    plan_misses: int = 0,
    plan_fallbacks: int = 0,
    plan_coalesced: int = 0,
) -> None:
    """Charge scan counters against this thread's active query (no-op
    outside a query, so storage paths call it unconditionally)."""
    st = current()
    if st is None:
        return
    st.series_scanned += series
    st.datapoints_scanned += datapoints
    st.bytes_scanned += bytes_
    st.cache_hits += cache_hits
    st.cache_misses += cache_misses
    st.resident_hits += resident_hits
    st.resident_misses += resident_misses
    st.resident_bytes += resident_bytes
    st.index_device_hits += index_device_hits
    st.index_device_misses += index_device_misses
    st.plan_hits += plan_hits
    st.plan_misses += plan_misses
    st.plan_fallbacks += plan_fallbacks
    st.plan_coalesced += plan_coalesced


def _count_dispatch(_kernel: str) -> None:
    """KernelProfiler seam (utils/instrument.set_dispatch_counter):
    every profiled device-kernel dispatch charges the query record
    active on the dispatching thread — the fused pipeline's ONE-dispatch
    acceptance metric. No-op between queries (current() is None)."""
    st = current()
    if st is not None:
        st.device_dispatches += 1


from ..utils.instrument import set_dispatch_counter as _set_dispatch_counter

_set_dispatch_counter(_count_dispatch)


class _Stage:
    """``with stage("fetch"):`` — accumulates elapsed wall time onto the
    active record and marks it as the query's CURRENT stage (what
    /debug/active_queries shows for an in-flight query); no-op (still
    times nothing extra) outside a query."""

    __slots__ = ("name", "_t0", "_prev")

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "_Stage":
        self._t0 = time.perf_counter()
        st = current()
        self._prev = st.current_stage if st is not None else None
        if st is not None:
            st.current_stage = self.name
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        st = current()
        if st is not None:
            st.add_stage(self.name, time.perf_counter() - self._t0)
            st.current_stage = self._prev


def stage(name: str) -> _Stage:
    return _Stage(name)


class ActiveQueryRegistry:
    """Bounded registry of IN-FLIGHT queries (the live sibling of the
    slow-query ring): every ``start()`` registers the thread's record,
    ``finish()`` removes it, and :meth:`dump` snapshots what is running
    RIGHT NOW — trace id, namespace, elapsed wall time, and the stage the
    query is currently in. Joined by traceId to ``/debug/slow_queries``
    and ``/debug/traces``, so "what is the coordinator doing" and "why was
    that slow" are the same id space.

    Bounded: past ``capacity`` concurrent queries, new registrations are
    dropped (counted in ``overflows``, surfaced in the dump) — the debug
    surface must not become the memory leak it exists to diagnose."""

    def __init__(self, capacity: int = 512) -> None:
        self.capacity = max(int(capacity), 1)
        self._live: dict[int, QueryStats] = {}
        self._lock = threading.Lock()
        self._overflows = 0

    def register(self, st: QueryStats) -> None:
        with self._lock:
            if len(self._live) >= self.capacity:
                self._overflows += 1
                return
            self._live[id(st)] = st

    def unregister(self, st: QueryStats) -> None:
        with self._lock:
            self._live.pop(id(st), None)

    def dump(self) -> dict:
        with self._lock:
            records = list(self._live.values())
            overflows = self._overflows
        now = time.time_ns()
        rows = []
        for st in records:
            row = {
                "query": st.query,
                "namespace": st.namespace,
                "tenant": st.tenant,
                "queueState": st.queue_state,
                "priority": st.priority,
                "traceId": st.trace_id,
                "stage": st.current_stage,
                "startUnixNanos": st.start_unix_nanos,
                "elapsedSecs": max(now - st.start_unix_nanos, 0) / 1e9,
            }
            objectives = slo_objectives_for(st.tenant)
            if objectives is not None:
                row["sloObjectives"] = objectives
            rows.append(row)
        rows.sort(key=lambda r: -r["elapsedSecs"])
        return {"queries": rows, "overflows": overflows}


# process-wide in-flight registry (what /debug/active_queries serves)
ACTIVE = ActiveQueryRegistry()


class SlowQueryRing:
    """Bounded ring of completed query records, newest last (the x/debug
    'recent expensive work' role). ``record`` is called for every completed
    query; consumers filter/sort by duration — at debug-endpoint rates the
    full ring is cheaper to ship than to pre-rank."""

    def __init__(self, capacity: int = 256) -> None:
        self._ring: deque[QueryStats] = deque(maxlen=max(capacity, 1))
        self._lock = threading.Lock()

    def record(self, st: QueryStats) -> None:
        with self._lock:
            self._ring.append(st)

    def dump(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            records = list(self._ring)
        if limit is not None:
            records = records[-limit:] if limit > 0 else []
        return [r.to_dict() for r in records]


def _env_capacity() -> int:
    try:
        return int(os.environ.get("M3_TPU_SLOW_QUERY_CAPACITY", "256"))
    except ValueError:
        return 256


# process-wide ring (what /debug/slow_queries serves); engines record here
# unless constructed with their own ring
RING = SlowQueryRing(_env_capacity())
