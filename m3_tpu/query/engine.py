"""Query engine: PromQL AST evaluation over storage blocks.

Reference: /root/reference/src/query/executor/ — Engine.ExecuteExpr
(engine.go:116) builds the transform DAG and pushes blocks through it
(state.go:183). Here evaluation is direct recursion over the AST: every node
produces a dense [S, T] block (or a [T] scalar row), so each transform is one
vectorized call into m3_tpu.query.functions — the DAG collapses into array
ops the device can fuse.
"""

from __future__ import annotations

import re as _re
import threading
import time
from dataclasses import dataclass, replace
from typing import Protocol

import numpy as np

from ..block.core import Bounds, SeriesMeta, Tags, make_tags
from .functions import aggregation as A
from .functions import binary as B
from .functions import linear as L
from .functions import temporal as T
from .functions import temporal_fused as TF
from .promql import (
    Aggregation,
    BinaryOp,
    Call,
    Expr,
    Matcher,
    NumberLiteral,
    RangeSelector,
    StringLiteral,
    Subquery,
    Unary,
    VectorSelector,
    parse,
)

NANOS = 1_000_000_000
DEFAULT_LOOKBACK = 5 * 60 * NANOS


@dataclass
class Result:
    """A evaluated vector: values [S, T] + per-series metas (scalar results
    have one row and scalar=True)."""

    values: np.ndarray
    metas: list[SeriesMeta]
    scalar: bool = False


class Storage(Protocol):
    """storage.Storage seam (src/query/storage/types.go): raw series fetch."""

    def fetch(
        self, matchers: list[Matcher], start_nanos: int, end_nanos: int
    ) -> list[tuple[Tags, np.ndarray, np.ndarray]]:
        """→ [(tags, times i64[n], values f64[n])] raw samples, times sorted."""
        ...


def consolidate_row(
    times: np.ndarray, vals: np.ndarray, grid: np.ndarray,
    lookback_nanos: int,
) -> np.ndarray:
    """ONE series' samples onto the step grid: value at step = last
    sample in (t-lookback, t]. This is THE 'last' consolidation rule —
    the fused device plan (query/plan.py) replicates it in-program and
    the err-lane host stitch (m3_storage) reuses it directly, so the
    bit-identity contract has exactly one host definition."""
    if len(times) == 0:
        return np.full(len(grid), np.nan)
    idx = np.searchsorted(times, grid, side="right") - 1
    ok = idx >= 0
    sample_t = times[np.maximum(idx, 0)]
    ok &= grid - sample_t < lookback_nanos
    return np.where(ok, vals[np.maximum(idx, 0)], np.nan)


def consolidate(
    series: list[tuple[Tags, np.ndarray, np.ndarray]],
    bounds: Bounds,
    lookback_nanos: int,
) -> Result:
    """Samples → step grid: value at step = last sample in (t-lookback, t]
    (storage/m3/consolidators/ 'last' consolidation)."""
    s = len(series)
    grid = bounds.timestamps()
    out = np.full((s, bounds.steps), np.nan)
    metas = []
    for i, (tags, times, vals) in enumerate(series):
        metas.append(SeriesMeta(tags=tags))
        if len(times) == 0:
            continue
        out[i] = consolidate_row(times, vals, grid, lookback_nanos)
    return Result(values=out, metas=metas)


class Engine:
    """executor.Engine equivalent."""

    def __init__(
        self,
        storage: Storage,
        lookback_nanos: int = DEFAULT_LOOKBACK,
        limits=None,
        global_enforcer=None,
        tenant_enforcers=None,
        scheduler=None,
    ) -> None:
        self.storage = storage
        self.lookback = lookback_nanos
        # per-query cost limits (query/cost.py); None = unlimited
        self.limits = limits
        self.global_enforcer = global_enforcer
        # per-tenant middle scopes (query/tenants.TenantEnforcers): when
        # set, the enforcer chain is query → tenant → global and each
        # query's parent scope resolves from the thread's tenant context
        self.tenant_enforcers = tenant_enforcers
        # admission scheduler (query/scheduler.QueryScheduler): when set,
        # every TOP-LEVEL query passes cost-aware admission before eval
        # and may be shed with a typed QueryShedError; nested evaluation
        # rides the outer query's slot
        self.scheduler = scheduler
        self._enforcer = threading.local()

    def query_range(
        self, query: str, start_nanos: int, end_nanos: int, step_nanos: int
    ) -> Result:
        # per-query accounting (stats.py): one QueryStats record rides a
        # thread-local through engine → storage → database; sealed records
        # feed the slow-query ring + m3tpu_query_* metrics. ``qs`` is None
        # on nested evaluation (an outer query already owns the record).
        from . import stats

        qs = stats.start(query)
        if qs is not None:
            # the storage adapter knows which namespace this engine serves
            # (M3Storage.namespace); /debug/active_queries shows it
            qs.namespace = str(getattr(self.storage, "namespace", "") or "")
        t_start = time.perf_counter()
        err: str | None = None
        admitted = False
        try:
            with stats.stage("parse"):
                ast = parse(query)
            steps = int((end_nanos - start_nanos) // step_nanos) + 1
            bounds = Bounds(start_nanos, step_nanos, steps)
            # @ start()/end() bind to the TOP-LEVEL query range, even inside
            # subqueries (prometheus PreprocessExpr)
            _bind_at(ast, bounds)
            if qs is not None and self.scheduler is not None:
                # cost-aware admission: may block briefly, may shed with
                # a typed QueryShedError (coordinator → HTTP 503); only
                # top-level queries admit — nested evaluation rides the
                # outer query's slot. The queue wait is bounded by the
                # caller's propagated deadline when one is ambient
                # (coordinator timeout param/header), else by the
                # scheduler's own max_queue_wait.
                from ..net.resilience import current_deadline

                self.scheduler.admit(
                    query, steps, record=qs, deadline=current_deadline()
                )
                admitted = True
            parent = self.global_enforcer
            if self.tenant_enforcers is not None:
                # the per-tenant middle scope: charges flow query →
                # tenant → global, so a runaway tenant trips its own
                # ceiling before it can exhaust the fleet's
                from . import tenants

                parent = self.tenant_enforcers.scope_for(tenants.current())
            if self.limits is None and parent is None:
                return self._eval(ast, bounds)
            from .cost import Enforcer, QueryLimits

            enforcer = Enforcer(
                self.limits if self.limits is not None else QueryLimits(),
                parent,
            )
            self._enforcer.current = enforcer
            try:
                return self._eval(ast, bounds)
            finally:
                self._enforcer.current = None
                enforcer.release()
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            from .cost import QueryLimitError

            if isinstance(exc, QueryLimitError):
                # the slow-query ring must show WHICH chain scope 422'd
                # the query — stamped on the thread's active record (the
                # outer record when this frame is a nested evaluation)
                cur = stats.current()
                if cur is not None:
                    cur.limit_exceeded = exc.scope
            raise
        finally:
            if admitted:
                self.scheduler.release()
                if err is None and qs is not None:
                    # feed the matched-series observation back into the
                    # cost memo so the NEXT run of this query is priced
                    # from evidence instead of the optimistic default
                    self.scheduler.observe(query, qs.series_scanned)
            if qs is not None:
                stats.finish(qs, time.perf_counter() - t_start, error=err)

    def query_instant(self, query: str, time_nanos: int) -> Result:
        return self.query_range(query, time_nanos, time_nanos, NANOS)

    def explain(
        self, query: str, start_nanos: int, end_nanos: int, step_nanos: int
    ) -> dict:
        """EXPLAIN: evaluate the query while recording where its time and
        data went — the full per-stage timing record (parse /
        index_resolve / fetch / decode / exec), scan counters, and the
        resident-vs-streamed routing decision PER (series, block) from the
        storage adapter (why a block streamed: buffered overlay, evicted
        page, pool off). Returns the sealed stats record plus a result
        summary; the record also lands in the slow-query ring and metrics
        like any query, prefixed ``EXPLAIN`` so dashboards can exclude it.
        """
        from . import stats

        st = stats.start(f"EXPLAIN {query}")
        if st is not None:
            st.record_routing = True
            st.namespace = str(getattr(self.storage, "namespace", "") or "")
        t_start = time.perf_counter()
        err: str | None = None
        try:
            r = self.query_range(query, start_nanos, end_nanos, step_nanos)
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            if st is not None:
                stats.finish(st, time.perf_counter() - t_start, error=err)
        out = st.to_dict() if st is not None else {"query": query}
        out["result"] = {
            "series": len(r.metas),
            "steps": int(np.asarray(r.values).shape[1]) if len(r.metas) else 0,
        }
        return out

    def scan_totals(self, query: str, start_nanos: int, end_nanos: int) -> dict:
        """Flagship raw-sample scan as an engine surface: ``query`` must
        be a plain vector selector (e.g. ``metric{job="x"}``) — the
        totals are whole-block reductions over the matched series'
        compressed streams, NOT PromQL semantics (no step grid, no
        lookback consolidation). Routing is the storage adapter's:
        decode-from-HBM when every matched block is resident
        (m3_tpu/resident/), streamed upload+decode otherwise; the result's
        ``path`` field and the per-query resident_hit counters
        (query/stats.py) record which way it went."""
        from . import stats

        storage_scan = getattr(self.storage, "scan_totals", None)
        if storage_scan is None:
            raise ValueError("storage does not support scan_totals")
        qs = stats.start(f"scan_totals({query})")
        if qs is not None:
            qs.namespace = str(getattr(self.storage, "namespace", "") or "")
        t_start = time.perf_counter()
        err: str | None = None
        try:
            with stats.stage("parse"):
                ast = parse(query)
            if not isinstance(ast, VectorSelector):
                raise ValueError("scan_totals: query must be a vector selector")
            if ast.at_nanos is not None or ast.offset_nanos:
                raise ValueError("scan_totals: @/offset modifiers unsupported")
            matchers = list(ast.matchers)
            if ast.name:
                matchers.append(Matcher("__name__", "=", ast.name))
            with stats.stage("fetch"):
                return storage_scan(matchers, start_nanos, end_nanos)
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            if qs is not None:
                stats.finish(qs, time.perf_counter() - t_start, error=err)

    # --- evaluation ---

    def _fetch(self, sel: VectorSelector, bounds: Bounds, extra_steps: int = 0) -> Result:
        start = bounds.start_nanos - sel.offset_nanos - extra_steps * bounds.step_nanos
        end = bounds.start_nanos - sel.offset_nanos + bounds.step_nanos * bounds.steps
        matchers = list(sel.matchers)
        if sel.name:
            matchers.append(Matcher("__name__", "=", sel.name))
        from . import stats

        b = Bounds(start, bounds.step_nanos, bounds.steps + extra_steps)
        # one-dispatch fused pipeline (query/plan.py): when the storage
        # adapter can serve fetch+consolidate as ONE device program it
        # returns the finished step grid — bit-identical to the staged
        # consolidate below — and the per-series host loops disappear.
        # None = ineligible (reason recorded in EXPLAIN routing): run
        # the staged path unchanged.
        grid_fetch = getattr(self.storage, "fetch_grid", None)
        if grid_fetch is not None:
            with stats.stage("fetch"):
                fused = grid_fetch(
                    matchers, start - self.lookback, end, b.timestamps(),
                    self.lookback,
                )
            if fused is not None:
                # metas arrive as ready SeriesMeta (cached on the plan
                # entry — matched set is invariant while the plan holds)
                metas, values, datapoints = fused
                stats.add(series=len(metas), datapoints=datapoints)
                enforcer = getattr(self._enforcer, "current", None)
                if enforcer is not None:
                    enforcer.charge(len(metas), datapoints)
                return Result(values, list(metas))
        with stats.stage("fetch"):
            raw = self.storage.fetch(matchers, start - self.lookback, end)
        stats.add(
            series=len(raw), datapoints=sum(len(t) for _, t, _ in raw)
        )
        enforcer = getattr(self._enforcer, "current", None)
        if enforcer is not None:
            # charge fetched series + raw datapoints against the query's
            # cost limits (query/cost.go block accounting)
            enforcer.charge(len(raw), sum(len(t) for _, t, _ in raw))
        return consolidate(raw, b, self.lookback)

    def _eval(self, e: Expr, bounds: Bounds) -> Result:
        if isinstance(e, NumberLiteral):
            return Result(
                np.full((1, bounds.steps), e.value), [SeriesMeta(())], scalar=True
            )
        if isinstance(e, VectorSelector):
            if e.at_nanos is not None:
                # @ pins evaluation: one instant, broadcast across steps
                at = _resolve_at(e.at_nanos, bounds)
                r = self._fetch(
                    replace(e, at_nanos=None), Bounds(at, bounds.step_nanos, 1)
                )
                return Result(
                    np.tile(np.asarray(r.values), (1, bounds.steps)), r.metas
                )
            return self._fetch(e, bounds)
        if isinstance(e, Unary):
            r = self._eval(e.expr, bounds)
            vals = -r.values if e.op == "-" else r.values
            return Result(vals, r.metas, r.scalar)
        if isinstance(e, Call):
            return self._call(e, bounds)
        if isinstance(e, Aggregation):
            return self._aggregate(e, bounds)
        if isinstance(e, BinaryOp):
            return self._binary(e, bounds)
        if isinstance(e, RangeSelector):
            raise ValueError("promql: range selector outside function call")
        if isinstance(e, StringLiteral):
            raise ValueError("promql: string literal in value position")
        raise TypeError(f"unhandled node {e!r}")

    # temporal functions taking a range argument
    _TEMPORAL = {
        "rate": lambda v, w, s: T.rate(v, w, s),
        "irate": lambda v, w, s: T.irate(v, w, s),
        "increase": lambda v, w, s: T.increase(v, w, s),
        "delta": lambda v, w, s: T.delta(v, w, s),
        "idelta": lambda v, w, s: T.idelta(v, w, s),
        "deriv": lambda v, w, s: T.deriv(v, w, s),
        "resets": lambda v, w, s: T.resets(v, w),
        "changes": lambda v, w, s: T.changes(v, w),
        "sum_over_time": lambda v, w, s: T.sum_over_time(v, w),
        "count_over_time": lambda v, w, s: T.count_over_time(v, w),
        "avg_over_time": lambda v, w, s: T.avg_over_time(v, w),
        "min_over_time": lambda v, w, s: T.min_over_time(v, w),
        "max_over_time": lambda v, w, s: T.max_over_time(v, w),
        "last_over_time": lambda v, w, s: T.last_over_time(v, w),
        "stddev_over_time": lambda v, w, s: T.stddev_over_time(v, w),
        "stdvar_over_time": lambda v, w, s: T.stdvar_over_time(v, w),
        "present_over_time": lambda v, w, s: np.where(
            np.asarray(T.count_over_time(v, w)) > 0, 1.0, np.nan
        ),
    }

    def _range_arg(self, arg: Expr, bounds: Bounds):
        """Range-vector argument → (values, metas, window, step_secs, post).

        ``values`` is a [S, N] sample matrix whose trailing axis a temporal
        function slides its ``window`` over; ``post`` maps the function's
        [S, N - window + 1] output onto the query's [S, steps] grid (identity
        for plain ranges; column re-selection for subqueries, whose samples
        are at the subquery step; broadcast for @-pinned ranges).
        """
        if isinstance(arg, RangeSelector):
            sel = arg.vector
            window = int(arg.range_nanos // bounds.step_nanos) + 1
            extra = window - 1
            step_s = bounds.step_nanos / NANOS
            if sel.at_nanos is not None:
                at = _resolve_at(sel.at_nanos, bounds)
                b_at = Bounds(
                    at - extra * bounds.step_nanos, bounds.step_nanos, window
                )
                r = self._fetch(replace(sel, at_nanos=None), b_at)

                def post(out, _steps=bounds.steps):
                    return np.tile(out[:, -1:], (1, _steps))

                return np.asarray(r.values), r.metas, window, step_s, post
            r = self._fetch(sel, bounds, extra_steps=extra)
            return np.asarray(r.values), r.metas, window, step_s, lambda out: out
        if isinstance(arg, Subquery):
            return self._subquery_arg(arg, bounds)
        raise ValueError("promql: function requires a range vector")

    def _subquery_arg(self, sq: Subquery, bounds: Bounds):
        sub_step = sq.step_nanos or bounds.step_nanos
        if sq.at_nanos is not None:
            at = _resolve_at(sq.at_nanos, bounds)
            outer_ts = np.asarray([at - sq.offset_nanos], np.int64)
        else:
            outer_ts = bounds.timestamps() - sq.offset_nanos
        window = int(sq.range_nanos // sub_step) + 1
        # inner evaluation instants align to ABSOLUTE multiples of the
        # subquery step (prometheus subquery semantics), so results don't
        # shift with the outer query's start; the grid extends DOWN past
        # (outer_min - range) so the earliest outer step has a full window
        lo = int(outer_ts.min()) - sq.range_nanos
        g_start = (lo // sub_step) * sub_step
        n_sub = int((int(outer_ts.max()) - g_start) // sub_step) + 1
        sub_bounds = Bounds(g_start, sub_step, n_sub)
        inner = self._eval(sq.expr, sub_bounds)
        vals = np.asarray(inner.values)
        grid = sub_bounds.timestamps()
        # output column j of a sliced temporal result ends at grid[j + w - 1];
        # each outer step wants the window ending at the last grid point <= t
        idx = np.searchsorted(grid, outer_ts, side="right") - 1
        cols = np.clip(idx - (window - 1), 0, max(n_sub - window, 0))

        if sq.at_nanos is not None:

            def post(out, _steps=bounds.steps, _cols=cols):
                return np.tile(out[:, _cols[:1]], (1, _steps))

        else:

            def post(out, _cols=cols):
                return out[:, _cols]

        return vals, inner.metas, window, sub_step / NANOS, post

    def _call(self, e: Call, bounds: Bounds) -> Result:
        name = e.func
        if name in self._TEMPORAL:
            vals, metas, w, step_s, post = self._range_arg(e.args[0], bounds)
            if name in TF.FUSABLE:
                # one VMEM-resident pallas pass on TPU (temporal_fused.py)
                out = np.asarray(TF.temporal_apply(name, vals, w, step_s))
            else:
                out = np.asarray(self._TEMPORAL[name](vals, w, step_s))
            return Result(post(out[:, w - 1 :]), metas)
        if name == "quantile_over_time":
            q = _number(e.args[0])
            vals, metas, w, step_s, post = self._range_arg(e.args[1], bounds)
            out = np.asarray(T.quantile_over_time(vals, w, q))
            return Result(post(out[:, w - 1 :]), metas)
        if name == "predict_linear":
            vals, metas, w, step_s, post = self._range_arg(e.args[0], bounds)
            t = _number(e.args[1])
            out = np.asarray(T.predict_linear(vals, w, step_s, t))
            return Result(post(out[:, w - 1 :]), metas)
        if name == "holt_winters":
            vals, metas, w, step_s, post = self._range_arg(e.args[0], bounds)
            sf, tf = _number(e.args[1]), _number(e.args[2])
            out = np.asarray(T.holt_winters(vals, w, sf, tf))
            return Result(post(out[:, w - 1 :]), metas)
        if name == "label_replace":
            return self._label_replace(e, bounds)
        if name == "label_join":
            return self._label_join(e, bounds)
        if name in L.MATH_FNS:
            r = self._eval(e.args[0], bounds)
            return Result(np.asarray(L.MATH_FNS[name](r.values)), r.metas, r.scalar)
        if name == "round":
            r = self._eval(e.args[0], bounds)
            to = _number(e.args[1]) if len(e.args) > 1 else 1.0
            return Result(np.asarray(L.round_to(r.values, to)), r.metas, r.scalar)
        if name == "clamp_min":
            r = self._eval(e.args[0], bounds)
            return Result(np.asarray(L.clamp_min(r.values, _number(e.args[1]))), r.metas)
        if name == "clamp_max":
            r = self._eval(e.args[0], bounds)
            return Result(np.asarray(L.clamp_max(r.values, _number(e.args[1]))), r.metas)
        if name == "clamp":
            r = self._eval(e.args[0], bounds)
            lo, hi = _number(e.args[1]), _number(e.args[2])
            return Result(np.clip(r.values, lo, hi), r.metas)
        if name == "histogram_quantile":
            q = _number(e.args[0])
            r = self._eval(e.args[1], bounds)
            index, bnds, metas = L.histogram_buckets(r.metas)
            out = np.asarray(L.histogram_quantile(q, r.values, index, bnds))
            return Result(out, metas)
        if name in ("sort", "sort_desc"):
            r = self._eval(e.args[0], bounds)
            order = L.sort_series(r.values, descending=name == "sort_desc")
            return Result(r.values[order], [r.metas[i] for i in order])
        if name == "absent":
            r = self._eval(e.args[0], bounds)
            vals = np.asarray(A.absent(r.values))
            return Result(vals, [SeriesMeta(())])
        if name == "scalar":
            r = self._eval(e.args[0], bounds)
            if len(r.metas) == 1:
                return Result(r.values[:1], [SeriesMeta(())], scalar=True)
            return Result(np.full((1, bounds.steps), np.nan), [SeriesMeta(())], scalar=True)
        if name == "vector":
            r = self._eval(e.args[0], bounds)
            return Result(r.values, [SeriesMeta(())])
        if name == "time":
            t = bounds.timestamps() / NANOS
            return Result(t[None, :].astype(np.float64), [SeriesMeta(())], scalar=True)
        if name == "timestamp":
            r = self._eval(e.args[0], bounds)
            t = (bounds.timestamps() / NANOS)[None, :]
            out = np.where(np.isnan(np.asarray(r.values)), np.nan, t)
            return Result(out, r.metas)
        if name in ("day_of_month", "day_of_week", "days_in_month", "hour", "minute", "month", "year"):
            if e.args:
                r = self._eval(e.args[0], bounds)
                vals, metas = r.values, r.metas
            else:
                vals = (bounds.timestamps() / NANOS)[None, :].astype(np.float64)
                metas = [SeriesMeta(())]
            return Result(L.datetime_fn(name, vals), metas)
        raise ValueError(f"promql: unsupported function {name}")

    # --- label manipulation (functions/label_replace, label_join —
    # src/query/functions/tag/ in the reference) ---

    def _label_replace(self, e: Call, bounds: Bounds) -> Result:
        r = self._eval(e.args[0], bounds)
        dst, repl, src, regex_s = (_string(a) for a in e.args[1:5])
        pattern = _re.compile(regex_s)
        metas = []
        for m in r.metas:
            tags = dict(m.tags)
            val = tags.get(src.encode(), b"").decode()
            mm = pattern.fullmatch(val)
            if mm is not None:
                new = mm.expand(_promql_template(repl))
                if new:
                    tags[dst.encode()] = new.encode()
                else:
                    tags.pop(dst.encode(), None)
            metas.append(
                SeriesMeta(tags=tuple(sorted(tags.items())), name=m.name)
            )
        return Result(r.values, metas, r.scalar)

    def _label_join(self, e: Call, bounds: Bounds) -> Result:
        r = self._eval(e.args[0], bounds)
        dst = _string(e.args[1])
        sep = _string(e.args[2])
        srcs = [_string(a).encode() for a in e.args[3:]]
        metas = []
        for m in r.metas:
            tags = dict(m.tags)
            joined = sep.encode().join(tags.get(sl, b"") for sl in srcs)
            if joined:
                tags[dst.encode()] = joined
            else:
                tags.pop(dst.encode(), None)
            metas.append(
                SeriesMeta(tags=tuple(sorted(tags.items())), name=m.name)
            )
        return Result(r.values, metas, r.scalar)

    def _aggregate(self, e: Aggregation, bounds: Bounds) -> Result:
        r = self._eval(e.expr, bounds)
        matching = [g.encode() for g in e.grouping]
        layout = A.group_by_tags(r.metas, matching or None, e.without)
        vals = np.asarray(r.values)
        if e.op in ("topk", "bottomk"):
            k = int(_number(e.param))
            fn = A.topk if e.op == "topk" else A.bottomk
            out = np.asarray(fn(vals, layout, k))
            keep = ~np.all(np.isnan(out), axis=1)
            return Result(out[keep], [r.metas[i] for i in np.nonzero(keep)[0]])
        if e.op == "quantile":
            out = np.asarray(A.grouped_quantile(vals, layout, _number(e.param)))
            return Result(out, layout.metas)
        if e.op == "count_values":
            label = e.param.value if isinstance(e.param, StringLiteral) else "value"
            out, metas = A.count_values(vals, r.metas, label.encode())
            return Result(out, metas)
        fn = {
            "sum": A.grouped_sum,
            "min": A.grouped_min,
            "max": A.grouped_max,
            "avg": A.grouped_avg,
            "count": A.grouped_count,
            "stddev": A.grouped_stddev,
            "stdvar": A.grouped_stdvar,
        }[e.op]
        return Result(np.asarray(fn(vals, layout)), layout.metas)

    def _binary(self, e: BinaryOp, bounds: Bounds) -> Result:
        lhs = self._eval(e.lhs, bounds)
        rhs = self._eval(e.rhs, bounds)
        lv, rv = np.asarray(lhs.values), np.asarray(rhs.values)

        if e.op in ("and", "or", "unless"):
            m = B.VectorMatching(on=e.on, matching_labels=tuple(x.encode() for x in e.matching_labels))
            fn = {"and": B.logical_and, "or": B.logical_or, "unless": B.logical_unless}[e.op]
            vals, metas = fn(lv, rv, lhs.metas, rhs.metas, m)
            return Result(np.asarray(vals), metas)

        is_comp = e.op in B.COMP_FNS
        # scalar op scalar / vector op scalar / scalar op vector
        if lhs.scalar and rhs.scalar:
            out = self._apply_scalar(e, lv, rv)
            return Result(out, lhs.metas, scalar=True)
        if rhs.scalar:
            out = self._apply_scalar(e, lv, rv)  # broadcast [1,T]
            return Result(out, _drop_names(lhs.metas) if not is_comp else lhs.metas)
        if lhs.scalar:
            if is_comp and not e.return_bool:
                cond = B.COMP_FNS[e.op](lv, rv)
                return Result(np.where(cond, rv, np.nan), rhs.metas)
            out = self._apply_scalar(e, lv, rv)
            return Result(out, _drop_names(rhs.metas) if not is_comp else rhs.metas)

        # vector op vector
        m = B.VectorMatching(on=e.on, matching_labels=tuple(x.encode() for x in e.matching_labels))
        if e.group_left or e.group_right:
            return self._binary_grouped(e, m, lhs, rhs, lv, rv, is_comp)
        tl, tr, metas = B.intersect(m, lhs.metas, rhs.metas)
        if is_comp:
            out = np.asarray(B.comparison(e.op, lv, rv, tl, tr, e.return_bool))
            metas = [lhs.metas[i] for i in tl] if not e.return_bool else metas
            return Result(out, metas)
        out = np.asarray(B.arithmetic(e.op, lv, rv, tl, tr))
        return Result(out, metas)

    def _binary_grouped(self, e: BinaryOp, m, lhs, rhs, lv, rv, is_comp) -> Result:
        """Many-to-one vector matching (binary.go group_left/group_right):
        each series on the MANY side joins at most one series on the ONE
        side; result keeps the many side's labels, plus any carried labels
        named in group_left(...)/group_right(...)."""
        many, one = (lhs, rhs) if e.group_left else (rhs, lhs)
        one_index: dict = {}
        for j, om in enumerate(one.metas):
            key = B._match_key(om.tags, m)
            if key in one_index:
                raise ValueError(
                    "promql: many-to-many matching: multiple series on the "
                    f"'one' side share match key {key!r}"
                )
            one_index[key] = j
        take_many, take_one, metas = [], [], []
        include = [x.encode() for x in e.include_labels]
        for i, mm in enumerate(many.metas):
            j = one_index.get(B._match_key(mm.tags, m))
            if j is None:
                continue
            take_many.append(i)
            take_one.append(j)
            tags = dict(mm.tags)
            if not is_comp:
                # arithmetic drops the metric name, as in the 1:1 path
                tags.pop(b"__name__", None)
            if include:
                one_tags = dict(one.metas[j].tags)
                for lbl in include:
                    if lbl in one_tags:
                        tags[lbl] = one_tags[lbl]
                    else:
                        tags.pop(lbl, None)
            metas.append(SeriesMeta(tags=tuple(sorted(tags.items())), name=mm.name))
        tm = np.asarray(take_many, np.int32)
        to = np.asarray(take_one, np.int32)
        # orient back to lhs/rhs for the (non-commutative) operator
        tl, tr = (tm, to) if e.group_left else (to, tm)
        if is_comp:
            out = np.asarray(B.comparison(e.op, lv, rv, tl, tr, e.return_bool))
            return Result(out, metas)
        out = np.asarray(B.arithmetic(e.op, lv, rv, tl, tr))
        return Result(out, metas)

    def _apply_scalar(self, e: BinaryOp, lv, rv):
        if e.op in B.COMP_FNS:
            cond = B.COMP_FNS[e.op](lv, rv)
            if e.return_bool:
                return cond.astype(np.float64)
            return np.where(cond, lv, np.nan)
        return np.asarray(B.ARITH_FNS[e.op](np.asarray(lv), np.asarray(rv)))


def _drop_names(metas: list[SeriesMeta]) -> list[SeriesMeta]:
    return [
        SeriesMeta(tags=tuple((k, v) for k, v in m.tags if k != b"__name__"), name=m.name)
        for m in metas
    ]


def _number(e: Expr | None) -> float:
    if isinstance(e, NumberLiteral):
        return e.value
    if isinstance(e, Unary) and isinstance(e.expr, NumberLiteral):
        return -e.expr.value if e.op == "-" else e.expr.value
    raise ValueError("promql: expected a number literal")


def _string(e: Expr) -> str:
    if isinstance(e, StringLiteral):
        return e.value
    raise ValueError("promql: expected a string literal")


def _bind_at(e, bounds: Bounds) -> None:
    """Resolve @ start()/end() sentinels against the top-level query bounds
    (must run before evaluation: subqueries evaluate their inner expression
    under DIFFERENT bounds, which must not re-bind start/end)."""
    if isinstance(e, VectorSelector):
        if isinstance(e.at_nanos, str):
            e.at_nanos = _resolve_at(e.at_nanos, bounds)
    elif isinstance(e, RangeSelector):
        _bind_at(e.vector, bounds)
    elif isinstance(e, Subquery):
        if isinstance(e.at_nanos, str):
            e.at_nanos = _resolve_at(e.at_nanos, bounds)
        _bind_at(e.expr, bounds)
    elif isinstance(e, Call):
        for a in e.args:
            _bind_at(a, bounds)
    elif isinstance(e, Aggregation):
        _bind_at(e.expr, bounds)
        if e.param is not None:
            _bind_at(e.param, bounds)
    elif isinstance(e, BinaryOp):
        _bind_at(e.lhs, bounds)
        _bind_at(e.rhs, bounds)
    elif isinstance(e, Unary):
        _bind_at(e.expr, bounds)


def _resolve_at(at, bounds: Bounds) -> int:
    """@ modifier value → absolute nanos (start()/end() use the bounds)."""
    if at == "start":
        return bounds.start_nanos
    if at == "end":
        return bounds.start_nanos + bounds.step_nanos * (bounds.steps - 1)
    return int(at)


def _promql_template(repl: str) -> str:
    """label_replace templates use $1/${name}; re.Match.expand wants \\1."""
    out = _re.sub(r"\$\{(\w+)\}", r"\\g<\1>", repl)
    return _re.sub(r"\$(\d+)", r"\\\1", out)
