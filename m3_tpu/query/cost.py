"""Per-query cost limits: bound series / datapoints a single query touches.

Reference: /root/reference/src/query/cost/ + src/x/cost/ — a per-query
ChainedEnforcer charges each fetched block against query-, tenant- and
global-scope limits and aborts the query when exceeded (the coordinator
returns 4xx instead of OOMing the node). Here an Enforcer accumulates
charges from the engine's fetch path; the chain above it is built from
:class:`GlobalEnforcer` scopes — the per-tenant middle scope
(query/tenants.TenantEnforcers) parents on the fleet-wide global scope,
so one tenant's runaway scan 422s without starving the fleet.

Every rejection is counted in ``m3tpu_query_limit_exceeded_total{scope}``
(scope = query | tenant | global): a 422 must leave a metric trail, or
capacity incidents look like silent client errors.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..utils.instrument import DEFAULT as METRICS


class QueryLimitError(Exception):
    """Cost limit exceeded — maps to HTTP 422 at the coordinator.
    ``scope`` names the chain link that tripped (query/tenant/global)."""

    def __init__(self, what: str, used: int, limit: int,
                 scope: str = "query") -> None:
        super().__init__(
            f"query limit exceeded: {what} used {used} > limit {limit}"
        )
        self.what = what
        self.used = used
        self.limit = limit
        self.scope = scope


def limit_error(scope: str, what: str, used: int, limit: int) -> QueryLimitError:
    """Build (and COUNT) a limit rejection — the one constructor every
    raise site uses, so the {scope} counter can't drift from the 422s."""
    METRICS.counter(
        "query_limit_exceeded_total",
        "cost-limit rejections (the 422 trail)",
        labels={"scope": scope},
    ).inc()
    return QueryLimitError(what, used, limit, scope=scope)


@dataclass
class QueryLimits:
    """0 = unlimited (cost/config defaults)."""

    max_series: int = 0
    max_datapoints: int = 0


class Enforcer:
    """Accumulates charges for ONE query (cost.ChainedEnforcer child)."""

    def __init__(self, limits: QueryLimits, parent: "GlobalEnforcer | None" = None):
        self.limits = limits
        self.parent = parent
        self.series = 0
        self.datapoints = 0

    def charge(self, series: int, datapoints: int) -> None:
        # record + propagate BEFORE checking own limits, so release() always
        # returns exactly what the parent received
        self.series += series
        self.datapoints += datapoints
        if self.parent is not None:
            self.parent.charge(series, datapoints)
        if 0 < self.limits.max_series < self.series:
            raise limit_error(
                "query", "series", self.series, self.limits.max_series
            )
        if 0 < self.limits.max_datapoints < self.datapoints:
            raise limit_error(
                "query", "datapoints", self.datapoints,
                self.limits.max_datapoints,
            )

    def release(self) -> None:
        if self.parent is not None:
            self.parent.release(self.series, self.datapoints)


class GlobalEnforcer:
    """A long-lived concurrent-cost scope: the sum over in-flight queries
    charged into it. With no ``parent`` it is the chain's GLOBAL ceiling;
    with one it is a middle scope (the per-tenant link) propagating up —
    charges are recorded and propagated BEFORE the local check (the
    Enforcer discipline), so release() unwinds exactly what each link
    received even when a check partway up the chain raised."""

    def __init__(self, limits: QueryLimits, scope: str = "global",
                 what: str = "global",
                 parent: "GlobalEnforcer | None" = None) -> None:
        self.limits = limits
        self.scope = scope
        self.what = what
        self.parent = parent
        self._lock = threading.Lock()
        self.series = 0
        self.datapoints = 0

    def charge(self, series: int, datapoints: int) -> None:
        with self._lock:
            self.series += series
            self.datapoints += datapoints
            used_s, used_d = self.series, self.datapoints
        if self.parent is not None:
            self.parent.charge(series, datapoints)
        if 0 < self.limits.max_series < used_s:
            raise limit_error(
                self.scope, f"{self.what} series", used_s,
                self.limits.max_series,
            )
        if 0 < self.limits.max_datapoints < used_d:
            raise limit_error(
                self.scope, f"{self.what} datapoints", used_d,
                self.limits.max_datapoints,
            )

    def release(self, series: int, datapoints: int) -> None:
        with self._lock:
            self.series -= series
            self.datapoints -= datapoints
        if self.parent is not None:
            self.parent.release(series, datapoints)
