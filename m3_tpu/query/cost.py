"""Per-query cost limits: bound series / datapoints a single query touches.

Reference: /root/reference/src/query/cost/ + src/x/cost/ — a per-query
ChainedEnforcer charges each fetched block against query- and global-scope
limits and aborts the query when exceeded (the coordinator returns 4xx
instead of OOMing the node). Here an Enforcer accumulates charges from the
engine's fetch path; the global scope is a shared parent enforcer.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


class QueryLimitError(Exception):
    """Cost limit exceeded — maps to HTTP 422 at the coordinator."""

    def __init__(self, what: str, used: int, limit: int) -> None:
        super().__init__(
            f"query limit exceeded: {what} used {used} > limit {limit}"
        )
        self.what = what
        self.used = used
        self.limit = limit


@dataclass
class QueryLimits:
    """0 = unlimited (cost/config defaults)."""

    max_series: int = 0
    max_datapoints: int = 0


class Enforcer:
    """Accumulates charges for ONE query (cost.ChainedEnforcer child)."""

    def __init__(self, limits: QueryLimits, parent: "GlobalEnforcer | None" = None):
        self.limits = limits
        self.parent = parent
        self.series = 0
        self.datapoints = 0

    def charge(self, series: int, datapoints: int) -> None:
        # record + propagate BEFORE checking own limits, so release() always
        # returns exactly what the parent received
        self.series += series
        self.datapoints += datapoints
        if self.parent is not None:
            self.parent.charge(series, datapoints)
        if 0 < self.limits.max_series < self.series:
            raise QueryLimitError("series", self.series, self.limits.max_series)
        if 0 < self.limits.max_datapoints < self.datapoints:
            raise QueryLimitError(
                "datapoints", self.datapoints, self.limits.max_datapoints
            )

    def release(self) -> None:
        if self.parent is not None:
            self.parent.release(self.series, self.datapoints)


class GlobalEnforcer:
    """Process-wide concurrent-cost ceiling (the global scope of the
    chained enforcer): the sum over in-flight queries."""

    def __init__(self, limits: QueryLimits) -> None:
        self.limits = limits
        self._lock = threading.Lock()
        self.series = 0
        self.datapoints = 0

    def charge(self, series: int, datapoints: int) -> None:
        with self._lock:
            self.series += series
            self.datapoints += datapoints
            if 0 < self.limits.max_series < self.series:
                raise QueryLimitError(
                    "global series", self.series, self.limits.max_series
                )
            if 0 < self.limits.max_datapoints < self.datapoints:
                raise QueryLimitError(
                    "global datapoints", self.datapoints, self.limits.max_datapoints
                )

    def release(self, series: int, datapoints: int) -> None:
        with self._lock:
            self.series -= series
            self.datapoints -= datapoints
