"""Remote coordinator federation: query another coordinator as a Storage.

Reference: /root/reference/src/query/remote/ — coordinators federate reads
across clusters/regions by speaking a compressed series protocol to each
other (compressed_codecs.go over gRPC). Here the transport is the Prometheus
remote-read endpoint every coordinator already serves
(/api/v1/prom/remote/read, snappy + prompb): RemoteCoordinatorStorage
implements the engine's Storage seam, so a FanoutStorage can mix local
namespaces and remote coordinators in one query.
"""

from __future__ import annotations

import urllib.request
from dataclasses import dataclass

import numpy as np

from ..gen import prompb_pb2 as prompb
from ..utils.snappy import compress, decompress
from .promql import Matcher

MS = 1_000_000

_OP_TO_TYPE = {"=": 0, "!=": 1, "=~": 2, "!~": 3}


@dataclass
class RemoteCoordinatorStorage:
    """Engine Storage backed by a peer coordinator's remote-read API."""

    base_url: str  # e.g. "http://coordinator-west:7201"
    timeout: float = 30.0

    def fetch(self, matchers: list[Matcher], start_nanos: int, end_nanos: int):
        req = prompb.ReadRequest()
        q = req.queries.add()
        q.start_timestamp_ms = start_nanos // MS
        q.end_timestamp_ms = max((end_nanos - 1) // MS, q.start_timestamp_ms)
        for m in matchers:
            q.matchers.add(
                type=_OP_TO_TYPE[m.op], name=m.name, value=m.value
            )
        body = compress(req.SerializeToString())
        http_req = urllib.request.Request(
            f"{self.base_url}/api/v1/prom/remote/read",
            data=body,
            headers={"Content-Type": "application/x-protobuf"},
        )
        with urllib.request.urlopen(http_req, timeout=self.timeout) as resp:
            raw = decompress(resp.read())
        read_resp = prompb.ReadResponse()
        read_resp.ParseFromString(raw)
        out = []
        for result in read_resp.results:
            for ts in result.timeseries:
                tags = tuple(
                    sorted(
                        (l.name.encode(), l.value.encode()) for l in ts.labels
                    )
                )
                times = np.asarray(
                    [s.timestamp * MS for s in ts.samples], np.int64
                )
                vals = np.asarray([s.value for s in ts.samples], np.float64)
                out.append((tags, times, vals))
        return out
