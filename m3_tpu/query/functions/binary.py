"""Binary operations with vector matching.

Reference: /root/reference/src/query/functions/binary/ — arithmetic
(arithmetic.go), comparison with optional BOOL modifier (comparison.go),
set logic and/or/unless (and.go, or.go, unless.go), all driven by the
intersect() series matcher (binary.go:233+). Matching is host-side (tag
hashing, data-independent); the per-step math is elementwise on gathered
series rows.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ...block.core import SeriesMeta, Tags

__all__ = [
    "VectorMatching",
    "intersect",
    "arithmetic",
    "comparison",
    "logical_and",
    "logical_or",
    "logical_unless",
    "ARITH_FNS",
    "COMP_FNS",
]

NAME_TAG = b"__name__"


@dataclass
class VectorMatching:
    """on/ignoring matching (binary/types.go VectorMatching)."""

    on: bool = False  # True: match only on `matching_labels`
    matching_labels: tuple[bytes, ...] = ()
    # group_left/group_right many-to-one is intentionally deferred until the
    # PromQL front end lands; intersect() is one-to-one like processBothSeries.


def _match_key(tags: Tags, matching: VectorMatching) -> Tags:
    labels = matching.matching_labels
    if matching.on:
        return tuple((k, v) for k, v in tags if k in labels)
    return tuple((k, v) for k, v in tags if k not in labels and k != NAME_TAG)


def intersect(
    matching: VectorMatching,
    l_metas: list[SeriesMeta],
    r_metas: list[SeriesMeta],
) -> tuple[np.ndarray, np.ndarray, list[SeriesMeta]]:
    """(take_left, corresponding_right, out_metas) — binary.go intersect()."""
    r_index: dict[Tags, int] = {}
    for i, rm in enumerate(r_metas):
        r_index.setdefault(_match_key(rm.tags, matching), i)
    take_left, take_right, metas = [], [], []
    for i, lm in enumerate(l_metas):
        key = _match_key(lm.tags, matching)
        j = r_index.get(key)
        if j is not None:
            take_left.append(i)
            take_right.append(j)
            metas.append(SeriesMeta(tags=key, name=lm.name))
    return (
        np.asarray(take_left, np.int32),
        np.asarray(take_right, np.int32),
        metas,
    )


def _go_mod(x, y):
    # Go math.Mod semantics: result sign follows x (arithmetic.go uses math.Mod)
    return x - jnp.trunc(x / y) * y


ARITH_FNS = {
    "+": lambda x, y: x + y,
    "-": lambda x, y: x - y,
    "*": lambda x, y: x * y,
    "/": lambda x, y: x / y,
    "^": lambda x, y: jnp.power(x, y),
    "%": _go_mod,
}


COMP_FNS = {
    "==": lambda x, y: x == y,
    "!=": lambda x, y: x != y,
    ">": lambda x, y: x > y,
    "<": lambda x, y: x < y,
    ">=": lambda x, y: x >= y,
    "<=": lambda x, y: x <= y,
}


def _gather(values, idx):
    return jnp.take(jnp.asarray(values), jnp.asarray(idx), axis=0)


def arithmetic(op: str, l_values, r_values, take_left, take_right):
    lv = _gather(l_values, take_left)
    rv = _gather(r_values, take_right)
    return ARITH_FNS[op](lv, rv)


def comparison(op: str, l_values, r_values, take_left, take_right, return_bool: bool):
    """comparison.go: filter mode keeps lhs value where true else NaN; BOOL
    mode is toFloat(cmp) with plain IEEE NaN comparisons — NaN > y is 0,
    NaN != y is 1, exactly like the reference's Go float comparisons."""
    lv = _gather(l_values, take_left)
    rv = _gather(r_values, take_right)
    cond = COMP_FNS[op](lv, rv)
    if return_bool:
        return cond.astype(lv.dtype)
    return jnp.where(cond, lv, jnp.nan)


def _key_set(metas: list[SeriesMeta], matching: VectorMatching):
    return {_match_key(m.tags, matching) for m in metas}


def logical_and(l_values, r_values, l_metas, r_metas, matching: VectorMatching):
    """and.go: keep lhs series whose match key exists in rhs AND rhs has a
    value at that step."""
    r_keys = {}
    for j, rm in enumerate(r_metas):
        r_keys.setdefault(_match_key(rm.tags, matching), j)
    take_l, take_r = [], []
    metas = []
    for i, lm in enumerate(l_metas):
        j = r_keys.get(_match_key(lm.tags, matching))
        if j is not None:
            take_l.append(i)
            take_r.append(j)
            metas.append(lm)
    if not take_l:
        return jnp.zeros((0, np.asarray(l_values).shape[1]), jnp.asarray(l_values).dtype), []
    lv = _gather(l_values, np.asarray(take_l, np.int32))
    rv = _gather(r_values, np.asarray(take_r, np.int32))
    return jnp.where(jnp.isnan(rv), jnp.nan, lv), metas


def logical_or(l_values, r_values, l_metas, r_metas, matching: VectorMatching):
    """or.go: all lhs series (with NaN steps filled from a matching rhs
    series, or.go:88-95), plus rhs series whose key is absent from lhs."""
    r_keys: dict[Tags, int] = {}
    for j, rm in enumerate(r_metas):
        r_keys.setdefault(_match_key(rm.tags, matching), j)
    lv = jnp.asarray(l_values)
    r_idx = np.asarray(
        [r_keys.get(_match_key(lm.tags, matching), -1) for lm in l_metas], np.int32
    )
    if len(r_metas) and (r_idx >= 0).any():
        rvv = _gather(r_values, np.maximum(r_idx, 0))
        matched = jnp.asarray(r_idx >= 0)[:, None]
        lv = jnp.where(matched & jnp.isnan(lv), rvv, lv)
    l_keys = _key_set(l_metas, matching)
    keep_r = [j for j, rm in enumerate(r_metas) if _match_key(rm.tags, matching) not in l_keys]
    if keep_r:
        out = jnp.concatenate([lv, _gather(r_values, np.asarray(keep_r, np.int32))], axis=0)
    else:
        out = lv
    metas = list(l_metas) + [r_metas[j] for j in keep_r]
    return out, metas


def logical_unless(l_values, r_values, l_metas, r_metas, matching: VectorMatching):
    """unless.go: lhs series whose key is NOT in rhs; where key IS in rhs,
    keep lhs values only at steps where rhs is NaN."""
    r_keys = {}
    for j, rm in enumerate(r_metas):
        r_keys.setdefault(_match_key(rm.tags, matching), j)
    lv = jnp.asarray(l_values)
    # rhs row index per lhs series, -1 when unmatched
    r_idx = np.asarray(
        [r_keys.get(_match_key(lm.tags, matching), -1) for lm in l_metas], np.int32
    )
    if len(r_metas):
        rvv = _gather(r_values, np.maximum(r_idx, 0))
        masked = jnp.where(jnp.isnan(rvv), lv, jnp.nan)
    else:
        masked = lv
    unmatched = jnp.asarray(r_idx < 0)[:, None]
    return jnp.where(unmatched, lv, masked), list(l_metas)
