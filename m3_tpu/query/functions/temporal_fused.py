"""Fused temporal-function evaluation: one pallas kernel, one HBM pass.

Reference semantics: /root/reference/src/query/functions/temporal/
{rate.go, aggregation.go:62-267, functions.go:89-117} — the per-step window
loops. The unfused jnp formulations in ``temporal.py`` are correct but each
windowed reduction tree is a separate HBM round trip (~25 array passes for
``rate``: measured 1.4B dp/s at 102k x 720 on v5e). Here the whole [S, T]
row-block is staged into VMEM once and every shifted-window pass runs on
chip: the same jnp code, lowered by Mosaic inside the kernel, with HBM
traffic = read input + write outputs (measured 18B dp/s for rate+avg — a
10x win, bit-identical results).

Multiple functions over the same range vector fuse into one kernel with one
output per function (PromQL rarely needs this, but the aggregation tier's
rollup pipelines do).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.instrument import DEFAULT as METRICS
from ...utils.instrument import KernelProfiler
from . import temporal as T

# compile observability (m3tpu_jit_compiles_total{kernel="temporal_fused"}:
# first call per static signature blocks on Mosaic compilation — BENCH
# rounds separate that warmup from steady-state throughput) plus sampled
# block_until_ready-bounded dispatch timings under
# M3_TPU_PROFILE_SAMPLE_RATE (m3tpu_kernel_dispatch_seconds)
_JIT = KernelProfiler("temporal_fused")
_M_PROCESSED = METRICS.counter(
    "temporal_fused_input_bytes_total",
    "bytes of range-vector input through the fused temporal kernel",
)

# name -> (fn(values, window, step_seconds) -> [S, T]) — only functions whose
# math is pure elementwise/shift (Mosaic-lowerable); quantile_over_time's
# axis sort stays unfused.
FUSABLE = {
    "rate": lambda v, w, s: T.rate(v, w, s),
    "irate": lambda v, w, s: T.irate(v, w, s),
    "increase": lambda v, w, s: T.increase(v, w, s),
    "delta": lambda v, w, s: T.delta(v, w, s),
    "idelta": lambda v, w, s: T.idelta(v, w, s),
    # deriv/predict_linear stay unfused: their chunked window-gather
    # (_linreg_sums) doesn't lower under Mosaic
    "resets": lambda v, w, s: T.resets(v, w),
    "changes": lambda v, w, s: T.changes(v, w),
    "sum_over_time": lambda v, w, s: T.sum_over_time(v, w),
    "count_over_time": lambda v, w, s: T.count_over_time(v, w),
    "avg_over_time": lambda v, w, s: T.avg_over_time(v, w),
    "min_over_time": lambda v, w, s: T.min_over_time(v, w),
    "max_over_time": lambda v, w, s: T.max_over_time(v, w),
    "last_over_time": lambda v, w, s: T.last_over_time(v, w),
    "stddev_over_time": lambda v, w, s: T.stddev_over_time(v, w),
    "stdvar_over_time": lambda v, w, s: T.stdvar_over_time(v, w),
}

BLOCK_ROWS = 64  # VMEM budget: ~30 live [64, T] f32 intermediates ≈ 5.5MB @ T=720


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


@functools.partial(
    jax.jit, static_argnames=("funcs", "window", "step_seconds", "t_cols")
)
def _fused_call(values, funcs: tuple, window: int, step_seconds: float, t_cols: int):
    from jax.experimental import pallas as pl

    n_out = len(funcs)

    def kernel(x_ref, *out_refs):
        v = x_ref[...]
        for name, ref in zip(funcs, out_refs):
            ref[...] = FUSABLE[name](v, window, step_seconds).astype(jnp.float32)

    s = values.shape[0]
    spec = pl.BlockSpec((BLOCK_ROWS, t_cols), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=(s // BLOCK_ROWS,),
        in_specs=[spec],
        out_specs=[spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((s, t_cols), jnp.float32)] * n_out,
    )(values)


def fused_temporal(values, window: int, step_seconds: float, funcs: tuple[str, ...]):
    """Evaluate ``funcs`` over the same [S, T] range matrix in one fused
    kernel on TPU; plain per-function evaluation elsewhere. Returns a tuple
    of [S, T] arrays in ``funcs`` order."""
    if not _on_tpu() or any(f not in FUSABLE for f in funcs):
        v = jnp.asarray(values, jnp.float32)
        return tuple(FUSABLE[f](v, window, step_seconds) for f in funcs)
    v = jnp.asarray(values, jnp.float32)
    s, t = v.shape
    pad = (-s) % BLOCK_ROWS
    if pad:
        v = jnp.pad(v, ((0, pad), (0, 0)), constant_values=jnp.nan)
    _M_PROCESSED.inc(int(v.size) * 4)
    with _JIT.dispatch(
        (tuple(funcs), v.shape, int(window), float(step_seconds)),
        cost=(_fused_call,
              (v, tuple(funcs), int(window), float(step_seconds), t), {}),
    ) as d:
        outs = d.done(
            _fused_call(v, tuple(funcs), int(window), float(step_seconds), t)
        )
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    if pad:
        outs = tuple(o[:s] for o in outs)
    return tuple(outs)


def temporal_apply(name: str, values, window: int, step_seconds: float):
    """Single-function entry used by the query engine: fused on TPU (the
    intermediates of even ONE rate call are ~25 HBM passes unfused),
    unfused elsewhere."""
    if name in FUSABLE and _on_tpu() and values.shape[0] >= BLOCK_ROWS:
        return fused_temporal(values, window, step_seconds, (name,))[0]
    v = jnp.asarray(values, jnp.float32)
    return FUSABLE[name](v, window, step_seconds)
