"""Linear (per-sample) functions + histogram_quantile + sort.

Reference: /root/reference/src/query/functions/linear/ — clamp.go, math.go,
round.go, sort.go, datetime.go, histogram_quantile.go. All elementwise ops
vectorize trivially; histogram_quantile groups series by tags-minus-le on the
host and interpolates buckets on device.
"""

from __future__ import annotations

import math

import jax.lax as _lax
import jax.numpy as jnp
import numpy as np

from ...block.core import SeriesMeta

__all__ = [
    "MATH_FNS",
    "clamp_min",
    "clamp_max",
    "round_to",
    "sort_series",
    "datetime_fn",
    "histogram_buckets",
    "histogram_quantile",
]

MATH_FNS = {
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "exp": jnp.exp,
    "sqrt": jnp.sqrt,
    "ln": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
}


def clamp_min(values, scalar: float):
    return jnp.maximum(values, scalar)


def clamp_max(values, scalar: float):
    return jnp.minimum(values, scalar)


def round_to(values, to_nearest: float = 1.0):
    # round.go: floor(v/to + 0.5) * to
    return jnp.floor(values / to_nearest + 0.5) * to_nearest


def sort_series(values, descending: bool = False):
    """sort.go: order series by their last-step value (instant queries)."""
    vals = np.asarray(values)
    key = vals[:, -1]
    # NaN series sort last in either direction
    key = np.where(np.isnan(key), np.inf if not descending else -np.inf, key)
    order = np.argsort(-key if descending else key, kind="stable")
    return order


_DATETIME_FNS = {
    "day_of_month": lambda tm: tm.tm_mday,
    "day_of_week": lambda tm: (tm.tm_wday + 1) % 7,  # Go: Sunday = 0
    "days_in_month": None,  # special-cased below
    "hour": lambda tm: tm.tm_hour,
    "minute": lambda tm: tm.tm_min,
    "month": lambda tm: tm.tm_mon,
    "year": lambda tm: tm.tm_year,
}


def datetime_fn(name: str, values):
    """datetime.go: interpret values as unix seconds (UTC)."""
    import calendar
    import time as _time

    vals = np.asarray(values, np.float64)
    out = np.full_like(vals, np.nan)
    it = np.nditer(vals, flags=["multi_index"])
    for v in it:
        fv = float(v)
        if math.isnan(fv):
            continue
        tm = _time.gmtime(fv)
        if name == "days_in_month":
            out[it.multi_index] = calendar.monthrange(tm.tm_year, tm.tm_mon)[1]
        else:
            out[it.multi_index] = _DATETIME_FNS[name](tm)
    return out


# ---------------------------------------------------------------------------
# histogram_quantile (histogram_quantile.go:153-384)
# ---------------------------------------------------------------------------

LE_TAG = b"le"


def histogram_buckets(series: list[SeriesMeta]):
    """Group series into histograms by tags-minus-le; sort buckets by le.

    Returns (index[G, B] int32 with -1 pad, bounds[G, B] f32 (+inf pad),
    metas[G]) — groups whose max bound isn't +Inf or with <2 buckets are
    dropped (sanitizeBuckets, :196-214)."""
    groups: dict = {}
    for i, sm in enumerate(series):
        le = None
        rest = []
        for k, v in sm.tags:
            if k == LE_TAG:
                le = v
            else:
                rest.append((k, v))
        if le is None:
            continue
        try:
            bound = float(le.decode())
        except ValueError:
            continue
        groups.setdefault(tuple(rest), []).append((bound, i))
    idxs, bounds, metas = [], [], []
    for key, buckets in groups.items():
        buckets.sort()
        bs = [b for b, _ in buckets]
        if len(buckets) < 2 or not math.isinf(bs[-1]) or bs[-1] < 0:
            continue
        idxs.append([i for _, i in buckets])
        bounds.append(bs)
        metas.append(SeriesMeta(tags=key))
    if not idxs:
        return np.zeros((0, 1), np.int32), np.zeros((0, 1), np.float32), []
    b = max(len(x) for x in idxs)
    index = np.full((len(idxs), b), -1, np.int32)
    bnd = np.full((len(idxs), b), np.inf, np.float32)
    for g, (ix, bo) in enumerate(zip(idxs, bounds)):
        index[g, : len(ix)] = ix
        bnd[g, : len(bo)] = bo
    return index, bnd, metas


def histogram_quantile(q: float, values, index, bounds):
    """Vectorized bucketQuantile (:216-256) with ensureMonotonic (:321-331).

    values: [S, T]; index: [G, B] series row per bucket (-1 pad);
    bounds: [G, B] le upper bounds. Returns [G, T]."""
    values = jnp.asarray(values)
    s, t = values.shape
    index = jnp.asarray(index)
    bounds = jnp.asarray(bounds)
    g, b = index.shape
    if g == 0:
        return jnp.zeros((0, t), values.dtype)

    v = jnp.take(values, jnp.clip(index, 0, s - 1), axis=0)  # [G, B, T]
    valid = (index >= 0)[:, :, None] & ~jnp.isnan(v)
    if q < 0 or q > 1:
        has = jnp.any(valid, axis=1)
        return jnp.where(has, -jnp.inf if q < 0 else jnp.inf, jnp.nan)

    # ensureMonotonic over valid buckets (lax.cummax == maximum.accumulate,
    # and exists on every supported jax version)
    vm = jnp.where(valid, v, -jnp.inf)
    vm = _lax.cummax(vm, axis=1)
    v = jnp.where(valid, jnp.maximum(v, vm), v)

    le = jnp.broadcast_to(bounds[:, :, None], (g, b, t))
    # last valid bucket must be the +Inf one
    bidx = jnp.broadcast_to(jnp.arange(b)[None, :, None], (g, b, t))
    last_idx = jnp.max(jnp.where(valid, bidx, -1), axis=1)  # [G, T]
    n_valid = jnp.sum(valid, axis=1)
    top_le = jnp.take_along_axis(le, jnp.maximum(last_idx, 0)[:, None, :], axis=1)[:, 0]
    top_val = jnp.take_along_axis(v, jnp.maximum(last_idx, 0)[:, None, :], axis=1)[:, 0]
    ok = (n_valid >= 2) & jnp.isinf(top_le) & (last_idx >= 0)

    rank = q * top_val  # [G, T]

    # first valid bucket (other than the last) with value >= rank
    cand = valid & (v >= rank[:, None, :]) & (bidx < last_idx[:, None, :])
    any_cand = jnp.any(cand, axis=1)
    first_cand = jnp.argmax(cand, axis=1)  # [G, T]

    # previous valid bucket before each bucket (for start bound / count)
    prev_idx = jnp.concatenate(
        [jnp.full((g, 1, t), -1, jnp.int32), _lax.cummax(jnp.where(valid, bidx, -1), axis=1)[:, :-1]],
        axis=1,
    )  # [G, B, T] index of last valid bucket strictly before b

    sel = first_cand[:, None, :]
    cur_le = jnp.take_along_axis(le, sel, axis=1)[:, 0]
    cur_val = jnp.take_along_axis(v, sel, axis=1)[:, 0]
    p_idx = jnp.take_along_axis(prev_idx, sel, axis=1)[:, 0]  # [G, T]
    has_prev = p_idx >= 0
    p_sel = jnp.maximum(p_idx, 0)[:, None, :]
    prev_le = jnp.take_along_axis(le, p_sel, axis=1)[:, 0]
    prev_val = jnp.take_along_axis(v, p_sel, axis=1)[:, 0]

    bucket_start = jnp.where(has_prev, prev_le, 0.0)
    count = cur_val - jnp.where(has_prev, prev_val, 0.0)
    rank_adj = rank - jnp.where(has_prev, prev_val, 0.0)
    interp = bucket_start + (cur_le - bucket_start) * rank_adj / jnp.where(
        count == 0, 1, count
    )

    # edge cases
    first_valid = jnp.argmax(valid, axis=1)  # [G, T]
    fv_le = jnp.take_along_axis(le, first_valid[:, None, :], axis=1)[:, 0]
    is_first = (first_cand == first_valid) & (fv_le <= 0)
    result = jnp.where(is_first, fv_le, interp)

    # no candidate below top: return second-last valid bucket's bound
    second_last = jnp.take_along_axis(prev_idx, jnp.maximum(last_idx, 0)[:, None, :], axis=1)[:, 0]
    sl_le = jnp.take_along_axis(le, jnp.maximum(second_last, 0)[:, None, :], axis=1)[:, 0]
    result = jnp.where(any_cand, result, sl_le)

    return jnp.where(ok, result, jnp.nan)
