"""Temporal (windowed, per-series) query functions as vectorized array ops.

Reference: /root/reference/src/query/functions/temporal/ — the sliding-window
controller (base.go:278-404, getIndices window [end-duration, end] inclusive
both ends) applying per-window scalar processors. Here every output step for
every series is computed at once: windowed reductions via
`jax.lax.reduce_window` over the time axis (maps directly onto TPU vector
units; no per-window Python), NaN marks missing samples exactly like the
reference's ts.Datapoints.

Conventions shared by all functions:
  - input `values`: [S, T] float array on a regular step grid; NaN = missing.
  - `window`: number of grid steps per window, inclusive of both ends —
    PromQL range `d` at step `s` is window = d/s + 1 steps, and the duration
    used by rate-style normalization is (window-1)*step_seconds.
  - output: [S, T], output[t] covers input steps [t-window+1, t] (windows
    clipped at the left edge see fewer points, matching a reference query
    with no earlier block available). Callers that carry context from the
    previous block simply prepend its columns and slice the output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "sum_over_time",
    "count_over_time",
    "avg_over_time",
    "min_over_time",
    "max_over_time",
    "last_over_time",
    "stddev_over_time",
    "stdvar_over_time",
    "quantile_over_time",
    "rate",
    "increase",
    "delta",
    "irate",
    "idelta",
    "deriv",
    "predict_linear",
    "resets",
    "changes",
    "holt_winters",
]


# Windowed reductions as SHIFTED-SLICE trees, not lax.reduce_window: XLA's
# TPU lowering of reduce_window on [S, T] with a (1, window) kernel is
# orders of magnitude off peak (measured ~0.4B dp/s at 65k×720 vs ~50B for
# the same math as shifted adds). out[t] = op(x[t-window+1] ... x[t]); each
# shift is a pad+slice the compiler fuses into pure vector ops. The tree
# halves the op count vs a linear chain (log2(window) depth of
# shift-by-2^j combines — prefix "doubling" on the suffix window).


def _win_reduce(x, window, op, fill):
    fill = jnp.asarray(fill, x.dtype)

    def shift(a, j):
        # a shifted right by j along time: out[t] = a[t-j], fill on the left
        return jnp.pad(a, ((0, 0), (j, 0)), constant_values=fill)[:, : a.shape[1]]

    # doubling tree: acc_w covers a suffix window of width w. Convention:
    # op(earlier_half, later_half) — shift(acc, w)[t] = acc[t-w] is the
    # EARLIER half of the doubled window.
    acc = x
    w = 1
    while w * 2 <= window:
        acc = op(shift(acc, w), acc)
        w *= 2
    if w < window:
        rest = _win_reduce(x, window - w, op, fill)
        acc = op(shift(rest, w), acc)
    return acc


def _win_sum(x, window):
    return _win_reduce(x, window, lax.add, 0)


def _win_max(x, window):
    return _win_reduce(x, window, lax.max, -jnp.inf)


def _win_min(x, window):
    return _win_reduce(x, window, lax.min, jnp.inf)


def _win_imax(x, window):
    """windowed max for int32 index arrays (init -1)."""
    return _win_reduce(x, window, lax.max, -1)


def _win_imin(x, window, big):
    return _win_reduce(x, window, lax.min, big)


def _valid(values):
    return ~jnp.isnan(values)


def _masked(values, fill=0.0):
    return jnp.where(_valid(values), values, jnp.asarray(fill, values.dtype))


# ---------------------------------------------------------------------------
# *_over_time aggregations (temporal/aggregation.go:144-236 NaN semantics)
# ---------------------------------------------------------------------------


def _sum_count(values, window):
    valid = _valid(values)
    s = _win_sum(_masked(values), window)
    c = _win_sum(valid.astype(values.dtype), window)
    return s, c


def sum_over_time(values, window):
    s, c = _sum_count(values, window)
    return jnp.where(c > 0, s, jnp.nan)


def count_over_time(values, window):
    _, c = _sum_count(values, window)
    return jnp.where(c > 0, c, jnp.nan)


def avg_over_time(values, window):
    s, c = _sum_count(values, window)
    return jnp.where(c > 0, s / c, jnp.nan)


def min_over_time(values, window):
    c = _win_sum(_valid(values).astype(values.dtype), window)
    m = _win_min(_masked(values, jnp.inf), window)
    return jnp.where(c > 0, m, jnp.nan)


def max_over_time(values, window):
    c = _win_sum(_valid(values).astype(values.dtype), window)
    m = _win_max(_masked(values, -jnp.inf), window)
    return jnp.where(c > 0, m, jnp.nan)


def last_over_time(values, window):
    last_idx, last_val = _win_last_valid(values, window)
    return jnp.where(last_idx >= 0, last_val, jnp.nan)


def stdvar_over_time(values, window):
    # Population variance over the window (aggregation.go:207-222; NaN unless
    # >= 2 points). Variance is shift-invariant, so subtract a per-series
    # baseline before the E[x^2]-mean^2 sums — without it the f32 sums
    # catastrophically cancel for large-mean series.
    valid = _valid(values)
    baseline = jnp.nanmean(jnp.where(valid, values, jnp.nan), axis=1, keepdims=True)
    baseline = jnp.where(jnp.isnan(baseline), 0.0, baseline)
    x = jnp.where(valid, values - baseline, 0.0)
    s = _win_sum(x, window)
    ss = _win_sum(x * x, window)
    c = _win_sum(valid.astype(values.dtype), window)
    mean = s / jnp.maximum(c, 1)
    var = ss / jnp.maximum(c, 1) - mean * mean
    return jnp.where(c >= 2, jnp.maximum(var, 0), jnp.nan)


def stddev_over_time(values, window):
    return jnp.sqrt(stdvar_over_time(values, window))


# ---------------------------------------------------------------------------
# window index machinery
# ---------------------------------------------------------------------------


# Windowed first/last-valid machinery WITHOUT device gathers: TPU gathers
# (take_along_axis on [S, T]) lower to per-element loops and cost seconds
# at 100k x 720. Instead, carry (idx, value, extras...) tuples through the
# same shifted-slice doubling tree — "rightmost valid wins" / "leftmost
# valid wins" are associative, so first/last values AND any rider arrays
# arrive in one vectorized pass.


def _win_reduce_tuple(arrs, fills, window, op):
    fills = tuple(jnp.asarray(f, a.dtype) for f, a in zip(fills, arrs))

    def shift(t_arrs, j):
        return tuple(
            jnp.pad(a, ((0, 0), (j, 0)), constant_values=f)[:, : a.shape[1]]
            for a, f in zip(t_arrs, fills)
        )

    # op(earlier_half, later_half): the shifted copy is the earlier half
    acc = tuple(arrs)
    w = 1
    while w * 2 <= window:
        acc = op(shift(acc, w), acc)
        w *= 2
    if w < window:
        rest = _win_reduce_tuple(arrs, fills, window - w, op)
        acc = op(shift(rest, w), acc)
    return acc


def _comb_later(a, b):
    """b covers the LATER half; prefer b's entry when it saw a valid sample
    (component 0 is the valid-sample index, -1 = none)."""
    sel = b[0] >= 0
    return tuple(jnp.where(sel, bb, aa) for aa, bb in zip(a, b))


def _comb_earlier(a, b):
    sel = a[0] >= 0
    return tuple(jnp.where(sel, aa, bb) for aa, bb in zip(a, b))


def _iota_valid(values):
    s, t = values.shape
    idx = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (s, t))
    return jnp.where(_valid(values), idx, -1)


def _win_last_valid(values, window, extras=()):
    """(last_idx, last_val, *extras at the last valid sample) per window."""
    arrs = (_iota_valid(values), _masked(values)) + tuple(extras)
    fills = (-1, 0.0) + tuple(
        -1 if jnp.issubdtype(e.dtype, jnp.integer) else 0.0 for e in extras
    )
    return _win_reduce_tuple(arrs, fills, window, _comb_later)


def _win_first_valid(values, window, extras=()):
    """(first_idx, first_val, *extras at the first valid sample); idx -1
    when the window holds no valid sample."""
    arrs = (_iota_valid(values), _masked(values)) + tuple(extras)
    fills = (-1, 0.0) + tuple(
        -1 if jnp.issubdtype(e.dtype, jnp.integer) else 0.0 for e in extras
    )
    return _win_reduce_tuple(arrs, fills, window, _comb_earlier)


def _window_valid_indices(values, window):
    """(last_idx, first_idx, count) of valid samples per window; -1 = none."""
    iv = _iota_valid(values)
    (last_idx,) = _win_reduce_tuple((iv,), (-1,), window, _comb_later)
    (first_idx,) = _win_reduce_tuple((iv,), (-1,), window, _comb_earlier)
    count = _win_sum(_valid(values).astype(jnp.float32), window)
    return last_idx, first_idx, count


def _ffill(values):
    """Forward fill along time: out[t] = last valid value at index <= t
    (NaN before any valid sample). Log-depth doubling on the ONE f32 array —
    measured ~4x cheaper than a tuple associative_scan, which XLA lowers to
    a generic combinator over every component at every level."""
    x = values
    t = x.shape[1]
    j = 1
    while j < t:
        shifted = jnp.pad(x, ((0, 0), (j, 0)), constant_values=jnp.nan)[:, :t]
        x = jnp.where(jnp.isnan(x), shifted, x)
        j *= 2
    return x


def _prev_valid_val(values):
    """Per index t: value of the last valid sample at index < t (NaN none).
    The cheap path for rate/increase/delta — no index array needed."""
    s, t = values.shape
    ff = _ffill(values)
    return jnp.concatenate(
        [jnp.full((s, 1), jnp.nan, values.dtype), ff[:, :-1]], axis=1
    )


def _prev_valid(values):
    """Per index t: (prev_idx, prev_val) of the last valid sample at index < t.
    Pair doubling driven by idx validity (same recurrence as _ffill)."""
    s, t = values.shape
    iv = _iota_valid(values)
    vv = _masked(values)
    j = 1
    while j < t:
        iv_s = jnp.pad(iv, ((0, 0), (j, 0)), constant_values=-1)[:, :t]
        vv_s = jnp.pad(vv, ((0, 0), (j, 0)), constant_values=0.0)[:, :t]
        hole = iv < 0
        iv = jnp.where(hole, iv_s, iv)
        vv = jnp.where(hole, vv_s, vv)
        j *= 2
    prev_idx = jnp.concatenate([jnp.full((s, 1), -1, jnp.int32), iv[:, :-1]], axis=1)
    prev_val = jnp.concatenate(
        [jnp.zeros((s, 1), values.dtype), vv[:, :-1]], axis=1
    )
    prev_val = jnp.where(prev_idx >= 0, prev_val, jnp.nan)
    return prev_idx, prev_val


def _pair_event_window_sum(values, event_amount, window):
    """Windowed sum of per-sample pair events, excluding the event attached to
    the window's FIRST valid sample (its pair partner lies before the window
    — mirrors the reference loops starting with zero state, e.g.
    rate.go:170-188, functions.go:89-117)."""
    wsum = _win_sum(event_amount, window)
    first_idx, _, first_event = _win_first_valid(
        values, window, extras=(event_amount,)
    )
    (last_idx,) = _win_reduce_tuple(
        (_iota_valid(values),), (-1,), window, _comb_later
    )
    first_event = jnp.where(first_idx >= 0, first_event, 0.0)
    return wsum - first_event, last_idx, first_idx


# ---------------------------------------------------------------------------
# rate family (temporal/rate.go:150-239)
# ---------------------------------------------------------------------------


def _rate_impl(values, window, step_seconds, is_rate, is_counter):
    dt = values.dtype
    s, t = values.shape
    duration = (window - 1) * step_seconds

    prev_val = _prev_valid_val(values)
    valid = _valid(values)
    reset = valid & ~jnp.isnan(prev_val) & (values < prev_val)
    corr_amount = jnp.where(reset & is_counter, _masked(prev_val), 0.0).astype(dt)
    corr, last_idx, first_idx = _pair_event_window_sum(values, corr_amount, window)
    _, last_val = _win_last_valid(values, window)
    _, first_val = _win_first_valid(values, window)

    has_two = (last_idx >= 0) & (first_idx >= 0) & (last_idx != first_idx)
    li = jnp.maximum(last_idx, 0)
    fi = jnp.maximum(first_idx, 0)

    # grid timestamps relative to each output step's rangeEnd, in seconds
    # (int iota + cast: Mosaic/pallas has no float iota, and this code also
    # runs inside the fused temporal kernel)
    out_idx = jnp.arange(t, dtype=jnp.int32).astype(jnp.float32)[None, :]
    t_last = (li.astype(jnp.float32) - out_idx) * step_seconds  # <= 0
    t_first = (fi.astype(jnp.float32) - out_idx) * step_seconds
    range_start = -duration

    duration_to_start = t_first - range_start
    duration_to_end = -t_last
    sampled_interval = t_last - t_first
    avg_between = sampled_interval / jnp.maximum((li - fi).astype(jnp.float32), 1)

    result = last_val - first_val + corr
    if is_counter:
        # zero-point extrapolation clamp (rate.go:200-211)
        dur_to_zero = sampled_interval * (first_val / jnp.where(result > 0, result, 1.0))
        clamp = (result > 0) & (first_val >= 0)
        duration_to_start = jnp.where(
            clamp & (dur_to_zero < duration_to_start), dur_to_zero, duration_to_start
        )

    threshold = avg_between * 1.1
    extrap = sampled_interval
    extrap = extrap + jnp.where(duration_to_start < threshold, duration_to_start, avg_between / 2)
    extrap = extrap + jnp.where(duration_to_end < threshold, duration_to_end, avg_between / 2)

    result = result * (extrap / jnp.maximum(sampled_interval, 1e-30))
    if is_rate:
        result = result / duration
    return jnp.where(has_two, result, jnp.nan).astype(dt)


def rate(values, window, step_seconds):
    return _rate_impl(values, window, step_seconds, is_rate=True, is_counter=True)


def increase(values, window, step_seconds):
    return _rate_impl(values, window, step_seconds, is_rate=False, is_counter=True)


def delta(values, window, step_seconds):
    return _rate_impl(values, window, step_seconds, is_rate=False, is_counter=False)


def _irate_impl(values, window, step_seconds, is_rate):
    """Last two valid samples in window (rate.go irateFunc:240-282)."""
    s, t = values.shape
    prev_idx, prev_val = _prev_valid(values)
    # second-to-last valid = prev_valid AT the last valid sample: ride the
    # prev arrays through the last-valid window reduction
    last_idx, last_val, second_idx, second_val = _win_last_valid(
        values, window, extras=(prev_idx, _masked(prev_val))
    )
    li = jnp.maximum(last_idx, 0)
    window_start = jnp.arange(t, dtype=jnp.int32)[None, :] - (window - 1)
    ok = (last_idx >= 0) & (second_idx >= 0) & (second_idx >= window_start)
    res = last_val - second_val
    if is_rate:
        dt_s = (li - second_idx).astype(values.dtype) * step_seconds
        res = res / jnp.maximum(dt_s, 1e-30)
    return jnp.where(ok, res, jnp.nan)


def irate(values, window, step_seconds):
    return _irate_impl(values, window, step_seconds, is_rate=True)


def idelta(values, window, step_seconds):
    return _irate_impl(values, window, step_seconds, is_rate=False)


# ---------------------------------------------------------------------------
# linear regression (temporal/linear_regression.go:145-190)
# ---------------------------------------------------------------------------


def _linreg_sums(values, window, step_seconds, chunk: int = 128):
    """Windowed least squares with timeDiff relative to the window end — the
    reference's interceptTime == evaluationTime (linear_regression.go:136).
    Uses exact per-window recentering on gathered windows (chunked) to avoid
    the f32 cancellation a shift-invariant cumulative formulation would hit.
    Slope is recenter-invariant, so deriv shares this."""
    dt = values.dtype
    s, t = values.shape
    nchunks = -(-t // chunk)
    # time diff of window slot j (0..W-1) from the window end, in seconds
    d = (jnp.arange(window, dtype=dt) - (window - 1)) * jnp.asarray(step_seconds, dt)

    def one_chunk(t0):
        w = _gather_windows(values, window, t0, chunk)  # [S, chunk, W]
        ok = ~jnp.isnan(w)
        x = jnp.where(ok, w, 0)
        vi = ok.astype(dt)
        n = jnp.sum(vi, axis=-1)
        sv = jnp.sum(x, axis=-1)
        sd = jnp.sum(d * vi, axis=-1)
        sdd = jnp.sum(d * d * vi, axis=-1)
        sdv = jnp.sum(d * x, axis=-1)
        nn = jnp.maximum(n, 1)
        cov = sdv - sd * sv / nn
        var = sdd - sd * sd / nn
        slope = cov / jnp.where(var == 0, 1, var)
        intercept = sv / nn - slope * sd / nn
        good = n >= 2
        return jnp.where(good, slope, jnp.nan), jnp.where(good, intercept, jnp.nan)

    slopes, intercepts = lax.map(one_chunk, jnp.arange(nchunks) * chunk)
    fix = lambda a: jnp.moveaxis(a, 0, 1).reshape(s, nchunks * chunk)[:, :t]
    return fix(slopes), fix(intercepts)


def deriv(values, window, step_seconds):
    slope, _ = _linreg_sums(values, window, step_seconds)
    return slope


def predict_linear(values, window, step_seconds, predict_seconds):
    slope, intercept = _linreg_sums(values, window, step_seconds)
    return slope * predict_seconds + intercept


# ---------------------------------------------------------------------------
# resets / changes (temporal/functions.go:89-117)
# ---------------------------------------------------------------------------


def _count_pairs(values, window, cmp):
    prev_val = _prev_valid_val(values)
    valid = _valid(values)
    event = valid & ~jnp.isnan(prev_val) & cmp(values, prev_val)
    count, last_idx, first_idx = _pair_event_window_sum(
        values, event.astype(values.dtype), window
    )
    # NaN iff no valid sample after the window's first slot (functions.go:93-116:
    # `prev` seeds from dps[0], loop over dps[1:]).
    t = values.shape[1]
    w1 = window - 1
    dtv = valid.astype(values.dtype)
    # validity at the window's first slot = a static shift (left-edge
    # windows clamp their first slot to column 0) — no gather needed
    shifted = jnp.pad(dtv, ((0, 0), (w1, 0)))[:, :t]
    colmask = jnp.arange(t, dtype=jnp.int32)[None, :] < w1
    first_slot = jnp.where(colmask, dtv[:, :1], shifted)
    valid_after_first = _win_sum(dtv, window) - first_slot
    return jnp.where(valid_after_first > 0, count, jnp.nan)


def resets(values, window):
    return _count_pairs(values, window, lambda c, p: c < p)


def changes(values, window):
    return _count_pairs(values, window, lambda c, p: c != p)


# ---------------------------------------------------------------------------
# holt_winters (temporal/holt_winters.go:77-141) — sequential smoothing within
# the window: lax.scan over the window axis on gathered windows, chunked over
# time to bound the [S, chunk, W] gather.
# ---------------------------------------------------------------------------


def _gather_windows(values, window, t0, chunk):
    """[S, chunk, W] windows ending at steps t0..t0+chunk-1 (NaN left-pad)."""
    values = jnp.asarray(values)
    s, t = values.shape
    ends = t0 + jnp.arange(chunk)
    offs = jnp.arange(window) - (window - 1)
    idx = ends[:, None] + offs[None, :]  # [chunk, W]
    oob = idx < 0
    g = jnp.take(values, jnp.clip(idx, 0, t - 1), axis=1)  # [S, chunk, W]
    return jnp.where(oob[None, :, :], jnp.nan, g)


def holt_winters(values, window, sf: float, tf: float, chunk: int = 128):
    s, t = values.shape
    dt = values.dtype
    nchunks = -(-t // chunk)

    def one_chunk(t0):
        w = _gather_windows(values, window, t0, chunk)  # [S, chunk, W]
        flat = w.reshape(s * chunk, window)

        def step(carry, v):
            found1, found2, prev, curr, trend, idx = carry
            nan = jnp.isnan(v)
            # first valid
            take1 = ~nan & ~found1
            # second valid: initialize trend
            take2 = ~nan & found1 & ~found2
            trend0 = jnp.where(take2, v - curr, trend)
            # smoothing update for 2nd+ valid samples
            upd = ~nan & found1
            trend_new = jnp.where(
                idx - 1 == 0, trend0, tf * (curr - prev) + (1 - tf) * trend0
            )
            new_curr = sf * v + (1 - sf) * (curr + trend_new)
            curr_out = jnp.where(take1, v, jnp.where(upd, new_curr, curr))
            prev_out = jnp.where(upd, curr, prev)
            trend_out = jnp.where(upd, trend_new, trend0)
            idx_out = jnp.where(~nan, idx + 1, idx)
            return (
                found1 | ~nan,
                found2 | take2,
                prev_out,
                curr_out,
                trend_out,
                idx_out,
            ), None

        z = jnp.zeros((flat.shape[0],), dt)
        init = (
            jnp.zeros_like(z, bool),
            jnp.zeros_like(z, bool),
            z,
            z,
            z,
            jnp.zeros_like(z, jnp.int32),
        )
        (f1, f2, _, curr, _, _), _ = lax.scan(step, init, flat.T)
        out = jnp.where(f2, curr, jnp.nan)
        return out.reshape(s, chunk)

    outs = lax.map(one_chunk, jnp.arange(nchunks) * chunk)  # [nchunks, S, chunk]
    out = jnp.moveaxis(outs, 0, 1).reshape(s, nchunks * chunk)
    return out[:, :t]


def quantile_over_time(values, window, q: float, chunk: int = 128):
    """quantile over valid samples in window (aggregation.go:239-280): sort the
    gathered window (NaNs sort to the end under jnp.sort), linear interpolate."""
    s, t = values.shape
    dt = values.dtype
    if q < 0:
        base = jnp.full((s, t), -jnp.inf, dt)
        c = _win_sum(_valid(values).astype(dt), window)
        return jnp.where(c > 0, base, jnp.nan)
    if q > 1:
        base = jnp.full((s, t), jnp.inf, dt)
        c = _win_sum(_valid(values).astype(dt), window)
        return jnp.where(c > 0, base, jnp.nan)
    nchunks = -(-t // chunk)

    def one_chunk(t0):
        w = _gather_windows(values, window, t0, chunk)  # [S, chunk, W]
        sw = jnp.sort(w, axis=-1)  # NaNs to the end
        n = jnp.sum(~jnp.isnan(w), axis=-1)  # [S, chunk]
        rank = q * (n - 1).astype(dt)
        lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, window - 1)
        hi = jnp.clip(lo + 1, 0, window - 1)
        hi = jnp.minimum(hi, jnp.maximum(n - 1, 0))
        frac = rank - lo.astype(dt)
        vlo = jnp.take_along_axis(sw, lo[..., None], axis=-1)[..., 0]
        vhi = jnp.take_along_axis(sw, hi[..., None], axis=-1)[..., 0]
        out = vlo + (vhi - vlo) * frac
        return jnp.where(n > 0, out, jnp.nan)

    outs = lax.map(one_chunk, jnp.arange(nchunks) * chunk)
    out = jnp.moveaxis(outs, 0, 1).reshape(s, nchunks * chunk)
    return out[:, :t]
