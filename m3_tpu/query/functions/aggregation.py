"""Cross-series (tag-grouped) aggregations as segment reductions.

Reference: /root/reference/src/query/functions/aggregation/function.go
(sum/min/max/avg/count/stddev/var/quantile/absent over tag buckets),
take.go (topk/bottomk). Grouping by tags happens host-side once per query
(group ids are data-independent); the per-step math is `jax.ops.segment_*`
over the series axis — the TPU-native form of the reference's bucket loops.

NaN semantics (function.go):
  sum/min/max: NaN iff every value in the bucket is NaN
  count: number of non-NaN values (0, not NaN, for empty buckets)
  avg/stddev/var: NaN iff count == 0 (population variance)
  absent: 1 where the bucket has no non-NaN value, else NaN
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...block.core import SeriesMeta, Tags

__all__ = [
    "group_by_tags",
    "GroupLayout",
    "grouped_sum",
    "grouped_count",
    "grouped_avg",
    "grouped_min",
    "grouped_max",
    "grouped_stddev",
    "grouped_stdvar",
    "grouped_quantile",
    "absent",
    "topk",
    "bottomk",
]


@dataclass
class GroupLayout:
    """Host-computed series→group assignment.

    group_ids: int32[S] group index per series
    metas: per-group SeriesMeta (the retained tags)
    pad_index: int32[G, M] series indices per group, -1 padded (for sort-based
      ops: quantile/topk), M = max group size
    """

    group_ids: np.ndarray
    metas: list[SeriesMeta]
    pad_index: np.ndarray

    @property
    def num_groups(self) -> int:
        return len(self.metas)


def group_by_tags(
    series: list[SeriesMeta],
    matching: list[bytes] | None = None,
    without: bool = False,
) -> GroupLayout:
    """PromQL by/without grouping (aggregation/function.go:180-210 via
    utils.GroupSeries). matching=None, without=False → one global group."""
    matching = [m if isinstance(m, bytes) else m.encode() for m in (matching or [])]
    groups: dict[Tags, int] = {}
    members: list[list[int]] = []
    metas: list[SeriesMeta] = []
    gids = np.zeros(len(series), np.int32)
    for i, sm in enumerate(series):
        if without:
            key = tuple((k, v) for k, v in sm.tags if k not in matching)
        else:
            key = tuple((k, v) for k, v in sm.tags if k in matching)
        gid = groups.get(key)
        if gid is None:
            gid = len(metas)
            groups[key] = gid
            metas.append(SeriesMeta(tags=key))
            members.append([])
        gids[i] = gid
        members[gid].append(i)
    m = max((len(x) for x in members), default=1)
    pad = np.full((len(metas), m), -1, np.int32)
    for g, idxs in enumerate(members):
        pad[g, : len(idxs)] = idxs
    return GroupLayout(group_ids=gids, metas=metas, pad_index=pad)


def _seg(values, layout: GroupLayout):
    gids = jnp.asarray(layout.group_ids)
    g = layout.num_groups
    valid = ~jnp.isnan(values)
    x = jnp.where(valid, values, 0)
    s = jax.ops.segment_sum(x, gids, num_segments=g)
    c = jax.ops.segment_sum(valid.astype(values.dtype), gids, num_segments=g)
    return s, c, gids, g, valid, x


def grouped_sum(values, layout: GroupLayout):
    s, c, *_ = _seg(values, layout)
    return jnp.where(c > 0, s, jnp.nan)


def grouped_count(values, layout: GroupLayout):
    _, c, *_ = _seg(values, layout)
    return c


def grouped_avg(values, layout: GroupLayout):
    s, c, *_ = _seg(values, layout)
    return jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan)


def grouped_min(values, layout: GroupLayout):
    gids = jnp.asarray(layout.group_ids)
    g = layout.num_groups
    x = jnp.where(jnp.isnan(values), jnp.inf, values)
    m = jax.ops.segment_min(x, gids, num_segments=g)
    c = jax.ops.segment_sum((~jnp.isnan(values)).astype(jnp.float32), gids, num_segments=g)
    return jnp.where(c > 0, m, jnp.nan)


def grouped_max(values, layout: GroupLayout):
    gids = jnp.asarray(layout.group_ids)
    g = layout.num_groups
    x = jnp.where(jnp.isnan(values), -jnp.inf, values)
    m = jax.ops.segment_max(x, gids, num_segments=g)
    c = jax.ops.segment_sum((~jnp.isnan(values)).astype(jnp.float32), gids, num_segments=g)
    return jnp.where(c > 0, m, jnp.nan)


def grouped_stdvar(values, layout: GroupLayout):
    # two-pass population variance exactly as varianceFn (function.go:124-143)
    s, c, gids, g, valid, x = _seg(values, layout)
    mean = jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan)
    diff = values - jnp.take(mean, gids, axis=0)
    sq = jnp.where(valid, diff * diff, 0)
    ss = jax.ops.segment_sum(sq, gids, num_segments=g)
    return jnp.where(c > 0, ss / jnp.maximum(c, 1), jnp.nan)


def grouped_stddev(values, layout: GroupLayout):
    return jnp.sqrt(grouped_stdvar(values, layout))


def absent(values, layout: GroupLayout | None = None):
    """absentFn (function.go:46-55): per step, 1 if no series has a value."""
    any_present = jnp.any(~jnp.isnan(values), axis=0)
    return jnp.where(any_present, jnp.nan, 1.0)[None, :]


def _padded(values, layout: GroupLayout):
    """[G, M, T] group-major view, NaN at padding."""
    idx = jnp.asarray(layout.pad_index)
    g = jnp.take(values, jnp.clip(idx, 0, values.shape[0] - 1), axis=0)
    return jnp.where((idx < 0)[:, :, None], jnp.nan, g)


def grouped_quantile(values, layout: GroupLayout, q: float):
    """Same interpolation as quantile_over_time (aggregation.go:265-297)."""
    p = _padded(values, layout)  # [G, M, T]
    m = p.shape[1]
    sw = jnp.sort(p, axis=1)  # NaN to the end of axis 1
    n = jnp.sum(~jnp.isnan(p), axis=1)  # [G, T]
    if q < 0:
        return jnp.where(n > 0, -jnp.inf, jnp.nan)
    if q > 1:
        return jnp.where(n > 0, jnp.inf, jnp.nan)
    dt = values.dtype
    rank = q * (n - 1).astype(dt)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, m - 1)
    hi = jnp.minimum(jnp.clip(lo + 1, 0, m - 1), jnp.maximum(n - 1, 0))
    frac = rank - lo.astype(dt)
    vlo = jnp.take_along_axis(sw, lo[:, None, :], axis=1)[:, 0, :]
    vhi = jnp.take_along_axis(sw, hi[:, None, :], axis=1)[:, 0, :]
    out = vlo + (vhi - vlo) * frac
    return jnp.where(n > 0, out, jnp.nan)


def _take(values, layout: GroupLayout, k: int, largest: bool):
    """topk/bottomk (take.go): keep k best per group per step, NaN the rest.
    Stable rank (ties broken by series order) like the reference heap."""
    p = _padded(values, layout)  # [G, M, T]
    key = jnp.where(jnp.isnan(p), -jnp.inf if largest else jnp.inf, p)
    if largest:
        key = -key  # argsort ascending == descending on value
    order = jnp.argsort(key, axis=1, stable=True)  # [G, M, T]
    ranks = jnp.argsort(order, axis=1, stable=True)  # rank of each slot
    keep_padded = (ranks < k) & ~jnp.isnan(p)
    # scatter back to [S, T]
    s = values.shape[0]
    idx = jnp.asarray(layout.pad_index)  # [G, M]
    flat_idx = jnp.clip(idx.reshape(-1), 0, s - 1)
    keep = jnp.zeros(values.shape, bool)
    src = keep_padded.reshape(-1, values.shape[1]) & (idx.reshape(-1) >= 0)[:, None]
    keep = keep.at[flat_idx].max(src)
    return jnp.where(keep, values, jnp.nan)


def topk(values, layout: GroupLayout, k: int):
    return _take(values, layout, k, largest=True)


def bottomk(values, layout: GroupLayout, k: int):
    return _take(values, layout, k, largest=False)


def count_values(values, series: list[SeriesMeta], label: bytes):
    """count_values (count_values.go): per step, count series sharing each
    distinct value. Host-side — output cardinality is data-dependent, which is
    inherently dynamic-shape; this runs on the result block, not the hot path.
    Returns (values[G, T], metas)."""
    vals = np.asarray(values)
    uniq = np.unique(vals[~np.isnan(vals)])
    out = np.full((len(uniq), vals.shape[1]), np.nan)
    metas = []
    for i, u in enumerate(uniq):
        cnt = np.sum(vals == u, axis=0).astype(np.float64)
        out[i] = np.where(cnt > 0, cnt, np.nan)
        metas.append(SeriesMeta(tags=((label, repr(float(u)).encode()),)))
    return out, metas
