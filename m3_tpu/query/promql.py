"""PromQL parser: lexer + Pratt parser → AST.

Reference: /root/reference/src/query/parser/promql/parse.go wraps the upstream
prometheus/promql parser and converts its AST into M3's transform DAG. This
framework owns the parser (no Go dependency): the grammar subset covers
vector/range selectors with matchers and offsets, all implemented functions,
aggregation operators with by/without and parameters, and binary operators
with precedence, BOOL, and on/ignoring vector matching.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..metrics.policy import parse_duration

# --- AST ---


@dataclass
class Expr:
    pass


@dataclass
class NumberLiteral(Expr):
    value: float


@dataclass
class StringLiteral(Expr):
    value: str


@dataclass
class Matcher:
    name: str
    op: str  # = != =~ !~
    value: str


@dataclass
class VectorSelector(Expr):
    name: str | None
    matchers: list[Matcher] = field(default_factory=list)
    offset_nanos: int = 0
    # @ modifier: absolute nanos, or "start"/"end" (resolved against the
    # query bounds at eval time)
    at_nanos: int | str | None = None


@dataclass
class RangeSelector(Expr):
    vector: VectorSelector
    range_nanos: int


@dataclass
class Call(Expr):
    func: str
    args: list[Expr]


@dataclass
class Subquery(Expr):
    """expr[range:step] — inner expr evaluated at step resolution, exposed
    to its enclosing function as a range vector (prometheus subqueries)."""

    expr: Expr
    range_nanos: int
    step_nanos: int = 0  # 0 = default (the outer query step)
    offset_nanos: int = 0
    at_nanos: int | str | None = None


@dataclass
class Aggregation(Expr):
    op: str
    expr: Expr
    param: Expr | None = None
    grouping: list[str] = field(default_factory=list)
    without: bool = False


@dataclass
class BinaryOp(Expr):
    op: str
    lhs: Expr
    rhs: Expr
    return_bool: bool = False
    on: bool = False
    ignoring: bool = False
    matching_labels: list[str] = field(default_factory=list)
    group_left: bool = False
    group_right: bool = False
    # carried labels of group_left(...)/group_right(...)
    include_labels: list[str] = field(default_factory=list)


@dataclass
class Unary(Expr):
    op: str
    expr: Expr


AGG_OPS = {
    "sum",
    "min",
    "max",
    "avg",
    "count",
    "stddev",
    "stdvar",
    "topk",
    "bottomk",
    "quantile",
    "count_values",
}

FUNCTIONS = {
    "rate", "irate", "increase", "delta", "idelta", "deriv", "predict_linear",
    "resets", "changes", "holt_winters",
    "sum_over_time", "count_over_time", "avg_over_time", "min_over_time",
    "max_over_time", "last_over_time", "stddev_over_time", "stdvar_over_time",
    "quantile_over_time", "present_over_time",
    "abs", "ceil", "floor", "exp", "sqrt", "ln", "log2", "log10", "round",
    "clamp_min", "clamp_max", "clamp",
    "histogram_quantile", "sort", "sort_desc", "absent", "scalar", "vector",
    "label_replace", "label_join",
    "time", "timestamp",
    "day_of_month", "day_of_week", "days_in_month", "hour", "minute", "month",
    "year",
}

_TOKEN_RE = re.compile(
    r"""
    (?P<space>\s+)
  | (?P<duration>\d+(?:\.\d+)?(?:ns|us|ms|s|m|h|d|w|y)(?:\d+(?:\.\d+)?(?:ns|us|ms|s|m|h|d|w|y))*)
  | (?P<number>\d+\.\d+|\d+|\.\d+)
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<ident>[a-zA-Z_][a-zA-Z0-9_:.]*)
  | (?P<colonident>:[a-zA-Z_:][a-zA-Z0-9_:.]*)
  | (?P<op>=~|!~|==|!=|<=|>=|<|>|=|\+|-|\*|/|%|\^|\(|\)|\{|\}|\[|\]|,|:|@)
    """,
    re.VERBOSE,
)

_KEYWORDS = {"by", "without", "on", "ignoring", "group_left", "group_right", "bool", "offset", "and", "or", "unless"}


@dataclass
class Token:
    kind: str
    text: str


def lex(s: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise ValueError(f"promql: unexpected character {s[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "space":
            continue
        if kind == "colonident":
            # leading-colon recording-rule names are idents; a bare ':'
            # (subquery step separator) stays an operator
            kind = "ident"
        text = m.group()
        if kind == "ident" and text in _KEYWORDS:
            kind = text
        out.append(Token(kind, text))
    out.append(Token("eof", ""))
    return out


_DUR_UNITS = {"w": "168h", "y": "8760h"}


def _duration_nanos(text: str) -> int:
    # normalize w/y which parse_duration doesn't know
    for u, repl in _DUR_UNITS.items():
        text = re.sub(rf"(\d+(?:\.\d+)?){u}", lambda m: f"{float(m.group(1)) * int(repl[:-1])}h", text)
    return parse_duration(text)


class Parser:
    # precedence: or < and/unless < comparison < +- < */% < ^
    _PREC = {
        "or": 1,
        "and": 2,
        "unless": 2,
        "==": 3, "!=": 3, "<": 3, ">": 3, "<=": 3, ">=": 3,
        "+": 4, "-": 4,
        "*": 5, "/": 5, "%": 5,
        "^": 6,
    }

    def __init__(self, tokens: list[Token]) -> None:
        self.toks = tokens
        self.i = 0

    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def eat(self, kind: str | None = None, text: str | None = None) -> Token:
        t = self.cur
        if kind is not None and t.kind != kind:
            raise ValueError(f"promql: expected {kind}, got {t.kind} {t.text!r}")
        if text is not None and t.text != text:
            raise ValueError(f"promql: expected {text!r}, got {t.text!r}")
        self.i += 1
        return t

    def parse(self) -> Expr:
        e = self.parse_expr(0)
        if self.cur.kind != "eof":
            raise ValueError(f"promql: trailing input at {self.cur.text!r}")
        return e

    def parse_expr(self, min_prec: int) -> Expr:
        lhs = self.parse_unary()
        while True:
            t = self.cur
            op = t.text if t.kind == "op" else t.kind
            prec = self._PREC.get(op)
            if t.kind not in ("op", "and", "or", "unless") or prec is None or prec < min_prec:
                return lhs
            self.i += 1
            node = BinaryOp(op=op, lhs=lhs, rhs=NumberLiteral(0))
            if self.cur.kind == "bool":
                self.eat("bool")
                node.return_bool = True
            if self.cur.kind in ("on", "ignoring"):
                which = self.eat().kind
                node.on = which == "on"
                node.ignoring = which == "ignoring"
                node.matching_labels = self._label_list()
                if self.cur.kind in ("group_left", "group_right"):
                    which = self.eat().kind
                    node.group_left = which == "group_left"
                    node.group_right = which == "group_right"
                    if self.cur.text == "(":
                        node.include_labels = self._label_list()
            # ^ is right-associative
            next_min = prec if op == "^" else prec + 1
            node.rhs = self.parse_expr(next_min)
            lhs = node

    def parse_unary(self) -> Expr:
        t = self.cur
        if t.kind == "op" and t.text in ("+", "-"):
            self.i += 1
            return Unary(t.text, self.parse_unary())
        return self.parse_postfix(self.parse_atom())

    def parse_postfix(self, e: Expr) -> Expr:
        while True:
            t = self.cur
            if t.kind == "op" and t.text == "[":
                self.eat(text="[")
                dur = self.eat("duration").text
                if self.cur.text == ":":
                    # subquery: expr[range:step?]
                    self.eat(text=":")
                    step = 0
                    if self.cur.kind == "duration":
                        step = _duration_nanos(self.eat("duration").text)
                    self.eat(text="]")
                    e = Subquery(e, _duration_nanos(dur), step)
                else:
                    self.eat(text="]")
                    if not isinstance(e, VectorSelector):
                        raise ValueError("promql: range on non-selector")
                    e = RangeSelector(e, _duration_nanos(dur))
            elif t.kind == "offset":
                self.eat("offset")
                neg = False
                if self.cur.text == "-":
                    self.eat(text="-")
                    neg = True
                dur = self.eat("duration").text
                off = _duration_nanos(dur) * (-1 if neg else 1)
                if isinstance(e, VectorSelector):
                    e.offset_nanos = off
                elif isinstance(e, RangeSelector):
                    e.vector.offset_nanos = off
                elif isinstance(e, Subquery):
                    e.offset_nanos = off
                else:
                    raise ValueError("promql: offset on non-selector")
            elif t.kind == "op" and t.text == "@":
                self.eat(text="@")
                if self.cur.kind == "number":
                    at = int(float(self.eat().text) * 1e9)
                elif self.cur.kind == "ident" and self.cur.text in ("start", "end"):
                    at = self.eat("ident").text
                    self.eat(text="(")
                    self.eat(text=")")
                else:
                    raise ValueError("promql: bad @ modifier")
                if isinstance(e, VectorSelector):
                    e.at_nanos = at
                elif isinstance(e, RangeSelector):
                    e.vector.at_nanos = at
                elif isinstance(e, Subquery):
                    e.at_nanos = at
                else:
                    raise ValueError("promql: @ on non-selector")
            else:
                return e

    def _label_list(self) -> list[str]:
        self.eat(text="(")
        labels = []
        while self.cur.text != ")":
            labels.append(self.eat("ident").text)
            if self.cur.text == ",":
                self.eat(text=",")
        self.eat(text=")")
        return labels

    def _matchers(self) -> list[Matcher]:
        self.eat(text="{")
        out = []
        while self.cur.text != "}":
            name = self.eat("ident").text
            op = self.eat("op").text
            if op not in ("=", "!=", "=~", "!~"):
                raise ValueError(f"promql: bad matcher op {op}")
            val = self.eat("string").text[1:-1]
            out.append(Matcher(name, op, val))
            if self.cur.text == ",":
                self.eat(text=",")
        self.eat(text="}")
        return out

    def parse_atom(self) -> Expr:
        t = self.cur
        if t.kind == "number":
            self.i += 1
            return NumberLiteral(float(t.text))
        if t.kind == "duration":
            # bare durations can appear as numbers in some positions
            self.i += 1
            return NumberLiteral(_duration_nanos(t.text) / 1e9)
        if t.kind == "string":
            self.i += 1
            return StringLiteral(t.text[1:-1])
        if t.kind == "op" and t.text == "(":
            self.eat(text="(")
            e = self.parse_expr(0)
            self.eat(text=")")
            return e
        if t.kind == "op" and t.text == "{":
            return VectorSelector(None, self._matchers())
        if t.kind == "ident":
            name = t.text
            self.i += 1
            # aggregation with modifiers
            if name in AGG_OPS and self.cur.kind in ("by", "without") or (
                name in AGG_OPS and self.cur.text == "("
            ):
                return self._aggregation(name)
            if name in FUNCTIONS and self.cur.text == "(":
                self.eat(text="(")
                args = []
                while self.cur.text != ")":
                    args.append(self.parse_expr(0))
                    if self.cur.text == ",":
                        self.eat(text=",")
                self.eat(text=")")
                return Call(name, args)
            matchers = self._matchers() if self.cur.text == "{" else []
            return VectorSelector(name, matchers)
        raise ValueError(f"promql: unexpected token {t.text!r}")

    def _aggregation(self, op: str) -> Expr:
        grouping: list[str] = []
        without = False
        if self.cur.kind in ("by", "without"):
            without = self.eat().kind == "without"
            grouping = self._label_list()
        self.eat(text="(")
        args = [self.parse_expr(0)]
        while self.cur.text == ",":
            self.eat(text=",")
            args.append(self.parse_expr(0))
        self.eat(text=")")
        if self.cur.kind in ("by", "without"):
            without = self.eat().kind == "without"
            grouping = self._label_list()
        if len(args) == 2:
            param, expr = args[0], args[1]
        else:
            param, expr = None, args[0]
        return Aggregation(op=op, expr=expr, param=param, grouping=grouping, without=without)


def parse(query: str) -> Expr:
    return Parser(lex(query)).parse()
