"""Per-tenant cost attribution: who is spending what, fleet-wide.

Reference shape: the reference's chained ``x/cost`` enforcer attributes
per-scope spend, and Monarch/"The Tail at Scale" both make per-user
quota + attribution the prerequisite for tail-latency control in a shared
metrics store. This module is the attribution substrate ROADMAP open item
3's scheduler keys off:

- a **tenant identity** rides a thread-local (:func:`tenant_context`) set
  by the coordinator from the ``M3-Tenant`` header / ``tenant=`` query
  param and re-established on the far side of every RPC hop by the server
  middleware (the ``_tenant`` wire frame field, same shape as ``_trace``)
  — so dbnode-side decode work is attributed to the caller too;
- a :class:`TenantLedger` keeps rolling-window + cumulative per-tenant
  accounting (queries, rpcs, series, datapoints, bytes streamed vs
  resident, decode device-seconds via the KernelProfiler attribution
  hook, cache hits/misses, limit rejections, sheds, errors), exposed as
  cardinality-capped ``m3tpu_tenant_*`` counters — which the selfmon
  collector stores into ``_m3tpu`` like any other registry family, so
  ``tenant:shed:rate5m``-style ruler rules work immediately — and served
  live at ``/debug/tenants``;
- :class:`TenantEnforcers` provides the per-tenant MIDDLE scope of the
  cost-enforcer chain (query → tenant → global): per-tenant
  :class:`~m3_tpu.query.cost.QueryLimits` loaded from a config file
  (:func:`load_tenant_limits`), so one tenant's runaway scan 422s without
  starving the fleet.

Cardinality: tenant ids come off unauthenticated HTTP headers and wire
frames, so every per-tenant structure here is capped — past
``max_tenants`` distinct ids, accounting collapses into the
``__overflow__`` tenant and the collapse is counted loudly
(``m3tpu_tenant_overflow_total``), the same discipline as the
RpcMiddleware per-op metric cap.

Configuration:

    M3_TPU_TENANT_CAP           distinct tenants tracked (default 64)
    M3_TPU_TENANT_WINDOW_SECS   rolling accounting window (default 300)
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..utils import instrument
from ..utils.instrument import DEFAULT as METRICS
from .cost import GlobalEnforcer, QueryLimits

# the identity every unattributed request gets: header/param absent, or
# work initiated by the fleet itself (ruler evals, selfmon scrapes)
DEFAULT_TENANT = "anonymous"

# where capped / invalid identities collapse (counted loudly): a flood of
# distinct wire-driven tenant ids must bound every per-tenant structure
OVERFLOW_TENANT = "__overflow__"

# sane tenant ids: bounded length, no exposition-hostile characters (the
# value lands in Prometheus label values and PromQL matchers)
TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:-]{0,63}$")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def normalize(raw) -> str:
    """An untrusted tenant identity → a safe ledger/label key.

    ``None``/empty → :data:`DEFAULT_TENANT`; a malformed id (wrong type,
    oversized, exposition-hostile characters) collapses to
    :data:`OVERFLOW_TENANT` and is counted — junk must never mint new
    label values or pollute the anonymous bucket."""
    if raw is None:
        return DEFAULT_TENANT
    if not isinstance(raw, str) or not raw:
        LEDGER.count_invalid()
        return OVERFLOW_TENANT
    if raw in (DEFAULT_TENANT, OVERFLOW_TENANT):
        return raw
    if TENANT_RE.match(raw) is None:
        LEDGER.count_invalid()
        return OVERFLOW_TENANT
    return raw


# --- thread-local tenant context -----------------------------------------

_local = threading.local()


def current() -> str | None:
    """The tenant active on this thread (None outside any request)."""
    return getattr(_local, "tenant", None)


class _TenantContext:
    """``with tenant_context("alpha"):`` — set/restore the thread's tenant
    (re-entrant: nested contexts restore the outer tenant on exit)."""

    __slots__ = ("tenant", "_prev")

    def __init__(self, tenant: str | None) -> None:
        self.tenant = tenant

    def __enter__(self) -> "_TenantContext":
        self._prev = current()
        if self.tenant is not None:
            _local.tenant = self.tenant
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        _local.tenant = self._prev


def tenant_context(tenant: str | None) -> _TenantContext:
    return _TenantContext(tenant)


# --- the ledger ----------------------------------------------------------

# every accountable resource; ``charge()`` kwargs, bucket keys, metric
# fields and dump columns all share this vocabulary
FIELDS = (
    "queries",
    "rpcs",
    "writes",
    "series",
    "datapoints",
    "bytes_streamed",
    "bytes_resident",
    "decode_seconds",
    "cache_hits",
    "cache_misses",
    "limit_rejections",
    "sheds",
    "errors",
)


class _Account:
    """One tenant's totals + rolling-window buckets (guarded by the
    ledger lock — charges are a handful of dict adds, far cheaper than a
    per-account lock ladder)."""

    __slots__ = ("totals", "buckets", "handles", "first_seen")

    def __init__(self, handles: dict, now: float) -> None:
        self.totals = dict.fromkeys(FIELDS, 0.0)
        # (bucket_index, {field: amount}) — newest last
        self.buckets: deque = deque()
        self.handles = handles
        self.first_seen = now


class TenantLedger:
    """Rolling-window per-tenant resource accounting.

    Charges land in cumulative totals, per-tenant ``m3tpu_tenant_*``
    registry counters (so the selfmon collector stores them in
    ``_m3tpu``), and a ring of coarse time buckets whose in-window sum
    :meth:`dump` reports — "what is tenant X doing RIGHT NOW" next to
    "what has it done ever".

    Bounded: at most ``max_tenants`` distinct accounts; past the cap new
    identities collapse into :data:`OVERFLOW_TENANT` (counted in
    ``m3tpu_tenant_overflow_total``) — tenant ids arrive off
    unauthenticated HTTP and wire input, and both the metric registry and
    this ledger must stay flood-proof (the RpcMiddleware per-op cap
    discipline)."""

    def __init__(
        self,
        max_tenants: int | None = None,
        window_secs: float | None = None,
        registry=None,
        clock=time.monotonic,
    ) -> None:
        self.max_tenants = max(
            max_tenants
            if max_tenants is not None
            else _env_int("M3_TPU_TENANT_CAP", 64),
            1,
        )
        self.window_secs = max(
            window_secs
            if window_secs is not None
            else _env_float("M3_TPU_TENANT_WINDOW_SECS", 300.0),
            1.0,
        )
        # ~30 buckets per window: coarse enough to stay tiny, fine enough
        # that the window sum moves smoothly as buckets expire
        self.bucket_secs = self.window_secs / 30.0
        self.clock = clock
        self._reg = registry if registry is not None else METRICS
        self._accounts: dict[str, _Account] = {}
        self._lock = threading.Lock()
        self._overflow = self._reg.counter(
            "tenant_overflow_total",
            "tenant identities collapsed into __overflow__ past the "
            "cardinality cap",
        )
        self._invalid = self._reg.counter(
            "tenant_invalid_ids_total",
            "malformed tenant identities (wrong type/charset/length) "
            "collapsed into __overflow__",
        )
        self._active = self._reg.gauge(
            "tenant_active", "distinct tenants currently tracked"
        )

    def count_invalid(self) -> None:
        self._invalid.inc()

    def _handles(self, tenant: str) -> dict:
        reg = self._reg
        labels = {"tenant": tenant}
        return {
            "queries": reg.counter(
                "tenant_queries_total", "completed queries", labels
            ),
            "rpcs": reg.counter(
                "tenant_rpcs_total",
                "wire-attributed RPC dispatches (dbnode-side work)",
                labels,
            ),
            "writes": reg.counter(
                "tenant_datapoints_written_total",
                "ingested datapoints attributed to the tenant",
                labels,
            ),
            "series": reg.counter(
                "tenant_series_scanned_total", "", labels
            ),
            "datapoints": reg.counter(
                "tenant_datapoints_scanned_total", "", labels
            ),
            "bytes_streamed": reg.counter(
                "tenant_bytes_streamed_total",
                "scan bytes served off the streamed path",
                labels,
            ),
            "bytes_resident": reg.counter(
                "tenant_bytes_resident_total",
                "scan bytes served from HBM residency",
                labels,
            ),
            "decode_seconds": reg.counter(
                "tenant_decode_seconds_total",
                "sampled decode device-seconds (KernelProfiler "
                "attribution under M3_TPU_PROFILE_SAMPLE_RATE)",
                labels,
            ),
            "cache_hits": reg.counter(
                "tenant_cache_hits_total", "", labels
            ),
            "cache_misses": reg.counter(
                "tenant_cache_misses_total", "", labels
            ),
            "limit_rejections": reg.counter(
                "tenant_limit_exceeded_total",
                "cost-limit 422s attributed to the tenant",
                labels,
            ),
            "sheds": reg.counter(
                "tenant_shed_total",
                "requests shed at admission for the tenant",
                labels,
            ),
            "errors": reg.counter(
                "tenant_query_errors_total", "", labels
            ),
        }

    def _account(self, tenant: str) -> _Account:
        acct = self._accounts.get(tenant)
        if acct is not None:
            return acct
        with self._lock:
            acct = self._accounts.get(tenant)
            if acct is not None:
                return acct
            if (
                len(self._accounts) >= self.max_tenants
                and tenant != OVERFLOW_TENANT
            ):
                self._overflow.inc()
                tenant = OVERFLOW_TENANT
                acct = self._accounts.get(tenant)
                if acct is not None:
                    return acct
            # metric children are created here, so registry cardinality is
            # bounded by the same cap as the account dict
            acct = self._accounts[tenant] = _Account(
                self._handles(tenant), self.clock()
            )
            self._active.set(len(self._accounts))
            return acct

    def charge(self, tenant: str | None, **amounts) -> None:
        """Charge resources against ``tenant`` (None → anonymous).
        Kwargs are :data:`FIELDS`; unknown fields raise — the accounting
        vocabulary is fixed, not grow-by-typo."""
        for k in amounts:
            if k not in FIELDS:
                raise TypeError(f"unknown ledger field {k!r}")
        name = tenant if tenant is not None else DEFAULT_TENANT
        acct = self._account(name)
        bucket = int(self.clock() // self.bucket_secs)
        horizon = bucket - 30
        with self._lock:
            totals = acct.totals
            handles = acct.handles
            for k, v in amounts.items():
                if not v:
                    continue
                totals[k] += v
                handles[k].inc(v)
            ring = acct.buckets
            if not ring or ring[-1][0] != bucket:
                ring.append((bucket, dict.fromkeys(FIELDS, 0.0)))
            cur = ring[-1][1]
            for k, v in amounts.items():
                if v:
                    cur[k] += v
            while ring and ring[0][0] <= horizon:
                ring.popleft()

    def window_totals(self, tenant: str) -> dict | None:
        """In-window sums for one tenant (None if untracked)."""
        with self._lock:
            acct = self._accounts.get(tenant)
            if acct is None:
                return None
            return self._window_locked(acct)

    def _window_locked(self, acct: _Account) -> dict:
        horizon = int(self.clock() // self.bucket_secs) - 30
        out = dict.fromkeys(FIELDS, 0.0)
        for idx, vals in acct.buckets:
            if idx <= horizon:
                continue
            for k, v in vals.items():
                out[k] += v
        return out

    def dump(self) -> dict:
        """The ``/debug/tenants`` surface: per-tenant window + cumulative
        columns, heaviest (window datapoints) first, plus the loud
        overflow/invalid tallies."""
        with self._lock:
            rows = [
                {
                    "tenant": name,
                    "window": self._window_locked(acct),
                    "total": dict(acct.totals),
                }
                for name, acct in self._accounts.items()
            ]
        rows.sort(
            key=lambda r: (-r["window"]["datapoints"], r["tenant"])
        )
        return {
            "windowSecs": self.window_secs,
            "tenants": rows,
            "overflows": self._overflow.value,
            "invalidIds": self._invalid.value,
        }


# process-wide ledger (what /debug/tenants serves and stats.finish,
# RpcMiddleware, and the kernel attribution hook charge into)
LEDGER = TenantLedger()


def _attribute_kernel_seconds(kernel: str, secs: float) -> None:
    """KernelProfiler attribution hook: a SAMPLED, block_until_ready-
    bounded dispatch that ran under a tenant context charges its device
    seconds to that tenant — on the coordinator (local storage) and on
    dbnodes (the wire `_tenant` field re-established the context around
    dispatch), so decode device-time is attributed wherever it burns.
    Sampled: totals are an M3_TPU_PROFILE_SAMPLE_RATE-fraction estimate,
    like the kernel_dispatch_seconds histogram they ride beside."""
    tenant = current()
    if tenant is None:
        return
    LEDGER.charge(tenant, decode_seconds=secs)


instrument.set_kernel_attribution(_attribute_kernel_seconds)


def charge_writes(n: int) -> None:
    """Attribute ``n`` ingested datapoints to the active tenant context
    (no-op outside one): the write-path twin of stats.finish's query
    charge, called by the coordinator ingest surfaces and the dbnode's
    wire write ops — write-heavy tenants must show their spend too."""
    if not n:
        return
    tenant = current()
    if tenant is None:
        return
    LEDGER.charge(tenant, writes=n)


# --- per-tenant cost-limit scopes ----------------------------------------


@dataclass
class TenantLimitSet:
    """Parsed per-tenant limits config (:func:`load_tenant_limits`)."""

    by_tenant: dict = field(default_factory=dict)  # tenant -> QueryLimits
    default_limits: QueryLimits | None = None  # unlisted tenants


def load_tenant_limits(path: str) -> TenantLimitSet:
    """Load the per-tenant limits file (YAML or JSON)::

        default:            # optional: every unlisted tenant
          max_series: 0     # 0 = unlimited
          max_datapoints: 0
        tenants:
          alpha:
            max_datapoints: 50000
          beta: {}          # listed, unlimited

    Limits bound the tenant's CONCURRENT in-flight spend (the middle
    scope of the enforcer chain), exactly like the global scope bounds
    the fleet's."""
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f"tenant limits file {path}: expected a mapping")
    unknown = set(data) - {"default", "tenants"}
    if unknown:
        raise ValueError(
            f"tenant limits file {path}: unknown keys {sorted(unknown)}"
        )

    def parse_limits(what: str, raw) -> QueryLimits:
        if raw is None:
            raw = {}
        if not isinstance(raw, dict):
            raise ValueError(f"{path}: {what}: expected a mapping")
        bad = set(raw) - {"max_series", "max_datapoints"}
        if bad:
            raise ValueError(f"{path}: {what}: unknown keys {sorted(bad)}")
        return QueryLimits(
            max_series=int(raw.get("max_series", 0)),
            max_datapoints=int(raw.get("max_datapoints", 0)),
        )

    out = TenantLimitSet()
    if "default" in data and data["default"] is not None:
        out.default_limits = parse_limits("default", data["default"])
    tenants = data.get("tenants") or {}
    if not isinstance(tenants, dict):
        raise ValueError(f"{path}: tenants: expected a mapping")
    for name, raw in tenants.items():
        name = str(name)
        if TENANT_RE.match(name) is None:
            raise ValueError(f"{path}: bad tenant id {name!r}")
        out.by_tenant[name] = parse_limits(f"tenants.{name}", raw)
    return out


class TenantEnforcers:
    """The per-tenant MIDDLE scope of the chained cost enforcer
    (query → tenant → global): one long-lived
    :class:`~m3_tpu.query.cost.GlobalEnforcer` per tenant accumulating
    that tenant's concurrent in-flight spend, parented on the fleet-wide
    global scope. Capped like the ledger: past ``max_tenants`` distinct
    ids share the overflow scope (default limits), so a tenant-id flood
    cannot mint unbounded enforcers."""

    def __init__(
        self,
        limits_by_tenant: dict | None = None,
        global_enforcer: GlobalEnforcer | None = None,
        default_limits: QueryLimits | None = None,
        max_tenants: int | None = None,
    ) -> None:
        self.limits_by_tenant = dict(limits_by_tenant or {})
        self.global_enforcer = global_enforcer
        self.default_limits = default_limits
        self.max_tenants = max(
            max_tenants
            if max_tenants is not None
            else _env_int("M3_TPU_TENANT_CAP", 64),
            1,
        )
        self._scopes: dict[str, GlobalEnforcer] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_limit_set(
        cls,
        limit_set: TenantLimitSet,
        global_enforcer: GlobalEnforcer | None = None,
    ) -> "TenantEnforcers":
        return cls(
            limits_by_tenant=limit_set.by_tenant,
            global_enforcer=global_enforcer,
            default_limits=limit_set.default_limits,
        )

    def scope_for(self, tenant: str | None) -> GlobalEnforcer:
        name = normalize(tenant)
        scope = self._scopes.get(name)
        if scope is not None:
            return scope
        with self._lock:
            scope = self._scopes.get(name)
            if scope is not None:
                return scope
            if (
                len(self._scopes) >= self.max_tenants
                and name != OVERFLOW_TENANT
            ):
                name = OVERFLOW_TENANT
                scope = self._scopes.get(name)
                if scope is not None:
                    return scope
            limits = self.limits_by_tenant.get(name, self.default_limits)
            scope = self._scopes[name] = GlobalEnforcer(
                limits if limits is not None else QueryLimits(),
                scope="tenant",
                what=f"tenant {name}",
                parent=self.global_enforcer,
            )
            return scope
