"""Metric tag filters: glob patterns + conjunctive tag filter maps.

Reference: /root/reference/src/metrics/filters/ — filter.go glob patterns
(wildcard '*', negation '!', char ranges '[a-z]' and alternation '{a,b}'),
tags_filter.go `ParseTagFilterValueMap` ("tag1:pat1 tag2:pat2") + conjunction
matching.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from ..block.core import Tags


def glob_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "*":
            out.append(".*")
        elif ch == "[":
            j = pattern.find("]", i)
            if j < 0:
                out.append(re.escape(ch))
            else:
                out.append(pattern[i : j + 1])
                i = j
        elif ch == "{":
            j = pattern.find("}", i)
            if j < 0:
                out.append(re.escape(ch))
            else:
                alts = pattern[i + 1 : j].split(",")
                out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
                i = j
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


@dataclass
class Filter:
    """Single-value glob filter with optional '!' negation (filter.go:90-130)."""

    pattern: str

    def __post_init__(self) -> None:
        pat = self.pattern
        self.negated = pat.startswith("!")
        if self.negated:
            pat = pat[1:]
        self._re = re.compile("^" + glob_to_regex(pat) + "$")

    def matches(self, value: bytes | str) -> bool:
        if isinstance(value, bytes):
            value = value.decode()
        ok = self._re.match(value) is not None
        return ok != self.negated


@dataclass
class TagsFilter:
    """Conjunction of per-tag filters (tags_filter.go:137+)."""

    filters: dict[bytes, Filter]

    @staticmethod
    def parse(s: str) -> "TagsFilter":
        """ParseTagFilterValueMap: space-separated `name:pattern` pairs."""
        filters: dict[bytes, Filter] = {}
        for part in s.split():
            if ":" not in part:
                raise ValueError(f"invalid tag filter {part!r}")
            name, pat = part.split(":", 1)
            filters[name.encode()] = Filter(pat)
        return TagsFilter(filters)

    def matches(self, tags: Tags) -> bool:
        tag_map = dict(tags)
        for name, f in self.filters.items():
            v = tag_map.get(name)
            if f.negated and v is None:
                # absent tag satisfies a pure-negation filter
                if f.pattern == "!*" or f.matches(b""):
                    continue
                return False
            if v is None:
                return False
            if not f.matches(v):
                return False
        return True
