"""Mapping and rollup rules + the active rule set matcher.

Reference: /root/reference/src/metrics/rules/ — mapping.go (filter → storage
policies / drop), rollup.go + rollup_target.go (filter → rollup metric with
grouped tags + pipeline), active_ruleset.go ForwardMatch, matcher/ per-ID
match caching, and src/metrics/transformation/ unary ops.

Rules are versioned via snapshots with cutover times exactly like
ruleset.go's snapshots: a match at time T uses the latest snapshot whose
cutover <= T.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..block.core import Tags, make_tags
from ..metrics.policy import StoragePolicy
from ..metrics.types import AggregationType
from .filters import TagsFilter

NAME_TAG = b"__name__"
ROLLUP_TAG = b"m3_rollup"  # marks generated rollup metrics


class TransformationType(enum.IntEnum):
    """src/metrics/transformation/type.go."""

    UNKNOWN = 0
    ABSOLUTE = 1
    PERSECOND = 2
    INCREASE = 3
    ADD = 4
    RESET = 5


@dataclass(frozen=True)
class RollupTarget:
    """rollup_target.go: new metric from grouped tags + policies."""

    new_name: bytes
    group_by: tuple[bytes, ...]  # tags retained on the rollup metric
    aggregations: tuple[AggregationType, ...] = ()
    policies: tuple[StoragePolicy, ...] = ()
    pipeline: tuple[TransformationType, ...] = ()


@dataclass
class MappingRule:
    """mapping.go: filter → storage policies (or drop)."""

    name: str
    filter: TagsFilter
    policies: tuple[StoragePolicy, ...] = ()
    aggregations: tuple[AggregationType, ...] = ()
    drop: bool = False
    cutover_nanos: int = 0


@dataclass
class RollupRule:
    name: str
    filter: TagsFilter
    targets: tuple[RollupTarget, ...] = ()
    cutover_nanos: int = 0


@dataclass
class MatchResult:
    """active_ruleset.go ForwardMatch output."""

    policies: tuple[StoragePolicy, ...] = ()
    aggregations: tuple[AggregationType, ...] = ()
    drop: bool = False
    rollups: tuple[tuple[Tags, RollupTarget], ...] = ()


@dataclass
class RuleSet:
    """Versioned rule set (ruleset.go): snapshots selected by cutover time."""

    mapping_rules: list[MappingRule] = field(default_factory=list)
    rollup_rules: list[RollupRule] = field(default_factory=list)
    version: int = 1

    def active_at(self, time_nanos: int) -> "ActiveRuleSet":
        return ActiveRuleSet(
            [r for r in self.mapping_rules if r.cutover_nanos <= time_nanos],
            [r for r in self.rollup_rules if r.cutover_nanos <= time_nanos],
        )


class ActiveRuleSet:
    """ForwardMatch (active_ruleset.go:119+) with per-ID result caching
    (matcher/cache)."""

    def __init__(self, mapping_rules, rollup_rules) -> None:
        self.mapping_rules = mapping_rules
        self.rollup_rules = rollup_rules
        self._cache: dict[Tags, MatchResult] = {}

    def forward_match(self, tags: Tags) -> MatchResult:
        cached = self._cache.get(tags)
        if cached is not None:
            return cached
        policies: list[StoragePolicy] = []
        aggs: list[AggregationType] = []
        drop = False
        for rule in self.mapping_rules:
            if rule.filter.matches(tags):
                if rule.drop:
                    drop = True
                policies.extend(rule.policies)
                aggs.extend(rule.aggregations)
        rollups = []
        for rule in self.rollup_rules:
            if rule.filter.matches(tags):
                for target in rule.targets:
                    kept = tuple(
                        (k, v) for k, v in tags if k in target.group_by
                    )
                    out_tags = make_tags(
                        [(NAME_TAG, target.new_name), (ROLLUP_TAG, b"true"), *kept]
                    )
                    rollups.append((out_tags, target))
        result = MatchResult(
            policies=tuple(dict.fromkeys(policies)),
            aggregations=tuple(dict.fromkeys(aggs)),
            drop=drop,
            rollups=tuple(rollups),
        )
        self._cache[tags] = result
        return result


def encode_tags_id(tags: Tags) -> bytes:
    """Canonical tag-encoded metric ID (the role of metric/id/m3 ids).

    Length-prefixed wire format (x/serialize/encoder.go:55-191 semantics) so
    tag bytes containing ','/'=' can never produce colliding IDs.
    """
    from ..utils.serialize import encode_tags

    return encode_tags(tags)


def decode_tags_id(mid: bytes) -> Tags:
    from ..utils.serialize import decode_tags

    if not mid:
        return ()
    return tuple(sorted(decode_tags(mid)))
