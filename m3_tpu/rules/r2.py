"""r2 rule-management API: JSON codec + KV-backed CRUD for rulesets.

Reference: /root/reference/src/ctl/service/r2/ — the rules REST service the
r2ctl UI drives (routes over namespaces + mapping/rollup rules), persisting
versioned rulesets the matcher service (rules/matcher.py) watches from KV.
This module is the JSON <-> RuleSet codec plus a small store facade; the
coordinator exposes the HTTP routes.
"""

from __future__ import annotations

from ..metrics.policy import StoragePolicy
from ..metrics.types import AggregationType
from .filters import TagsFilter
from .matcher import NAMESPACES_KEY, ruleset_key
from .rules import (
    MappingRule,
    RollupRule,
    RollupTarget,
    RuleSet,
    TransformationType,
)


def _filter_to_str(f: TagsFilter) -> str:
    return " ".join(
        f"{name.decode()}:{flt.pattern}" for name, flt in sorted(f.filters.items())
    )


def mapping_rule_to_dict(r: MappingRule) -> dict:
    return {
        "name": r.name,
        "filter": _filter_to_str(r.filter),
        "policies": [str(p) for p in r.policies],
        "aggregations": [a.name for a in r.aggregations],
        "drop": r.drop,
        "cutoverNanos": r.cutover_nanos,
    }


def rollup_rule_to_dict(r: RollupRule) -> dict:
    return {
        "name": r.name,
        "filter": _filter_to_str(r.filter),
        "targets": [
            {
                "newName": t.new_name.decode(),
                "groupBy": [g.decode() for g in t.group_by],
                "aggregations": [a.name for a in t.aggregations],
                "policies": [str(p) for p in t.policies],
                "pipeline": [op.name for op in t.pipeline],
            }
            for t in r.targets
        ],
        "cutoverNanos": r.cutover_nanos,
    }


def ruleset_to_dict(rs: RuleSet) -> dict:
    return {
        "version": rs.version,
        "mappingRules": [mapping_rule_to_dict(r) for r in rs.mapping_rules],
        "rollupRules": [rollup_rule_to_dict(r) for r in rs.rollup_rules],
    }


def mapping_rule_from_dict(d: dict) -> MappingRule:
    return MappingRule(
        name=d["name"],
        filter=TagsFilter.parse(d["filter"]),
        policies=tuple(StoragePolicy.parse(p) for p in d.get("policies", [])),
        aggregations=tuple(
            AggregationType[a] for a in d.get("aggregations", [])
        ),
        drop=bool(d.get("drop", False)),
        cutover_nanos=int(d.get("cutoverNanos", 0)),
    )


def rollup_rule_from_dict(d: dict) -> RollupRule:
    return RollupRule(
        name=d["name"],
        filter=TagsFilter.parse(d["filter"]),
        targets=tuple(
            RollupTarget(
                new_name=t["newName"].encode(),
                group_by=tuple(g.encode() for g in t.get("groupBy", [])),
                aggregations=tuple(
                    AggregationType[a] for a in t.get("aggregations", [])
                ),
                policies=tuple(
                    StoragePolicy.parse(p) for p in t.get("policies", [])
                ),
                pipeline=tuple(
                    TransformationType[op] for op in t.get("pipeline", [])
                ),
            )
            for t in d.get("targets", [])
        ),
        cutover_nanos=int(d.get("cutoverNanos", 0)),
    )


def ruleset_from_dict(d: dict) -> RuleSet:
    return RuleSet(
        mapping_rules=[mapping_rule_from_dict(r) for r in d.get("mappingRules", [])],
        rollup_rules=[rollup_rule_from_dict(r) for r in d.get("rollupRules", [])],
        version=int(d.get("version", 1)),
    )


class RuleStore:
    """CRUD facade over the matcher's KV keys (r2/store role): updates are
    seen live by any rules/matcher.Matcher watching the same KV.

    Namespace-list and version updates ride CAS loops — the coordinator
    serves these routes from a threading HTTP server, and a lost
    read-modify-write would orphan a namespace's ruleset."""

    def __init__(self, kv) -> None:
        self.kv = kv

    def namespaces(self) -> list[str]:
        vv = self.kv.get(NAMESPACES_KEY)
        return list(vv.value) if vv is not None and vv.value else []

    def get(self, namespace: str) -> RuleSet | None:
        vv = self.kv.get(ruleset_key(namespace))
        if vv is None:
            return None
        # rulesets are stored as WIRE-SAFE dicts (a Python RuleSet object
        # cannot cross the networked KV); in-process writers may still have
        # stored the object form
        return ruleset_from_dict(vv.value) if isinstance(vv.value, dict) else vv.value

    def _edit_namespaces(self, fn) -> None:
        while True:
            vv = self.kv.get(NAMESPACES_KEY)
            names = list(vv.value) if vv is not None and vv.value else []
            new = fn(names)
            if new == names:
                return
            try:
                self.kv.check_and_set(
                    NAMESPACES_KEY, vv.version if vv is not None else 0, new
                )
                return
            except ValueError:
                continue  # lost the race; retry on fresh state

    def set(self, namespace: str, rs: RuleSet) -> None:
        key = ruleset_key(namespace)
        while True:
            vv = self.kv.get(key)
            if vv is None:
                cur_ver = 0
            elif isinstance(vv.value, dict):
                cur_ver = int(vv.value.get("version", 0))
            else:
                cur_ver = vv.value.version
            rs.version = cur_ver + 1
            try:
                self.kv.check_and_set(
                    key, vv.version if vv is not None else 0, ruleset_to_dict(rs)
                )
                break
            except ValueError:
                continue
        self._edit_namespaces(
            lambda names: names if namespace in names else names + [namespace]
        )

    def delete(self, namespace: str) -> bool:
        if namespace not in self.namespaces():
            return False
        self._edit_namespaces(lambda names: [n for n in names if n != namespace])
        self.kv.delete(ruleset_key(namespace))
        return True


def listing_dict(store: RuleStore) -> dict:
    """The GET /api/v1/rules response body (shared by the coordinator route
    and the standalone r2ctl service); one namespaces() read per request."""
    names = store.namespaces()
    return {
        "namespaces": names,
        "rulesets": {
            ns: ruleset_to_dict(rs)
            for ns in names
            if (rs := store.get(ns)) is not None
        },
    }
