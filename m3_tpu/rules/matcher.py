"""Rule matcher service: KV-watched rulesets with per-ID match caching.

Reference: /root/reference/src/metrics/matcher/match.go (+ matcher/cache/) —
the coordinator's downsampler doesn't call rulesets directly: a Matcher
watches the rules namespaces key in KV, keeps per-namespace active rulesets
hot, serves ForwardMatch from an LRU cache, and invalidates when a ruleset's
version changes, so rule updates propagate without restarts.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from ..block.core import Tags
from .rules import ActiveRuleSet, MatchResult, RuleSet

NAMESPACES_KEY = "_rules/namespaces"


def ruleset_key(namespace: str) -> str:
    return f"_rules/ruleset/{namespace}"


@dataclass
class MatcherOptions:
    cache_capacity: int = 100_000
    namespaces_key: str = NAMESPACES_KEY


class Matcher:
    """matcher.Matcher: resolve (namespace, id tags, time) → MatchResult."""

    def __init__(self, kv, opts: MatcherOptions | None = None) -> None:
        self.kv = kv
        self.opts = opts or MatcherOptions()
        # RLock: a namespaces update subscribes rulesets (and replays their
        # current values) while already holding the lock
        self._lock = threading.RLock()
        # namespace -> (ruleset version, RuleSet)
        self._rulesets: dict[str, tuple[int, RuleSet]] = {}
        self._active: dict[tuple, ActiveRuleSet] = {}
        # (namespace, tags) -> MatchResult, LRU-bounded (matcher/cache)
        self._cache: OrderedDict = OrderedDict()
        self.cache_hits = 0
        self.cache_misses = 0
        self.invalidations = 0
        self.decode_errors = 0  # undecodable rulesets seen on the watch
        self._unsubs = []
        self._watch_namespaces()

    # -- KV wiring (matcher.go namespaces watch + per-namespace ruleset
    # watches) --

    def _watch_namespaces(self) -> None:
        def on_namespaces(vv) -> None:
            names = list(vv.value or [])
            with self._lock:
                for name in names:
                    if name not in self._rulesets:
                        self._rulesets[name] = (-1, RuleSet())
                        self._subscribe_ruleset(name)
                for gone in set(self._rulesets) - set(names):
                    del self._rulesets[gone]
                self._active.clear()
                self._invalidate_locked()

        self._unsubs.append(self.kv.watch(self.opts.namespaces_key, on_namespaces))
        vv = self.kv.get(self.opts.namespaces_key)
        if vv is not None:
            on_namespaces(vv)

    def _subscribe_ruleset(self, namespace: str) -> None:
        key = ruleset_key(namespace)

        def on_ruleset(vv) -> None:
            rs = vv.value
            if isinstance(rs, dict):
                # networked KV delivers the wire-safe dict form (r2.py)
                from .r2 import ruleset_from_dict

                try:
                    rs = ruleset_from_dict(rs)
                except (KeyError, ValueError, TypeError) as exc:
                    # a ruleset this matcher can't decode (e.g. written by
                    # a newer version) leaves it on the PREVIOUS rules —
                    # make the divergence observable instead of silent
                    import sys as _sys

                    self.decode_errors += 1
                    print(
                        f"WARN matcher: undecodable ruleset for "
                        f"{namespace!r} v{vv.version}: {exc}",
                        file=_sys.stderr, flush=True,
                    )
                    return
            if not isinstance(rs, RuleSet):
                return
            with self._lock:
                cur = self._rulesets.get(namespace)
                if cur is not None and cur[0] == vv.version:
                    return
                self._rulesets[namespace] = (vv.version, rs)
                self._active.clear()
                self._invalidate_locked()

        self._unsubs.append(self.kv.watch(key, on_ruleset))
        vv = self.kv.get(key)
        if vv is not None:
            on_ruleset(vv)

    def _invalidate_locked(self) -> None:
        self._cache.clear()
        self.invalidations += 1

    # -- matching --

    def namespaces(self) -> list[str]:
        with self._lock:
            return sorted(self._rulesets)

    def _cutover_epoch(self, rs: RuleSet, time_nanos: int) -> int:
        """Number of rule cutovers at or before ``time_nanos`` — the active
        set (and thus match results) only changes when this does, so caches
        key on it instead of being time-blind (a rule with a future cutover
        must activate once time passes it)."""
        cutovers = sorted(
            {r.cutover_nanos for r in rs.mapping_rules}
            | {r.cutover_nanos for r in rs.rollup_rules}
        )
        epoch = 0
        for c in cutovers:
            if c <= time_nanos:
                epoch += 1
        return epoch

    def match(self, namespace: str, tags: Tags, time_nanos: int) -> MatchResult:
        with self._lock:
            entry = self._rulesets.get(namespace)
            rs = entry[1] if entry else RuleSet()
            epoch = self._cutover_epoch(rs, time_nanos)
            key = (namespace, epoch, tags)
            cached = self._cache.get(key)
            if cached is not None:
                self._cache.move_to_end(key)
                self.cache_hits += 1
                return cached
            self.cache_misses += 1
            active = self._active.get((namespace, epoch))
            if active is None:
                active = rs.active_at(time_nanos)
                self._active[(namespace, epoch)] = active
            result = active.forward_match(tags)
            self._cache[key] = result
            while len(self._cache) > self.opts.cache_capacity:
                self._cache.popitem(last=False)
            return result

    def close(self) -> None:
        for unsub in self._unsubs:
            unsub()
        self._unsubs = []


def set_namespaces(kv, names: list[str]) -> None:
    """Admin helper: publish the rules namespaces list."""
    kv.set(NAMESPACES_KEY, list(names))


def set_ruleset(kv, namespace: str, ruleset: RuleSet) -> None:
    """Admin helper: publish a namespace's ruleset (bumps the KV version,
    which invalidates every matcher's cache)."""
    kv.set(ruleset_key(namespace), ruleset)
