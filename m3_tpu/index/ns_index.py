"""Namespace reverse index: per-block-start index blocks over segments.

Reference: /root/reference/src/dbnode/storage/index.go — nsIndex.WriteBatch
(:531) inserts into the active mutable segment of the write-time block,
Query (:1182) unions matches across blocks overlapping the query range,
AggregateQuery (:1218) returns tag names/values, WarmFlush (:868) seals
mutable segments into immutable ones.
"""

from __future__ import annotations

import os
import re as _re
import struct
import threading
from dataclasses import dataclass, field

import numpy as np

from ..block.core import Tags
from ..utils.blob import read_checked_blob, write_atomic_checked_blob
from .query import Query, execute
from .segment import Document, MutableSegment, SealedSegment

_SEG_MAGIC = 0x6D334958  # "m3IX"
_SEG_FILE_RE = _re.compile(r"^segments-(-?\d+)\.(db|idx)$")


class IndexBlock:
    def __init__(self, block_start: int) -> None:
        self.block_start = block_start
        self.mutable = MutableSegment()
        self.sealed: list[SealedSegment] = []
        # set on insert/seal, cleared once persisted — so flush only rewrites
        # blocks that actually changed
        self.dirty = False

    @property
    def segments(self):
        return ([self.mutable] if len(self.mutable) else []) + self.sealed

    def seal(self) -> None:
        """WarmFlush: mutable → immutable segment (storage/index.go:868)."""
        if len(self.mutable):
            self.sealed.append(self.mutable.seal())
            self.mutable = MutableSegment()
            self.dirty = True


@dataclass
class QueryResult:
    docs: list[Document]
    exhaustive: bool = True


class NamespaceIndex:
    """nsIndex: block-partitioned reverse index."""

    def __init__(self, block_size_nanos: int, retention_nanos: int | None = None,
                 device_store=None) -> None:
        self.block_size = block_size_nanos
        self.retention = retention_nanos
        self.blocks: dict[int, IndexBlock] = {}
        # the index has its own lock (storage/index.go insert queue +
        # RWMutex role); hot write/query paths no longer ride the db lock
        self.lock = threading.RLock()
        # device-resident tier (index/device/): sealed segments admit
        # into HBM at seal time and queries plan onto batched kernels;
        # None keeps the index fully host-side
        self.device_store = device_store
        # computed postings for regexp/field scans over immutable segments
        # (postings_list_cache.go:59)
        from .postings_cache import PostingsListCache

        self.postings_cache = PostingsListCache()

    # ---- device-tier admission (index/device/store.py) ----

    def _admit_segment(self, seg, block_start: int):
        """Wrap + admit one sealed segment into the device store.
        MUST be called with NO index lock held: admission stages and
        uploads device arrays (the PR 3 pattern — uploads never stall
        writers or queries on this index). Returns the wrapper, or the
        segment unchanged when there is no device tier."""
        if self.device_store is None or hasattr(seg, "search_ast"):
            return seg
        return self.device_store.admit(
            seg, block_start=block_start, label=f"block:{block_start}"
        )

    def _drop_segments(self, segments) -> None:
        """A segment left the index (compacted away, superseded, or
        expired): release its device tier and its postings-cache
        entries so neither outlives it."""
        for seg in segments:
            if self.device_store is not None:
                self.device_store.invalidate(seg)
            self.postings_cache.invalidate_segment(seg)

    def _block_for(self, t_nanos: int) -> IndexBlock:
        bs = (t_nanos // self.block_size) * self.block_size
        blk = self.blocks.get(bs)
        if blk is None:
            blk = IndexBlock(bs)
            self.blocks[bs] = blk
        return blk

    def write(self, series_id: bytes, tags: Tags, t_nanos: int) -> None:
        with self.lock:
            blk = self._block_for(t_nanos)
            blk.mutable.insert(Document(series_id, tags))
            blk.dirty = True

    def write_batch(self, entries: list[tuple[bytes, Tags, int]]) -> None:
        with self.lock:  # one acquisition for the whole batch
            for sid, tags, t in entries:
                blk = self._block_for(t)
                blk.mutable.insert(Document(sid, tags))
                blk.dirty = True

    def query(
        self, q: Query, start_nanos: int, end_nanos: int, limit: int | None = None,
        force_host: bool = False,
    ) -> QueryResult:
        """storage/index.go:1182 — union across overlapping blocks, dedupe.
        ``force_host`` unwraps device-resident segments so the whole query
        runs on the host executor — the parity surface the property suite
        and tools/check_index.py diff the device path against."""
        with self.lock:
            segs = []
            for bs in sorted(self.blocks):
                if bs + self.block_size <= start_nanos or bs >= end_nanos:
                    continue
                segs.extend(self.blocks[bs].segments)
        if force_host:
            segs = [getattr(s, "host", s) for s in segs]
        prematched = None
        if not force_host:
            # cross-segment batched leaf match: >1 device-resident
            # segment in range resolves ALL exact leaves in ONE binary-
            # search launch instead of one per segment (device/batch.py;
            # best-effort — None falls back to per-segment launches)
            device_segs = [s for s in segs if getattr(s, "resident", False)]
            if len(device_segs) > 1:
                from .device import batch

                prematched = batch.prematch(device_segs, q)
        docs = execute(segs, q, limit=limit, cache=self.postings_cache,
                       prematched=prematched)
        exhaustive = limit is None or len(docs) < limit
        return QueryResult(docs=docs, exhaustive=exhaustive)

    def aggregate_query(
        self,
        q: Query | None,
        start_nanos: int,
        end_nanos: int,
        field_filter: list[bytes] | None = None,
    ) -> dict[bytes, set[bytes]]:
        """AggregateQuery (:1218): tag names → value sets, optionally only for
        docs matching q."""
        out: dict[bytes, set[bytes]] = {}
        if q is None:
            with self.lock:
                blocks = list(self.blocks.items())
            for bs, blk in blocks:
                if bs + self.block_size <= start_nanos or bs >= end_nanos:
                    continue
                for seg in blk.segments:
                    for name in seg.fields():
                        if field_filter and name not in field_filter:
                            continue
                        out.setdefault(name, set()).update(seg.terms(name))
            return out
        for doc in self.query(q, start_nanos, end_nanos).docs:
            for name, value in doc.fields:
                if field_filter and name not in field_filter:
                    continue
                out.setdefault(name, set()).add(value)
        return out

    def seal_before(self, t_nanos: int, admit: bool = True) -> None:
        """Seal eligible blocks' mutable segments, then admit the new
        immutable segments into the device tier. Admission runs OUTSIDE
        the index lock (uploads must never stall the hot path); the
        wrapper swaps in by identity afterwards, so a concurrent persist
        or eviction that already replaced the segment simply wins.
        ``admit=False`` skips the device tier — persist_before seals
        through here and admits the compacted DiskSegment instead (one
        upload per flush, not two)."""
        sealed_new: list[tuple[IndexBlock, object]] = []
        with self.lock:
            for bs, blk in list(self.blocks.items()):
                if bs + self.block_size <= t_nanos:
                    before = len(blk.sealed)
                    blk.seal()
                    if len(blk.sealed) > before:
                        sealed_new.append((blk, blk.sealed[-1]))
        if self.device_store is None or not admit:
            return
        for blk, seg in sealed_new:
            wrapper = self._admit_segment(seg, blk.block_start)
            if wrapper is seg:
                continue
            with self.lock:
                replaced = False
                # the block itself must still be SERVED (retention
                # expiry pops it from self.blocks without touching its
                # sealed list) — publishing into an orphaned block would
                # pin device budget no query can ever reach
                if self.blocks.get(blk.block_start) is blk:
                    for i, cur in enumerate(blk.sealed):
                        if cur is seg:
                            blk.sealed[i] = wrapper
                            replaced = True
                            break
            if not replaced:
                # the segment is already gone (persist compaction or
                # retention raced us): don't leak its device tier
                self._drop_segments([wrapper])

    def evict_before(
        self, t_nanos: int, base: str | None = None, ns_name: str | None = None
    ) -> None:
        """Drop index blocks entirely before ``t_nanos``; when a segment
        directory is given, also unlink their persisted segment files so
        expired blocks neither survive on disk nor resurrect at bootstrap
        (storage/index.go block expiry + its file cleanup)."""
        dropped_segments = []
        with self.lock:
            for bs in [b for b in self.blocks if b + self.block_size <= t_nanos]:
                blk = self.blocks.pop(bs)
                dropped_segments.extend(blk.sealed)
        # expired segments release their device tier and postings-cache
        # entries immediately (not on eventual LRU churn)
        self._drop_segments(dropped_segments)
        if base is None or ns_name is None:
            return
        d = self._seg_dir(base, ns_name)
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return
        for n in names:
            m = _SEG_FILE_RE.match(n)
            if m and int(m.group(1)) + self.block_size <= t_nanos:
                try:
                    os.remove(os.path.join(d, n))
                except FileNotFoundError:
                    pass

    # --- persistence (storage/index.go:868 WarmFlush of index blocks +
    # m3ninx/persist segment file sets) ---

    @staticmethod
    def _seg_dir(base: str, ns_name: str) -> str:
        return os.path.join(base, "index", ns_name)

    def persist_before(self, base: str, ns_name: str, t_nanos: int) -> list[str]:
        """Seal blocks entirely before the cutoff; each DIRTY block's
        sealed segments are COMPACTED into one immutable segment
        (builder/multi_segments role) and written in the mmap format
        (disk_segment.py, the fst segment file's role) with an atomic
        replace. The in-memory sealed list is then swapped for the
        zero-copy DiskSegment, so a persisted block's memory cost is page
        cache, not heap. Unchanged blocks are skipped. Returns paths."""
        from .disk_segment import DiskSegment, write_disk_segment
        from .segment import merge_segments

        self.seal_before(t_nanos, admit=False)
        out = []
        d = self._seg_dir(base, ns_name)
        with self.lock:
            blocks = sorted(self.blocks.items())
        for bs, blk in blocks:
            if bs + self.block_size > t_nanos or not blk.sealed:
                continue
            path = os.path.join(d, f"segments-{bs}.idx")
            if not blk.dirty and os.path.exists(path):
                continue
            os.makedirs(d, exist_ok=True)
            seg = (
                blk.sealed[0]
                if len(blk.sealed) == 1
                else merge_segments(blk.sealed)
            )
            write_disk_segment(path, seg)
            # the persisted zero-copy segment replaces the in-memory
            # sealed list; its device tier admits OUTSIDE the index lock
            # (upload staging must not stall writers), then the swap is
            # bookkeeping-only and the replaced segments drop their
            # device tiers + postings-cache entries
            disk = self._admit_segment(DiskSegment(path), bs)
            with self.lock:
                if self.blocks.get(bs) is blk:
                    replaced = blk.sealed
                    blk.sealed = [disk]
                    blk.dirty = False
                else:
                    # retention expired the block mid-persist: the new
                    # segment joins the replaced ones in the drop below
                    replaced = blk.sealed + [disk]
            self._drop_segments(replaced)
            legacy = os.path.join(d, f"segments-{bs}.db")
            if os.path.exists(legacy):
                os.remove(legacy)
            out.append(path)
        return out

    def load_persisted(self, base: str, ns_name: str) -> set[int]:
        """Load persisted index blocks; returns the block starts restored.
        Corrupt files read as absent (the block is then rebuilt from fileset
        IDs by bootstrap)."""
        d = self._seg_dir(base, ns_name)
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return set()
        # one file per block; the mmap format wins over a legacy leftover
        chosen: dict[int, tuple[str, str]] = {}
        for n in sorted(names):
            m = _SEG_FILE_RE.match(n)
            if not m:
                continue
            bs, kind = int(m.group(1)), m.group(2)
            if bs not in chosen or kind == "idx":
                chosen[bs] = (kind, n)
        loaded: set[int] = set()
        for bs, (kind, n) in sorted(chosen.items()):
            if kind == "idx":
                # mmap format: open is O(1), nothing deserialized
                from .disk_segment import DiskSegment

                try:
                    segs = [DiskSegment(os.path.join(d, n))]
                except (ValueError, OSError):
                    continue
            else:  # legacy in-memory blob format
                body = read_checked_blob(os.path.join(d, n), _SEG_MAGIC)
                if body is None:
                    continue
                try:
                    (count,) = struct.unpack_from("<I", body, 0)
                    pos = 4
                    segs = []
                    for _ in range(count):
                        (ln,) = struct.unpack_from("<Q", body, pos)
                        pos += 8
                        segs.append(SealedSegment.deserialize(body[pos : pos + ln]))
                        pos += ln
                except (struct.error, ValueError):
                    continue
            blk = self._block_for(bs)
            # bootstrap re-admission: restored segments go device-resident
            # like freshly sealed ones (no lock is contended at bootstrap,
            # and admission takes none of ours)
            blk.sealed = [self._admit_segment(s, bs) for s in segs]
            blk.dirty = False
            loaded.add(bs)
        return loaded
