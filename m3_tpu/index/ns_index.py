"""Namespace reverse index: per-block-start index blocks over segments.

Reference: /root/reference/src/dbnode/storage/index.go — nsIndex.WriteBatch
(:531) inserts into the active mutable segment of the write-time block,
Query (:1182) unions matches across blocks overlapping the query range,
AggregateQuery (:1218) returns tag names/values, WarmFlush (:868) seals
mutable segments into immutable ones.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..block.core import Tags
from .query import Query, execute
from .segment import Document, MutableSegment, SealedSegment


class IndexBlock:
    def __init__(self, block_start: int) -> None:
        self.block_start = block_start
        self.mutable = MutableSegment()
        self.sealed: list[SealedSegment] = []

    @property
    def segments(self):
        return ([self.mutable] if len(self.mutable) else []) + self.sealed

    def seal(self) -> None:
        """WarmFlush: mutable → immutable segment (storage/index.go:868)."""
        if len(self.mutable):
            self.sealed.append(self.mutable.seal())
            self.mutable = MutableSegment()


@dataclass
class QueryResult:
    docs: list[Document]
    exhaustive: bool = True


class NamespaceIndex:
    """nsIndex: block-partitioned reverse index."""

    def __init__(self, block_size_nanos: int, retention_nanos: int | None = None) -> None:
        self.block_size = block_size_nanos
        self.retention = retention_nanos
        self.blocks: dict[int, IndexBlock] = {}

    def _block_for(self, t_nanos: int) -> IndexBlock:
        bs = (t_nanos // self.block_size) * self.block_size
        blk = self.blocks.get(bs)
        if blk is None:
            blk = IndexBlock(bs)
            self.blocks[bs] = blk
        return blk

    def write(self, series_id: bytes, tags: Tags, t_nanos: int) -> None:
        self._block_for(t_nanos).mutable.insert(Document(series_id, tags))

    def write_batch(self, entries: list[tuple[bytes, Tags, int]]) -> None:
        for sid, tags, t in entries:
            self.write(sid, tags, t)

    def query(
        self, q: Query, start_nanos: int, end_nanos: int, limit: int | None = None
    ) -> QueryResult:
        """storage/index.go:1182 — union across overlapping blocks, dedupe."""
        segs = []
        for bs in sorted(self.blocks):
            if bs + self.block_size <= start_nanos or bs >= end_nanos:
                continue
            segs.extend(self.blocks[bs].segments)
        docs = execute(segs, q, limit=limit)
        exhaustive = limit is None or len(docs) < limit
        return QueryResult(docs=docs, exhaustive=exhaustive)

    def aggregate_query(
        self,
        q: Query | None,
        start_nanos: int,
        end_nanos: int,
        field_filter: list[bytes] | None = None,
    ) -> dict[bytes, set[bytes]]:
        """AggregateQuery (:1218): tag names → value sets, optionally only for
        docs matching q."""
        out: dict[bytes, set[bytes]] = {}
        if q is None:
            for bs, blk in self.blocks.items():
                if bs + self.block_size <= start_nanos or bs >= end_nanos:
                    continue
                for seg in blk.segments:
                    for name in seg.fields():
                        if field_filter and name not in field_filter:
                            continue
                        out.setdefault(name, set()).update(seg.terms(name))
            return out
        for doc in self.query(q, start_nanos, end_nanos).docs:
            for name, value in doc.fields:
                if field_filter and name not in field_filter:
                    continue
                out.setdefault(name, set()).add(value)
        return out

    def seal_before(self, t_nanos: int) -> None:
        for bs, blk in self.blocks.items():
            if bs + self.block_size <= t_nanos:
                blk.seal()

    def evict_before(self, t_nanos: int) -> None:
        for bs in [b for b in self.blocks if b + self.block_size <= t_nanos]:
            del self.blocks[bs]
