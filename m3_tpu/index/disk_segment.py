"""Immutable on-disk index segments, mmap'd and zero-copy.

Reference: /root/reference/src/m3ninx/index/segment/fst/ — the reference
seals mutable segments into mmap'd FST files (segment.go:181: fields FST →
terms FST → postings offsets → bitsets) so an index block's memory cost is
page-cache, not heap, and opening a segment is O(1). This framework's
equivalent keeps the same contract with array-first machinery instead of
FSTs: a single file holding

    header        magic, version, n_docs, n_terms, section table
    fields table  name → [term_start, term_count) into the global term dict
    term offsets  u64[n_terms+1] into the terms blob (per-field sorted)
    terms blob    concatenated term bytes
    postings idx  u64[n_terms, 2] → [start, end) into postings data
    postings data i32[total] ascending doc ids per term
    ids index     u64[n_docs+1] into the ids blob
    ids blob      concatenated doc id bytes
    columns       i32[n_fields, n_docs] — doc's GLOBAL term index per field
                  (-1 = field absent), field-major in sorted field order

The doc store is COLUMNAR (v2): a document's tags are (field, term-index)
references into the shared term dictionary, so the whole docs section is
built by inverting the postings lists with vectorized numpy scatters (no
per-doc Python encode — v1's per-doc tag blobs cost ~20s/M docs to write)
and a doc materializes zero-copy off the term blob. Term lookup is binary
search over the offset table (the FST's job); regexp scans narrow to the
literal-prefix range first (the automaton∩FST prune, fst/regexp/regexp.go).

``DiskSegment`` implements the SealedSegment surface (len/fields/terms/
postings/docs) so the search executor and aggregate queries run on it
unchanged; v1 files (per-doc tag blobs) remain readable.
"""

from __future__ import annotations

import os
import re as _re
import struct
from bisect import bisect_left

import numpy as np

from ..utils.serialize import decode_tags, encode_tags
from .segment import Document, literal_prefix, prefix_upper

MAGIC = 0x4D334658  # "M3FX"
VERSION = 2
V1 = 1

_HDR = struct.Struct("<IIQQ")  # magic, version, n_docs, n_terms
_SECT = struct.Struct("<QQ")  # offset, length
(S_FIELDS, S_TERM_OFFS, S_TERMS, S_POST_IDX, S_POST_DATA, S_IDS_IDX, S_IDS,
 S_COLS) = range(8)
_N_SECTS = {V1: 7, VERSION: 8}


def _align8(n: int) -> int:
    return (n + 7) & ~7


def _header_len(version: int) -> int:
    return _HDR.size + _N_SECTS[version] * _SECT.size


def _iter_term_postings(seg, name: bytes):
    if hasattr(seg, "iter_term_postings"):
        yield from seg.iter_term_postings(name)
    else:
        for t in seg.terms(name):
            yield t, seg.postings(name, t)


def write_disk_segment(path: str, seg) -> str:
    """Serialize any sealed-surface segment to the mmap format; atomic
    replace (persist crash-safety: a torn write never shadows the old
    file). Falls back to the v1 per-doc layout if any doc carries two
    values for one field (the columnar store holds one term per field)."""
    n_docs = len(seg)
    term_blobs: list[bytes] = []
    term_offs: list[int] = [0]
    post_idx: list[tuple[int, int]] = []
    post_chunks: list[np.ndarray] = []
    fields_parts: list[bytes] = []
    cols: list[np.ndarray] = []
    n_terms = 0
    post_off = 0
    blob_off = 0
    field_names = seg.fields()
    for name in field_names:
        col = np.full(n_docs, -1, np.int32)
        assigned = 0
        n_field_terms = 0
        base = n_terms
        for t, p in _iter_term_postings(seg, name):
            t = bytes(t)
            blob_off += len(t)
            term_blobs.append(t)
            term_offs.append(blob_off)
            p = np.asarray(p, np.int32)
            post_chunks.append(p)
            post_idx.append((post_off, post_off + len(p)))
            post_off += len(p)
            col[p] = n_terms  # invert postings → per-doc term reference
            assigned += len(p)
            n_terms += 1
            n_field_terms += 1
        fields_parts.append(
            struct.pack("<I", len(name)) + bytes(name)
            + struct.pack("<QQ", base, n_field_terms)
        )
        if assigned != int(np.count_nonzero(col >= 0)):
            # duplicate field value on some doc: columnar can't hold it
            return _write_disk_segment_v1(path, seg)
        cols.append(col)

    docs_seq = seg.docs
    ids = [bytes(docs_seq[i].id) for i in range(n_docs)]
    ids_blob = b"".join(ids)
    ids_offs = np.zeros(n_docs + 1, "<u8")
    if n_docs:
        np.cumsum(np.fromiter((len(i) for i in ids), np.int64, n_docs),
                  out=ids_offs[1:])

    sections = [
        struct.pack("<I", len(field_names)) + b"".join(fields_parts),
        np.asarray(term_offs, "<u8").tobytes(),
        b"".join(term_blobs),
        np.asarray(post_idx, "<u8").tobytes() if post_idx else b"",
        (np.concatenate(post_chunks) if post_chunks else np.zeros(0, np.int32))
        .astype("<i4")
        .tobytes(),
        ids_offs.tobytes(),
        ids_blob,
        (np.concatenate(cols) if cols else np.zeros(0, np.int32))
        .astype("<i4")
        .tobytes(),
    ]
    return _write_sections(path, VERSION, n_docs, n_terms, sections)


def _write_disk_segment_v1(path: str, seg) -> str:
    """v1 layout: per-doc tag blobs (kept for multi-valued fields)."""
    term_blobs: list[bytes] = []
    term_offs: list[int] = [0]
    post_idx: list[tuple[int, int]] = []
    post_chunks: list[np.ndarray] = []
    fields_parts: list[bytes] = []
    n_terms = 0
    post_off = 0
    blob_off = 0
    for name in seg.fields():
        base = n_terms
        cnt = 0
        for t, p in _iter_term_postings(seg, name):
            t = bytes(t)
            blob_off += len(t)
            term_blobs.append(t)
            term_offs.append(blob_off)
            p = np.asarray(p, np.int32)
            post_chunks.append(p)
            post_idx.append((post_off, post_off + len(p)))
            post_off += len(p)
            n_terms += 1
            cnt += 1
        fields_parts.append(
            struct.pack("<I", len(name)) + bytes(name)
            + struct.pack("<QQ", base, cnt)
        )

    docs_parts: list[bytes] = []
    docs_offs: list[int] = [0]
    off = 0
    n_docs = len(seg)
    docs_seq = seg.docs
    for i in range(n_docs):
        d = docs_seq[i]
        enc = encode_tags(d.fields)
        rec = struct.pack("<I", len(d.id)) + bytes(d.id) + enc
        docs_parts.append(rec)
        off += len(rec)
        docs_offs.append(off)

    sections = [
        struct.pack("<I", len(seg.fields())) + b"".join(fields_parts),
        np.asarray(term_offs, "<u8").tobytes(),
        b"".join(term_blobs),
        np.asarray(post_idx, "<u8").tobytes() if post_idx else b"",
        (np.concatenate(post_chunks) if post_chunks else np.zeros(0, np.int32))
        .astype("<i4")
        .tobytes(),
        np.asarray(docs_offs, "<u8").tobytes(),
        b"".join(docs_parts),
    ]
    return _write_sections(path, V1, n_docs, n_terms, sections)


def _write_sections(path, version, n_docs, n_terms, sections) -> str:
    hdr_len = _header_len(version)
    table = []
    pos = _align8(hdr_len)
    body = []
    for s in sections:
        table.append((pos, len(s)))
        pad = _align8(pos + len(s)) - (pos + len(s))
        body.append(s)
        body.append(b"\0" * pad)
        pos = _align8(pos + len(s))

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        hdr = _HDR.pack(MAGIC, version, n_docs, n_terms)
        hdr += b"".join(_SECT.pack(o, ln) for o, ln in table)
        f.write(hdr)
        f.write(b"\0" * (_align8(hdr_len) - hdr_len))
        for b in body:
            f.write(b)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


class _LazyDocs:
    """Sequence view over the doc store (decoded on access only)."""

    def __init__(self, seg: "DiskSegment") -> None:
        self._seg = seg

    def __len__(self) -> int:
        return self._seg._n_docs

    def __getitem__(self, i: int) -> Document:
        return self._seg.doc(i)


class DiskSegment:
    """Zero-copy mmap'd immutable segment (fst/segment.go role)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._mm = np.memmap(path, dtype=np.uint8, mode="r")
        buf = self._mm
        magic, version, n_docs, n_terms = _HDR.unpack_from(buf, 0)
        if magic != MAGIC or version not in _N_SECTS:
            raise ValueError(f"bad segment file {path!r}")
        self.version = version
        self._n_docs = int(n_docs)
        self._n_terms = int(n_terms)
        sects = [
            _SECT.unpack_from(buf, _HDR.size + i * _SECT.size)
            for i in range(_N_SECTS[version])
        ]

        def view(i, dtype):
            o, ln = sects[i]
            return np.frombuffer(
                buf, dtype=dtype, count=ln // np.dtype(dtype).itemsize, offset=o
            )

        self._term_offs = view(S_TERM_OFFS, "<u8")
        self._terms_blob = memoryview(buf)[
            sects[S_TERMS][0] : sects[S_TERMS][0] + sects[S_TERMS][1]
        ]
        pi = view(S_POST_IDX, "<u8")
        self._post_idx = pi.reshape(-1, 2) if pi.size else pi.reshape(0, 2)
        self._post_data = view(S_POST_DATA, "<i4")
        self._docs_idx = view(S_IDS_IDX, "<u8")
        self._docs_blob = memoryview(buf)[
            sects[S_IDS][0] : sects[S_IDS][0] + sects[S_IDS][1]
        ]
        # fields table is tiny: parse once at open
        o, ln = sects[S_FIELDS]
        fb = bytes(memoryview(buf)[o : o + ln])
        (n_fields,) = struct.unpack_from("<I", fb, 0)
        pos = 4
        self._fields: dict[bytes, tuple[int, int]] = {}
        for _ in range(n_fields):
            (nl,) = struct.unpack_from("<I", fb, pos)
            pos += 4
            name = fb[pos : pos + nl]
            pos += nl
            start, count = struct.unpack_from("<QQ", fb, pos)
            pos += 16
            self._fields[name] = (int(start), int(count))
        if version >= 2:
            all_cols = view(S_COLS, "<i4")
            self._cols = [
                (name, all_cols[k * self._n_docs : (k + 1) * self._n_docs])
                for k, name in enumerate(sorted(self._fields))
            ]
        else:
            self._cols = None
        self._term_cache: dict[int, bytes] = {}  # gi -> bytes, on demand
        self.docs = _LazyDocs(self)

    # --- sealed-segment surface ---

    def __len__(self) -> int:
        return self._n_docs

    def fields(self) -> list[bytes]:
        return sorted(self._fields)

    def _term(self, gi: int) -> bytes:
        return bytes(self._terms_blob[self._term_offs[gi] : self._term_offs[gi + 1]])

    def terms(self, name: bytes):
        start, count = self._fields.get(name, (0, 0))
        return [self._term(start + i) for i in range(count)]

    def iter_terms(self, name: bytes):
        start, count = self._fields.get(name, (0, 0))
        for i in range(count):
            yield start + i, self._term(start + i)

    def iter_term_postings(self, name: bytes):
        for gi, t in self.iter_terms(name):
            s, e = self._post_idx[gi]
            yield t, self._post_data[s:e]

    def _find_term(self, name: bytes, value: bytes) -> int:
        """Global term index, or -1 (binary search — the FST lookup)."""
        start, count = self._fields.get(name, (0, 0))
        if not count:
            return -1

        class _V:  # bisect over a virtual sorted sequence of term bytes
            def __getitem__(s, i):
                return self._term(start + i)

            def __len__(s):
                return count

        i = bisect_left(_V(), bytes(value))
        if i < count and self._term(start + i) == bytes(value):
            return start + i
        return -1

    def postings(self, name: bytes, value: bytes) -> np.ndarray:
        gi = self._find_term(name, value)
        if gi < 0:
            return np.zeros(0, np.int32)
        s, e = self._post_idx[gi]
        return self._post_data[s:e]

    def postings_regexp(self, name: bytes, pattern: bytes) -> np.ndarray:
        """Literal-prefix-pruned regexp scan over the sorted term range
        (fst/regexp/regexp.go automaton∩FST role)."""
        start, count = self._fields.get(name, (0, 0))
        if not count:
            return np.zeros(0, np.int32)
        lo, hi = 0, count

        class _V:
            def __getitem__(s, i):
                return self._term(start + i)

            def __len__(s):
                return count

        pre = literal_prefix(pattern)
        if pre:
            lo = bisect_left(_V(), pre)
            up = prefix_upper(pre)
            hi = bisect_left(_V(), up) if up is not None else count
        rx = _re.compile(b"^(?:" + pattern + b")$")
        out = []
        for i in range(lo, hi):
            gi = start + i
            if rx.match(self._term(gi)):
                s, e = self._post_idx[gi]
                out.append(self._post_data[s:e])
        if not out:
            return np.zeros(0, np.int32)
        return np.unique(np.concatenate(out)).astype(np.int32)

    def postings_for_terms(self, name: bytes, predicate) -> np.ndarray:
        """Union of postings for terms matching predicate(term) (field
        searchers / generic scans)."""
        out = []
        for gi, t in self.iter_terms(name):
            if predicate(t):
                s, e = self._post_idx[gi]
                out.append(self._post_data[s:e])
        if not out:
            return np.zeros(0, np.int32)
        return np.unique(np.concatenate(out)).astype(np.int32)

    def doc_ids(self, postings) -> list[bytes]:
        """Batch doc-id extraction (no tag materialization) — the executor's
        dedupe and the series-select path need only ids."""
        offs = self._docs_idx
        blob = self._docs_blob
        if self.version >= 2:
            return [bytes(blob[offs[i] : offs[i + 1]]) for i in map(int, postings)]
        out = []
        for i in map(int, postings):
            s = int(offs[i])
            (idl,) = struct.unpack_from("<I", blob, s)
            out.append(bytes(blob[s + 4 : s + 4 + idl]))
        return out

    def doc(self, i: int) -> Document:
        s, e = int(self._docs_idx[i]), int(self._docs_idx[i + 1])
        if self.version >= 2:
            did = bytes(self._docs_blob[s:e])
            # term bytes intern lazily per segment: bulk materialization of
            # K docs shares tag-value objects instead of re-slicing the
            # blob K times, while a single-doc lookup only materializes its
            # own few terms
            cache = self._term_cache
            fields = []
            for name, col in self._cols:
                gi = int(col[i])
                if gi < 0:
                    continue
                t = cache.get(gi)
                if t is None:
                    t = cache[gi] = self._term(gi)
                fields.append((name, t))
            return Document(did, tuple(fields))
        rec = bytes(self._docs_blob[s:e])
        (idl,) = struct.unpack_from("<I", rec, 0)
        did = rec[4 : 4 + idl]
        fields = decode_tags(rec[4 + idl :]) if len(rec) > 4 + idl else ()
        return Document(did, tuple(fields))

    def close(self) -> None:
        # memmaps release with the object; explicit close for tests
        self._mm = None
