"""Device-resident sealed index segment.

``DeviceSegment`` wraps a host sealed segment (SealedSegment or
DiskSegment) and — while its device tier is resident — answers WHOLE
query ASTs on device: batched binary-search term match over the packed
term-key matrix, postings-union bitmaps, and bitwise AND/OR/ANDNOT for
conjunction/disjunction/negation (the roaring-bitmap algebra of the
reference's m3ninx executor, as uint32 word kernels). The wrapper also
implements the full SealedSegment surface by delegation, so every host
consumer (aggregate queries, segment merge/persist, peer streaming,
the host executor fallback) runs on it unchanged.

Routing contract (the gating bit-identity property): ``search_ast``
either returns EXACTLY the doc-id array the host executor would
produce, or returns None — evicted / not-admitted / device error —
and the executor transparently re-plans the segment onto the host
path. General regexps keep their term MATCHING host-side (an
automaton cannot become a fixed-width compare) after the literal-prefix
narrow, but their postings union and all surrounding set algebra still
run on device; the routing reason records ``regexp-host-fallback`` so
EXPLAIN shows the hybrid.

Regexp classes resolved fully on device:
- pure literal patterns (a degenerate regexp): batched exact match;
- ``literal.*`` prefixes: the narrowed dictionary range IS the match;
- top-level alternations of literals (``a|b|c``): batched exact match
  of every branch in the same launch.
"""

from __future__ import annotations

import re

import numpy as np

from ..query import (
    AllQuery,
    ConjunctionQuery,
    DisjunctionQuery,
    FieldQuery,
    NegationQuery,
    Query,
    RegexpQuery,
    TermQuery,
)
from ..segment import REGEXP_SPECIALS as _SPECIALS
from ..segment import literal_prefix, prefix_upper
from . import kernels


class _Unsupported(Exception):
    """AST node the device evaluator does not model — host fallback."""


def classify_regexp(pattern: bytes):
    """("literal", value) | ("prefix", prefix) | ("alternation",
    [literals]) | ("general", None) — the classes the device can match
    without a host automaton walk. Conservative: anything unclear is
    general."""
    p = pattern[1:] if pattern.startswith(b"^") else pattern
    if p.endswith(b"$"):
        p = p[:-1]
    if not any(c in p for c in _SPECIALS):
        return "literal", p
    if p.endswith(b".*") and not any(c in p[:-2] for c in _SPECIALS):
        return "prefix", p[:-2]
    alt = _literal_alternation(p)
    if alt is not None:
        return "alternation", alt
    return "general", None


def _literal_alternation(p: bytes):
    """Branches of a top-level alternation of plain literals (one
    optional wrapping group allowed), or None."""
    if p.startswith(b"(") and p.endswith(b")"):
        inner = p[1:-1]
        if b"(" not in inner and b")" not in inner:
            p = inner
    if b"|" not in p:
        return None
    branches = p.split(b"|")
    for b in branches:
        if not b or any(c in b for c in _SPECIALS):
            return None
    return branches


class DeviceArrays:
    """The device tier of one sealed segment (built by store.admit as
    ONE staging upload, sliced/cast on device)."""

    __slots__ = (
        "term_keys", "term_lens", "post_idx", "post_data", "all_words",
        "fields", "k_words", "n_terms", "n_docs", "n_words", "nbytes",
        "host_keys", "host_lens", "dot_safe",
        # weak-referenceable: the cross-segment match cache (batch.py)
        # keys entries by arrays identity WITHOUT pinning the tier alive
        "__weakref__",
    )

    def __init__(self, term_keys, term_lens, post_idx, post_data, all_words,
                 fields, k_words, n_docs, n_words, nbytes,
                 host_keys, host_lens, dot_safe=True) -> None:
        self.term_keys = term_keys
        self.term_lens = term_lens
        self.post_idx = post_idx
        self.post_data = post_data
        self.all_words = all_words
        # name -> (global term start, term count, postings data start,
        # postings data end): the data slice bounds each leaf's bitmap
        # build to O(field postings) — kernels.bitmap_from_terms
        self.fields = fields
        self.k_words = k_words
        self.n_terms = int(term_keys.shape[0])
        self.n_docs = n_docs
        self.n_words = n_words
        self.nbytes = nbytes
        # host mirror of the key matrix: literal-prefix range narrowing
        # and general-regexp candidate walks never touch the device
        self.host_keys = host_keys
        self.host_lens = host_lens
        # the `lit.*` fast class treats the narrowed range as the match,
        # but host `.` does NOT match \n — if any term contains one, the
        # class must downgrade to the host-matched general path or the
        # two executors would disagree on exactly that term
        self.dot_safe = dot_safe


def collect_leaves(query: Query):
    """(leaves [(field, value)], order [(leaf, start_slot, n)], classes
    {id(regexp leaf) -> classification}) for every term / literal-regexp
    / alternation leaf of ``query`` — the batched-binary-search input.
    Shared by the per-segment match below and the CROSS-segment batcher
    (index/device/batch.py), which resolves all of a query's exact
    leaves over every device-resident segment in ONE launch."""
    leaves: list[tuple[bytes, bytes]] = []  # (field, value)
    order: list[tuple[Query, int, int]] = []  # (leaf, start_slot, n)
    classes: dict = {}

    def walk(q: Query) -> None:
        if isinstance(q, TermQuery):
            order.append((q, len(leaves), 1))
            leaves.append((q.field, q.value))
        elif isinstance(q, RegexpQuery):
            kind, val = classes[id(q)] = classify_regexp(q.pattern)
            if kind == "literal":
                order.append((q, len(leaves), 1))
                leaves.append((q.field, val))
            elif kind == "alternation":
                order.append((q, len(leaves), len(val)))
                for branch in val:
                    leaves.append((q.field, branch))
        elif isinstance(q, (ConjunctionQuery, DisjunctionQuery)):
            for s in q.queries:
                walk(s)
        elif isinstance(q, NegationQuery):
            walk(q.query)

    walk(query)
    return leaves, order, classes


class DeviceSegment:
    """SealedSegment-surface wrapper owning a segment's device tier."""

    def __init__(self, host, store, block_start: int | None = None,
                 label: str = "") -> None:
        self.host = host
        self.store = store
        self.block_start = block_start
        self.label = label or f"segment:{id(host):x}"
        # written by the store under ITS lock; read racily on the query
        # path (worst case: one extra fallback or one search against a
        # just-evicted tier, both correct)
        self._arrays: DeviceArrays | None = None
        self._state = "pending"
        self._reserved = 0  # budget bytes the store charged for this tier

    # ---- residency / routing ----

    @property
    def resident(self) -> bool:
        return self._arrays is not None

    def status(self) -> str:
        return self._state

    # ---- SealedSegment surface (host delegation) ----

    @property
    def docs(self):
        return self.host.docs

    def __len__(self) -> int:
        return len(self.host)

    def fields(self):
        return self.host.fields()

    def terms(self, name: bytes):
        return self.host.terms(name)

    def postings(self, name: bytes, value: bytes):
        return self.host.postings(name, value)

    def postings_regexp(self, name: bytes, pattern: bytes):
        return self.host.postings_regexp(name, pattern)

    _DELEGATED = frozenset(
        {"doc_ids", "postings_for_terms", "iter_term_postings", "iter_terms",
         "doc", "path", "version"}
    )

    def __getattr__(self, name: str):
        # hasattr-gated optional surface (MatchedDocs probes doc_ids,
        # the executor probes postings_for_terms): present exactly when
        # the host has it
        if name in DeviceSegment._DELEGATED:
            return getattr(self.host, name)
        raise AttributeError(name)

    # ---- device AST evaluation ----

    def search_ast(self, query: Query, prematched=None) -> np.ndarray | None:
        """Doc ids for the whole AST via device bitmaps — bit-identical
        to the host executor — or None to fall back (evicted / not
        admitted / unsupported node / device error). Never raises: a
        device fault must degrade to the host path, not fail the query.

        ``prematched``: (arrays, gis_map, classes) from the
        cross-segment leaf batcher (index/device/batch.py) — used only
        when its arrays snapshot is still THIS segment's tier (an
        eviction/re-admission between batch and search falls back to a
        private match, never to stale indices)."""
        from ...query import stats

        arrays = self._arrays
        if arrays is None:
            stats.add(index_device_misses=1)
            stats.add_routing(self.label, self.block_start, "index-host",
                              self._state)
            self.store.count_search(hit=False)
            return None
        try:
            note = {"host_regexp": False}
            if prematched is not None and prematched[0] is arrays:
                gis, classes = prematched[1], prematched[2]
            else:
                gis, classes = self._match_leaves(arrays, query)
            bitmap = self._eval(arrays, query, gis, classes, note)
            words = np.asarray(bitmap)
        except _Unsupported:
            stats.add(index_device_misses=1)
            stats.add_routing(self.label, self.block_start, "index-host",
                              "unsupported-node")
            self.store.count_search(hit=False)
            return None
        except Exception:
            # count loudly, never raise: the host path is always correct.
            # This is ALSO a fallback, so the miss counter covers it —
            # hits + misses must always sum to total searches
            self.store.count_error()
            self.store.count_search(hit=False)
            stats.add(index_device_misses=1)
            stats.add_routing(self.label, self.block_start, "index-host",
                              "device-error")
            return None
        self.store.touch(self)
        self.store.count_search(hit=True)
        stats.add(index_device_hits=1)
        stats.add_routing(
            self.label, self.block_start, "index-device",
            "regexp-host-fallback" if note["host_regexp"] else "",
        )
        return kernels.bitmap_to_docids(words)

    # -- phase 1: batch every exact-match leaf into ONE search launch --

    def _match_leaves(self, arrays: DeviceArrays, query: Query):
        """(id(leaf) -> int32 global term indices, id(regexp leaf) ->
        classification) for every term / literal-regexp / alternation
        leaf, resolved by one batched binary search. Patterns classify
        ONCE here; phase 2 reads the cached class."""
        leaves, order, classes = collect_leaves(query)
        if not leaves:
            return {}, classes
        import jax.numpy as jnp

        b = len(leaves)
        b_pad = kernels.pad_pow2(b)
        values = [v for _, v in leaves] + [b""] * (b_pad - b)
        q_keys, q_lens = kernels.build_query_keys(values, arrays.k_words)
        lo = np.zeros(b_pad, np.int32)
        hi = np.zeros(b_pad, np.int32)
        for i, (field, _v) in enumerate(leaves):
            start, count = arrays.fields.get(field, (0, 0, 0, 0))[:2]
            lo[i], hi[i] = start, start + count
        gis = np.asarray(
            kernels.match_terms(
                arrays.term_keys, arrays.term_lens,
                jnp.asarray(lo), jnp.asarray(hi),
                jnp.asarray(q_keys), jnp.asarray(q_lens),
            )
        )
        out: dict = {}
        for leaf, start, n in order:
            out[id(leaf)] = gis[start : start + n]
        return out, classes

    # -- phase 2: bitmap algebra over the resolved leaves --

    def _eval(self, arrays: DeviceArrays, q: Query, gis: dict,
              classes: dict, note: dict):
        import jax.numpy as jnp

        nw = arrays.n_words
        if isinstance(q, TermQuery):
            return self._leaf_bitmap(arrays, gis[id(q)], q.field)
        if isinstance(q, RegexpQuery):
            return self._regexp_bitmap(arrays, q, gis, classes, note)
        if isinstance(q, FieldQuery):
            start, count, ds, de = arrays.fields.get(q.field, (0, 0, 0, 0))
            return kernels.bitmap_from_term_range(
                arrays.post_idx, arrays.post_data,
                jnp.int32(start), jnp.int32(start + count), nw,
                data_start=ds, slab=kernels.pad_pow2(de - ds),
            )
        if isinstance(q, AllQuery):
            return arrays.all_words
        if isinstance(q, ConjunctionQuery):
            if not q.queries:
                return kernels.zero_bitmap(nw)
            pos = [s for s in q.queries if not isinstance(s, NegationQuery)]
            negs = [s for s in q.queries if isinstance(s, NegationQuery)]
            if pos:
                acc = self._eval(arrays, pos[0], gis, classes, note)
                for s in pos[1:]:
                    acc = acc & self._eval(arrays, s, gis, classes, note)
            else:
                acc = arrays.all_words
            for s in negs:
                acc = acc & ~self._eval(arrays, s.query, gis, classes, note)
            return acc
        if isinstance(q, DisjunctionQuery):
            acc = kernels.zero_bitmap(nw)
            for s in q.queries:
                acc = acc | self._eval(arrays, s, gis, classes, note)
            return acc
        if isinstance(q, NegationQuery):
            return arrays.all_words & ~self._eval(
                arrays, q.query, gis, classes, note
            )
        raise _Unsupported(type(q).__name__)

    def _leaf_bitmap(self, arrays: DeviceArrays, leaf_gis: np.ndarray,
                     field: bytes):
        import jax.numpy as jnp

        _, _, ds, de = arrays.fields.get(field, (0, 0, 0, 0))
        b_pad = kernels.pad_pow2(len(leaf_gis))
        padded = np.full(b_pad, -1, np.int32)
        padded[: len(leaf_gis)] = leaf_gis
        return kernels.bitmap_from_terms(
            arrays.post_idx, arrays.post_data, jnp.asarray(padded),
            arrays.n_words, data_start=ds, slab=kernels.pad_pow2(de - ds),
        )

    def _regexp_bitmap(self, arrays: DeviceArrays, q: RegexpQuery,
                       gis: dict, classes: dict, note: dict):
        import jax.numpy as jnp

        kind, _val = classes[id(q)]
        if kind in ("literal", "alternation"):
            return self._leaf_bitmap(arrays, gis[id(q)], q.field)
        start, count, ds, de = arrays.fields.get(q.field, (0, 0, 0, 0))
        if not count:
            return kernels.zero_bitmap(arrays.n_words)
        lo, hi = self._prefix_range(arrays, q.pattern, start, count)
        if kind == "prefix" and not arrays.dot_safe:
            kind = "general"  # a \n-bearing term breaks range == match
        if kind == "prefix":
            # the narrowed range IS the match: every term in it carries
            # the literal prefix and `.*` accepts any suffix
            return kernels.bitmap_from_term_range(
                arrays.post_idx, arrays.post_data,
                jnp.int32(lo), jnp.int32(hi), arrays.n_words,
                data_start=ds, slab=kernels.pad_pow2(de - ds),
            )
        # general pattern: the automaton walk stays host-side over the
        # narrowed candidate slab (reason `regexp-host-fallback` — the
        # postings union below still runs on device)
        note["host_regexp"] = True
        rx = re.compile(b"^(?:" + q.pattern + b")$")
        matched = [
            gi for gi in range(lo, hi) if rx.match(self._host_term(arrays, gi))
        ]
        return self._leaf_bitmap(arrays, np.asarray(matched, np.int32), q.field)

    def _prefix_range(self, arrays: DeviceArrays, pattern: bytes,
                      start: int, count: int) -> tuple[int, int]:
        """[lo, hi) global candidate range from the literal prefix —
        host binary search over the key-matrix mirror (segment.py's
        prefix-prune, shared compare definition in kernels.py)."""
        lo, hi = start, start + count
        pre = literal_prefix(pattern)
        if not pre:
            return lo, hi
        width = 4 * arrays.k_words
        if len(pre) > width:
            # every term is <= width bytes: nothing can carry this prefix
            return start, start
        pk, pl = kernels.build_term_keys([pre], arrays.k_words)
        lo = kernels.host_lower_bound(
            arrays.host_keys, arrays.host_lens, lo, hi, pk[0], int(pl[0])
        )
        up = prefix_upper(pre)
        if up is not None and len(up) <= width:
            uk, ul = kernels.build_term_keys([up], arrays.k_words)
            hi = kernels.host_lower_bound(
                arrays.host_keys, arrays.host_lens, lo, hi, uk[0], int(ul[0])
            )
        return lo, hi

    def _host_term(self, arrays: DeviceArrays, gi: int) -> bytes:
        """Term bytes for a global index, read from the HOST segment
        (DiskSegment addresses globally; SealedSegment via its per-field
        sorted list). ``arrays`` is the caller's snapshot — re-reading
        self._arrays here would race a concurrent eviction into a
        spurious device-error."""
        host = self.host
        term = getattr(host, "_term", None)
        if term is not None:  # DiskSegment: zero-copy global lookup
            return term(gi)
        for name in sorted(arrays.fields):
            start, count = arrays.fields[name][:2]
            if start <= gi < start + count:
                return host.terms(name)[gi - start]
        raise IndexError(gi)
