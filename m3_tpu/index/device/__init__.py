"""Device-resident inverted index tier (the reference's m3ninx L2 layer
— segment/fst term dictionaries + roaring postings — as HBM arrays
queried by batched kernels).

- kernels.py — batched term binary search, postings-union bitmaps,
  and the shared fixed-width key ordering definition;
- segment.py — DeviceSegment: SealedSegment-surface wrapper evaluating
  whole query ASTs on device, bit-identical to the host executor;
- store.py — DeviceIndexStore: seal-time admission, one staged upload
  per segment, LRU eviction under ``--index-device-bytes``.
"""

from .kernels import bitmap_to_docids
from .segment import DeviceSegment, classify_regexp
from .store import DeviceIndexStore, IndexDeviceOptions

__all__ = [
    "DeviceIndexStore",
    "DeviceSegment",
    "IndexDeviceOptions",
    "bitmap_to_docids",
    "classify_regexp",
]
