"""Budget-capped store of device-resident index segments.

The index-tier sibling of the resident pool (m3_tpu/resident/pool.py):
sealed segments admit at seal time, evict LRU under one device byte
budget, and fall back to the host executor transparently when absent.
Admission follows the PR 3 three-phase pattern — stage on host and
UPLOAD OUTSIDE every lock (one staging transfer per segment), reserve
budget under the store lock before the upload, publish after — so a
flush's index upload never stalls queries or writers, and an
invalidation racing the upload drops the pending tier instead of
publishing a stale one.

Admission can REJECT a segment (stays host-only, wrapper records why):
- ``term-too-long``: a term over ``max_term_bytes`` would need a wider
  fixed-width key than the kernels' compare covers (no truncation —
  a truncated compare could return wrong doc ids);
- ``over-budget``: the segment alone exceeds the whole budget;
- ``empty``: nothing to index.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ...utils.instrument import DEFAULT as METRICS
from . import kernels
from .segment import DeviceArrays, DeviceSegment


@dataclass
class IndexDeviceOptions:
    """Knobs for the device index tier (``--index-device-bytes``)."""

    enabled: bool = True
    max_bytes: int = 0  # 0 disables the tier
    max_term_bytes: int = 64  # fixed-width key cap (see store docstring)

    def validate(self) -> None:
        from ...utils.config import ConfigError

        if self.max_bytes < 0:
            raise ConfigError("index_device.max_bytes must be >= 0")
        if self.max_term_bytes <= 0:
            raise ConfigError("index_device.max_term_bytes must be > 0")


class DeviceIndexStore:
    """LRU of device-resident segments under one byte budget."""

    def __init__(self, options: IndexDeviceOptions | None = None,
                 registry=None) -> None:
        self.options = options or IndexDeviceOptions()
        self._lock = threading.Lock()
        self._od: "OrderedDict[int, DeviceSegment]" = OrderedDict()
        self._bytes = 0
        self.admissions = 0
        self.rejections = 0
        self.evictions = 0
        self.invalidations = 0
        self.search_hits = 0
        self.search_misses = 0
        self.errors = 0
        reg = registry or METRICS
        self._m_admissions = reg.counter(
            "index_device_admissions_total",
            "sealed index segments admitted to the device tier",
        )
        self._m_rejections = reg.counter(
            "index_device_rejections_total",
            "segments refused at admission (term-too-long / over-budget)",
        )
        self._m_evictions = reg.counter(
            "index_device_evictions_total", "LRU/budget segment evictions"
        )
        self._m_invalidations = reg.counter(
            "index_device_invalidations_total",
            "segments dropped because they were superseded or expired",
        )
        self._m_hits = reg.counter(
            "index_device_search_hits_total",
            "segment searches answered by the device executor",
        )
        self._m_misses = reg.counter(
            "index_device_search_misses_total",
            "segment searches that fell back to the host executor",
        )
        self._m_errors = reg.counter(
            "index_device_errors_total",
            "device evaluation faults degraded to host fallback (any "
            "nonzero value deserves a look — results stay correct, the "
            "acceleration is silently off)",
        )
        self._g_bytes = reg.gauge(
            "index_device_bytes", "device bytes held by resident index segments"
        )
        self._g_segments = reg.gauge(
            "index_device_segments", "segments currently device-resident"
        )

    # ---------- surface ----------

    @property
    def enabled(self) -> bool:
        return self.options.enabled and self.options.max_bytes > 0

    def __len__(self) -> int:
        return len(self._od)

    def device_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def admit(self, host_seg, block_start: int | None = None,
              label: str = "") -> DeviceSegment:
        """Wrap ``host_seg`` and (if it fits) build + upload its device
        tier. ALWAYS returns a wrapper — a rejected or disabled segment
        keeps serving through the host surface, with the refusal reason
        on ``status()`` for the routing record."""
        seg = DeviceSegment(host_seg, self, block_start=block_start,
                            label=label)
        if not self.enabled:
            seg._state = "not-admitted:disabled"
            return seg
        staged = self._build_host(host_seg)
        if isinstance(staged, str):
            seg._state = f"not-admitted:{staged}"
            with self._lock:
                self.rejections += 1
                self._m_rejections.inc()
            return seg
        flat, parts = staged
        nbytes = int(flat.nbytes) + int(parts["all_words"].nbytes)
        if nbytes > self.options.max_bytes:
            seg._state = "not-admitted:over-budget"
            with self._lock:
                self.rejections += 1
                self._m_rejections.inc()
            return seg
        with self._lock:
            # reserve budget BEFORE the upload so concurrent admissions
            # can't collectively overshoot; the entry is pending (arrays
            # None) and invisible to the device path until published
            while self._bytes + nbytes > self.options.max_bytes:
                if not self._evict_one_locked():
                    break
            if self._bytes + nbytes > self.options.max_bytes:
                self.rejections += 1
                self._m_rejections.inc()
                seg._state = "not-admitted:over-budget"
                return seg
            self._od[id(seg)] = seg
            seg._reserved = nbytes
            self._bytes += nbytes
            self._publish_locked()
        arrays = self._upload(flat, parts, nbytes)
        with self._lock:
            if id(seg) not in self._od:
                # invalidated/evicted mid-upload: never publish
                return seg
            seg._arrays = arrays
            seg._state = "resident"
            self.admissions += 1
            self._m_admissions.inc()
        return seg

    def touch(self, seg: DeviceSegment) -> None:
        with self._lock:
            if id(seg) in self._od:
                self._od.move_to_end(id(seg))

    def invalidate(self, seg) -> None:
        """Drop a superseded/expired segment's device tier (ns_index
        calls this when persist compaction or retention replaces it)."""
        if not isinstance(seg, DeviceSegment):
            return
        with self._lock:
            if self._drop_locked(seg, "invalidated"):
                self.invalidations += 1
                self._m_invalidations.inc()

    def clear(self) -> int:
        with self._lock:
            n = 0
            for seg in list(self._od.values()):
                if self._drop_locked(seg, "invalidated"):
                    n += 1
            self.invalidations += n
            self._m_invalidations.inc(n)
            return n

    # ---------- accounting (called by DeviceSegment) ----------

    def count_search(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.search_hits += 1
            else:
                self.search_misses += 1
        (self._m_hits if hit else self._m_misses).inc()

    def count_error(self) -> None:
        with self._lock:
            self.errors += 1
        self._m_errors.inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "segments": len(self._od),
                "bytes": self._bytes,
                "max_bytes": self.options.max_bytes,
                "admissions": self.admissions,
                "rejections": self.rejections,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "search_hits": self.search_hits,
                "search_misses": self.search_misses,
                "errors": self.errors,
            }

    # ---------- internals ----------

    def _drop_locked(self, seg: DeviceSegment, state: str) -> bool:
        if self._od.pop(id(seg), None) is None:
            return False
        self._bytes -= getattr(seg, "_reserved", 0)
        seg._arrays = None  # device buffers free with the references
        seg._state = state
        self._publish_locked()
        return True

    def _evict_one_locked(self) -> bool:
        if not self._od:
            return False
        _, seg = next(iter(self._od.items()))
        self._drop_locked(seg, "evicted")
        self.evictions += 1
        self._m_evictions.inc()
        return True

    def _publish_locked(self) -> None:
        self._g_bytes.set(float(self._bytes))
        self._g_segments.set(float(len(self._od)))

    def _build_host(self, host_seg):
        """Host staging: one flat uint32 buffer holding the key matrix,
        lengths, postings index, and postings data (uploaded in one
        transfer), plus the side parts. Returns a rejection reason
        string instead when the segment can't take a device tier."""
        n_docs = len(host_seg)
        if n_docs == 0:
            return "empty"
        terms_all: list[bytes] = []
        idx_rows: list[tuple[int, int]] = []
        chunks: list[np.ndarray] = []
        fields: dict[bytes, tuple[int, int]] = {}
        max_len = 1
        offset = 0
        dot_safe = True  # no term contains \n (see DeviceArrays.dot_safe)
        max_slab = 1
        for name in host_seg.fields():
            start = len(terms_all)
            data_start = offset
            for t, p in _iter_term_postings(host_seg, name):
                t = bytes(t)
                if len(t) > self.options.max_term_bytes:
                    return "term-too-long"
                if b"\n" in t:
                    dot_safe = False
                max_len = max(max_len, len(t))
                terms_all.append(t)
                p = np.asarray(p, np.int32)
                chunks.append(p)
                idx_rows.append((offset, offset + len(p)))
                offset += len(p)
            # per-FIELD postings slice: terms append field by field, so a
            # field's postings are one contiguous [data_start, offset)
            # run of post_data — leaf bitmap builds work over THIS slice
            # (O(field postings)), not the whole buffer
            fields[bytes(name)] = (
                start, len(terms_all) - start, data_start, offset
            )
            max_slab = max(max_slab, kernels.pad_pow2(offset - data_start))
        if not terms_all:
            return "empty"
        k_words = kernels.key_width_words(max_len)
        keys, lens = kernels.build_term_keys(terms_all, k_words)
        post_idx = np.asarray(idx_rows, np.int64).astype(np.uint32)
        post_data = (
            np.concatenate(chunks) if chunks else np.zeros(0, np.int32)
        ).astype(np.uint32)
        flat = np.concatenate([
            keys.ravel(),
            lens.astype(np.uint32),
            post_idx.ravel(),
            post_data,
            # slack so every field's pow2-rounded slab slice stays in
            # bounds (lax.dynamic_slice would silently CLAMP the start
            # otherwise, shifting positions and corrupting the bitmap)
            np.zeros(max_slab, np.uint32),
        ])
        parts = {
            "fields": fields,
            "k_words": k_words,
            "n_terms": len(terms_all),
            "n_docs": n_docs,
            "n_words": -(-n_docs // 32),
            "all_words": kernels.all_docs_words(n_docs),
            "host_keys": keys,
            "host_lens": lens,
            "dot_safe": dot_safe,
        }
        return flat, parts

    def _upload(self, flat: np.ndarray, parts: dict, nbytes: int) -> DeviceArrays:
        """ONE host->device staging transfer, then device-side slice/cast
        into the kernel operand shapes. No lock is held here (M3L001):
        segment uploads are independent — unlike the resident pool there
        is no shared functional buffer chain to serialize."""
        import jax
        import jax.numpy as jnp

        n, k = parts["n_terms"], parts["k_words"]
        dev = jax.device_put(flat)
        aw = jax.device_put(parts["all_words"])
        o = n * k
        term_keys = dev[:o].reshape(n, k)
        term_lens = dev[o : o + n].astype(jnp.int32)
        o += n
        post_idx = dev[o : o + 2 * n].astype(jnp.int32).reshape(n, 2)
        o += 2 * n
        post_data = dev[o:].astype(jnp.int32)
        return DeviceArrays(
            term_keys=term_keys,
            term_lens=term_lens,
            post_idx=post_idx,
            post_data=post_data,
            all_words=aw,
            fields=parts["fields"],
            k_words=k,
            n_docs=parts["n_docs"],
            n_words=parts["n_words"],
            nbytes=nbytes,
            host_keys=parts["host_keys"],
            host_lens=parts["host_lens"],
            dot_safe=parts["dot_safe"],
        )


def _iter_term_postings(seg, name: bytes):
    if hasattr(seg, "iter_term_postings"):
        yield from seg.iter_term_postings(name)
    else:
        for t in seg.terms(name):
            yield t, seg.postings(name, t)
