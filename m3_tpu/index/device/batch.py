"""Cross-segment batched leaf match: ONE binary-search launch per query.

The per-segment device executor (segment.py) already batches every
exact-match leaf of a query AST into one ``match_terms`` launch — but a
namespace holding several device-resident segments (multiple index
blocks in range, or mutable/sealed generations) paid one launch PER
SEGMENT, and each launch is a host round trip (PROFILE.md's dispatch
floor). Here ALL of a query's exact leaves resolve over ALL
device-resident segments in one launch:

- the segments' fixed-width term-key matrices concatenate into one
  matrix, each padded to the widest segment's key width (trailing zero
  words preserve the (words, length) order within a segment, and every
  search row's [lo, hi) bounds stay inside one segment's field range —
  per-row bounds are exactly what ``match_terms`` was built for);
- query rows are laid out (segment-major) × (leaf), with per-row bounds
  offset by the segment's base; a value wider than ITS segment's key
  width is marked unmatchable for that segment only;
- results map back per segment by subtracting the base.

The concatenated matrix is cached per segment-identity tuple (a tiny
bounded map holding WEAK references to its sources — identity changes
on admission/eviction invalidate entries without pinning evicted
tiers). The concatenated copy itself is device memory OUTSIDE the index
store's byte budget, bounded by the cache cap × the term dictionaries
of one segment set — the deliberate price of the one-launch resolve.
The batcher is best-effort: any failure returns None and segments fall
back to their private single-launch match, so correctness never
depends on it.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ...utils.instrument import DEFAULT as METRICS
from . import kernels
from .segment import collect_leaves

_M_BATCHED = METRICS.counter(
    "index_batched_match_total",
    "cross-segment batched leaf-match launches (one per query touching "
    ">1 device-resident segment; replaces one launch per segment)",
)
_M_ERRORS = METRICS.counter(
    "index_batched_match_errors_total",
    "batched leaf matches that failed and fell back to per-segment "
    "launches (best-effort: never affects results)",
)

_CACHE_CAP = 4
_combined_cache: "OrderedDict[tuple, tuple]" = OrderedDict()


def _combined(arrays_list):
    """Concatenated (keys, lens, bases, k_max) for a segment-arrays
    tuple, cached by identity. Identity is held via WEAK references: a
    plain id()-keyed entry whose sources were garbage-collected could
    alias a recycled address onto different segments and serve a stale
    term matrix, while strong references would pin evicted index tiers
    (device bytes the store's budget thinks are free). A dead or
    mismatched weakref simply rebuilds the bundle."""
    import weakref

    key = tuple(id(a) for a in arrays_list)
    hit = _combined_cache.get(key)
    if hit is not None and all(
        ref() is a for ref, a in zip(hit[4], arrays_list)
    ):
        _combined_cache.move_to_end(key)
        return hit[:4]
    import jax.numpy as jnp

    k_max = max(a.k_words for a in arrays_list)
    mats = []
    lens = []
    bases = [0]
    for a in arrays_list:
        tk = a.term_keys
        if a.k_words < k_max:
            tk = jnp.pad(tk, ((0, 0), (0, k_max - a.k_words)))
        mats.append(tk)
        lens.append(a.term_lens)
        bases.append(bases[-1] + a.n_terms)
    out = (
        jnp.concatenate(mats, axis=0),
        jnp.concatenate(lens, axis=0),
        np.asarray(bases, np.int64),
        k_max,
    )
    _combined_cache[key] = out + (
        tuple(weakref.ref(a) for a in arrays_list),
    )
    while len(_combined_cache) > _CACHE_CAP:
        _combined_cache.popitem(last=False)
    return out


def prematch(device_segs, query) -> dict | None:
    """Resolve every exact-match leaf of ``query`` over every segment in
    ``device_segs`` with ONE ``match_terms`` launch.

    Returns ``{id(seg): (arrays, gis_map, classes)}`` suitable for
    ``DeviceSegment.search_ast(query, prematched=...)`` — each entry
    pinned to the arrays snapshot it was computed against — or None when
    batching is not applicable (a segment's tier mid-eviction, no exact
    leaves) or anything fails (callers fall back to per-segment
    matches)."""
    try:
        snaps = []
        for seg in device_segs:
            arrays = getattr(seg, "_arrays", None)
            if arrays is None:
                return None
            snaps.append((seg, arrays))
        leaves, order, classes = collect_leaves(query)
        if not leaves:
            # nothing to batch; hand every segment its (empty) result so
            # per-segment searches skip their own empty launch too
            return {
                id(seg): (arrays, {}, dict(classes))
                for seg, arrays in snaps
            }
        import jax.numpy as jnp

        keys, lens, bases, k_max = _combined([a for _, a in snaps])
        n_segs = len(snaps)
        b = len(leaves)
        rows = n_segs * b
        rows_pad = kernels.pad_pow2(rows)
        q_rows: list[bytes] = []
        lo = np.zeros(rows_pad, np.int32)
        hi = np.zeros(rows_pad, np.int32)
        over = []  # (row, value wider than its segment's key width)
        for s, (_seg, a) in enumerate(snaps):
            width = 4 * a.k_words
            base = int(bases[s])
            for i, (field, value) in enumerate(leaves):
                row = s * b + i
                q_rows.append(value)
                start, count = a.fields.get(field, (0, 0, 0, 0))[:2]
                lo[row], hi[row] = base + start, base + start + count
                if len(value) > width:
                    over.append(row)
        q_rows += [b""] * (rows_pad - rows)
        q_keys, q_lens = kernels.build_query_keys(q_rows, k_max)
        for row in over:
            # wider than THIS segment's keys: unmatchable there even
            # though the padded width could hold the bytes
            q_lens[row] = -1
        gis = np.asarray(
            kernels.match_terms(
                keys, lens, jnp.asarray(lo), jnp.asarray(hi),
                jnp.asarray(q_keys), jnp.asarray(q_lens),
            )
        )
        _M_BATCHED.inc()
        out: dict = {}
        for s, (seg, a) in enumerate(snaps):
            base = int(bases[s])
            seg_gis = gis[s * b : s * b + b].copy()
            hitmask = seg_gis >= 0
            seg_gis[hitmask] -= base
            gis_map = {}
            for leaf, start, n in order:
                gis_map[id(leaf)] = seg_gis[start : start + n]
            out[id(seg)] = (a, gis_map, dict(classes))
        return out
    except Exception:
        _M_ERRORS.inc()
        return None
