"""Batched device kernels for the HBM-resident inverted index.

The reference resolves a query by walking FSTs and merging roaring
bitmaps term by term (m3ninx segment/fst + postings.List); here the
same work is three array kernels over a sealed segment's device tier
(segment.py builds the arrays, store.py owns their budget):

- ``match_terms`` — one lower-bound binary search over the sorted
  fixed-width term-key matrix for B query terms AT ONCE (the batched
  FST lookup): per-row [lo, hi) bounds let one launch mix fields.
- ``bitmap_from_terms`` / ``bitmap_from_term_range`` — union the
  postings of the selected terms into a packed doc bitmap
  (uint32[n_docs/32]) via a difference-array + cumsum mask over the
  flat postings data (O(postings + terms), no ragged gathers).
- bitwise AND/OR/ANDNOT over those words (plain jnp ops in
  segment.py) replace the host executor's sorted-array set algebra.

Term ordering contract: a term is keyed as its bytes zero-padded to a
fixed width and viewed as BIG-endian uint32 words, with the byte
LENGTH as the tiebreak. (words, length) compares exactly like raw
bytes: padding only collides when one term is a NUL-extension of the
other, and the length tiebreak resolves precisely that case the way
bytes ordering does (shorter first). The host-side mirror of this
compare (term key building + lower bound, used for literal-prefix
narrowing) lives here too so both sides share one definition.

jit compilation is keyed on array shapes: per segment the term/postings
shapes are fixed, and per query the batch axis pads to a power of two
(``pad_pow2``), so each segment costs a handful of compiles total.

All jax imports are deferred (module import stays light; lint and
jax-less tools can import the package).
"""

from __future__ import annotations

import numpy as np

from ...utils.instrument import KernelProfiler

# dispatch observability for the eager index kernels: compile attribution
# plus the per-query device-dispatch count (query/stats.py seam) — the
# staged index path pays one profiled launch per kernel here, while the
# fused query plan (query/plan.py) inlines the traced bodies into its
# single program
PROFILER = KernelProfiler("index_device")

# ---------- host-side key building / compare (shared definition) ----------


def key_width_words(max_term_len: int) -> int:
    """uint32 words per term key covering ``max_term_len`` bytes."""
    return max(-(-int(max_term_len) // 4), 1)


def build_term_keys(terms: list, k_words: int):
    """(uint32[n, k_words] big-endian-packed keys, int32[n] lengths) for a
    list of term byte strings, each at most ``4 * k_words`` bytes."""
    n = len(terms)
    width = 4 * k_words
    buf = bytearray(n * width)
    lens = np.zeros(n, np.int32)
    for i, t in enumerate(terms):
        buf[i * width : i * width + len(t)] = t
        lens[i] = len(t)
    keys = np.frombuffer(bytes(buf), ">u4").reshape(n, k_words).astype(np.uint32)
    return keys, lens


def build_query_keys(values: list, k_words: int):
    """Key rows for query-side values. Values LONGER than the segment's
    key width cannot exist in its dictionary: their row is zeroed and the
    caller marks it unmatchable (lo == hi) instead of truncating — a
    truncated compare could false-match."""
    width = 4 * k_words
    clipped = [v if len(v) <= width else b"" for v in values]
    keys, lens = build_term_keys(clipped, k_words)
    for i, v in enumerate(values):
        if len(v) > width:
            lens[i] = -1  # sentinel: caller zeroes the search range
    return keys, lens


def host_key_lt(a_key, a_len: int, b_key, b_len: int) -> bool:
    """The (words, length) compare, host side — must order exactly like
    ``bytes(a) < bytes(b)`` (property-tested)."""
    neq = a_key != b_key
    if neq.any():
        i = int(np.argmax(neq))
        return int(a_key[i]) < int(b_key[i])
    return a_len < b_len


def host_lower_bound(keys, lens, lo: int, hi: int, q_key, q_len: int) -> int:
    """First index in [lo, hi) whose term is >= the query key — the
    host mirror of the device search, used for literal-prefix range
    narrowing (log n iterations of an O(K) compare)."""
    while lo < hi:
        mid = (lo + hi) // 2
        if host_key_lt(keys[mid], int(lens[mid]), q_key, q_len):
            lo = mid + 1
        else:
            hi = mid
    return lo


def pad_pow2(n: int, lo: int = 1) -> int:
    return max(lo, 1 << max(int(n) - 1, 0).bit_length())


def bitmap_to_docids(words: np.ndarray) -> np.ndarray:
    """Packed uint32 doc bitmap -> ascending int32 doc ids (host side).
    Bit j of word w is doc ``32*w + j``; on a little-endian host the
    byte view + little bit order reads exactly that sequence."""
    # m3lint: disable=M3L010 -- input bitmap is already host-side (Planner._execute reads back once before calling this); host unpackbits is the point of this helper
    words = np.ascontiguousarray(np.asarray(words, np.uint32))
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.flatnonzero(bits).astype(np.int32)


# ---------- jitted device kernels (built lazily, cached by shape) ----------

_JITS: dict = {}


def _get_jit(name: str, builder):
    fn = _JITS.get(name)
    if fn is None:
        fn = _JITS[name] = builder()
    return fn


def match_terms_traced(keys, lens, lo, hi, q_keys, q_lens):
    """Traced batched-term-lookup body (shared by the eager
    :func:`match_terms` wrapper and the fused query-plan program,
    query/plan.py, which inlines it into ONE jit)."""
    import jax.numpy as jnp

    n = keys.shape[0]
    n_iter = max(int(n).bit_length(), 1)
    lo_v = jnp.where(q_lens < 0, 0, lo).astype(jnp.int32)
    hi_v = jnp.where(q_lens < 0, 0, hi).astype(jnp.int32)
    hi_orig = hi_v

    def _lt(ak, al, bk, bl):
        neq = ak != bk
        any_neq = jnp.any(neq, axis=1)
        idx = jnp.argmax(neq, axis=1)
        aw = jnp.take_along_axis(ak, idx[:, None], axis=1)[:, 0]
        bw = jnp.take_along_axis(bk, idx[:, None], axis=1)[:, 0]
        return jnp.where(any_neq, aw < bw, al < bl)

    for _ in range(n_iter):
        active = lo_v < hi_v
        mid = (lo_v + hi_v) // 2
        midc = jnp.clip(mid, 0, n - 1)
        go_right = _lt(keys[midc], lens[midc], q_keys, q_lens)
        lo_v = jnp.where(active & go_right, mid + 1, lo_v)
        hi_v = jnp.where(active & ~go_right, mid, hi_v)
    pos = jnp.clip(lo_v, 0, n - 1)
    eq = jnp.all(keys[pos] == q_keys, axis=1) & (lens[pos] == q_lens)
    found = (lo_v < hi_orig) & eq
    return jnp.where(found, lo_v, -1).astype(jnp.int32)


def match_terms(keys, lens, lo, hi, q_keys, q_lens):
    """Batched term lookup: for each query row b, the GLOBAL term index
    of q_keys[b] within the sorted range [lo[b], hi[b]), or -1.

    ``keys``/``lens`` are the segment's device key matrix; ``lo``/``hi``
    int32[B] per-row bounds (a conjunction mixing fields resolves in ONE
    launch); q_lens < 0 marks an unmatchable row (over-width value)."""
    import jax

    def build():
        return jax.jit(match_terms_traced)

    fn = _get_jit("match", build)
    with PROFILER.dispatch(("match", tuple(q_keys.shape))) as d:
        return d.done(fn(keys, lens, lo, hi, q_keys, q_lens))


def bitmap_from_terms(post_idx, post_data, gis, n_words: int,
                      data_start=0, slab: int | None = None):
    """OR of the postings lists of the selected global term indices
    (``gis`` int32[B], -1 entries skipped) as a packed uint32[n_words]
    doc bitmap. Duplicate gis are harmless (difference-array counts).

    ``data_start``/``slab``: the FIELD's contiguous postings slice (every
    leaf matches within one field) — the difference-array/cumsum then
    runs over O(field postings), not O(total postings). ``slab`` is
    pow2-rounded by the caller so jit signatures stay bounded; None
    falls back to the whole-buffer build."""
    import jax

    def build():
        return jax.jit(bitmap_from_terms_traced, static_argnums=(4, 5))

    if post_idx.shape[0] == 0:
        return zero_bitmap(n_words)
    if slab is None:
        data_start, slab = 0, int(post_data.shape[0])
    import jax.numpy as jnp

    fn = _get_jit("bm_terms", build)
    with PROFILER.dispatch(("bm_terms", tuple(gis.shape), n_words, slab)) as d:
        return d.done(
            fn(post_idx, post_data, gis, jnp.int32(data_start), n_words, slab)
        )


def bitmap_from_term_range(post_idx, post_data, lo, hi, n_words: int,
                           data_start=0, slab: int | None = None):
    """OR of the postings of every term in the global range [lo, hi) —
    the whole-field and prefix-matches-everything cases, without
    shipping an index vector per query. ``data_start``/``slab`` as in
    bitmap_from_terms (ranges never cross a field boundary)."""
    import jax

    def build():
        return jax.jit(bitmap_from_term_range_traced, static_argnums=(5, 6))

    if post_idx.shape[0] == 0:
        return zero_bitmap(n_words)
    if slab is None:
        data_start, slab = 0, int(post_data.shape[0])
    import jax.numpy as jnp

    fn = _get_jit("bm_range", build)
    with PROFILER.dispatch(("bm_range", n_words, slab)) as d:
        return d.done(
            fn(post_idx, post_data, lo, hi, jnp.int32(data_start), n_words, slab)
        )


def bitmap_from_terms_traced(post_idx, post_data, gis, data_start,
                             n_words: int, slab: int):
    """Traced body of :func:`bitmap_from_terms` (also inlined by the
    fused query-plan program)."""
    import jax.numpy as jnp

    valid = (gis >= 0).astype(jnp.int32)
    gic = jnp.clip(gis, 0, max(post_idx.shape[0] - 1, 0))
    starts = jnp.where(valid > 0, post_idx[gic, 0], 0)
    ends = jnp.where(valid > 0, post_idx[gic, 1], 0)
    return _mask_to_bitmap(
        post_data, starts, ends, valid, n_words, data_start, slab
    )


def bitmap_from_term_range_traced(post_idx, post_data, lo, hi, data_start,
                                  n_words: int, slab: int):
    """Traced body of :func:`bitmap_from_term_range` (also inlined by
    the fused query-plan program)."""
    import jax.numpy as jnp

    n = post_idx.shape[0]
    sel = (jnp.arange(n, dtype=jnp.int32) >= lo) & (
        jnp.arange(n, dtype=jnp.int32) < hi
    )
    valid = sel.astype(jnp.int32)
    starts = jnp.where(sel, post_idx[:, 0], 0)
    ends = jnp.where(sel, post_idx[:, 1], 0)
    return _mask_to_bitmap(
        post_data, starts, ends, valid, n_words, data_start, slab
    )


def _mask_to_bitmap(post_data, starts, ends, valid, n_words: int,
                    data_start, slab: int):
    """Difference array over the field's postings slice -> covered-
    position mask -> packed doc bitmap (traced helper shared by both
    builders). ``starts``/``ends`` are GLOBAL flat offsets; the slice
    [data_start, data_start + slab) is pulled with a static-size
    dynamic_slice (the store pads post_data so it never clamps) and the
    offsets rebase into it — invalid rows rebase to empty [0, 0)."""
    import jax
    import jax.numpy as jnp

    sl = jax.lax.dynamic_slice(post_data, (data_start,), (slab,))
    starts = jnp.clip(starts - data_start, 0, slab)
    ends = jnp.clip(ends - data_start, 0, slab)
    delta = jnp.zeros(slab + 1, jnp.int32)
    delta = delta.at[starts].add(valid)
    delta = delta.at[ends].add(-valid)
    covered = jnp.cumsum(delta)[:slab] > 0
    n_pad = n_words * 32
    # uncovered positions scatter into a discard slot past the bitmap
    docs = jnp.where(covered, sl, n_pad)
    present = jnp.zeros(n_pad + 1, jnp.uint32).at[docs].set(1)[:n_pad]
    shifted = present.reshape(n_words, 32) << jnp.arange(32, dtype=jnp.uint32)
    # each column holds a distinct bit, so the sum IS the bitwise OR
    return shifted.sum(axis=1, dtype=jnp.uint32)


def zero_bitmap(n_words: int):
    import jax.numpy as jnp

    return jnp.zeros(n_words, jnp.uint32)


def all_docs_words(n_docs: int) -> np.ndarray:
    """Host-built all-docs bitmap with the tail bits past n_docs zeroed
    (uploaded once per segment; negation ANDs against it so phantom
    tail docs can never appear)."""
    n_words = -(-n_docs // 32)
    bits = np.zeros(n_words * 32, np.uint8)
    bits[:n_docs] = 1
    return np.packbits(bits, bitorder="little").view(np.uint32).copy()
