"""Index query AST + executor.

Reference: /root/reference/src/m3ninx/ — idx.Query builders (idx/), searchers
(search/searcher/: term, regexp, conjunction, disjunction, negation, all,
empty, field) and executor (search/executor/) iterating matches across
segments.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .segment import Document


@dataclass(frozen=True)
class Query:
    pass


@dataclass(frozen=True)
class TermQuery(Query):
    field: bytes
    value: bytes


@dataclass(frozen=True)
class RegexpQuery(Query):
    field: bytes
    pattern: bytes


@dataclass(frozen=True)
class FieldQuery(Query):
    """Matches docs that have the field at all (searcher/field.go)."""

    field: bytes


@dataclass(frozen=True)
class AllQuery(Query):
    pass


@dataclass(frozen=True)
class ConjunctionQuery(Query):
    queries: tuple[Query, ...]


@dataclass(frozen=True)
class DisjunctionQuery(Query):
    queries: tuple[Query, ...]


@dataclass(frozen=True)
class NegationQuery(Query):
    query: Query


def term(field: bytes, value: bytes) -> TermQuery:
    return TermQuery(field, value)


def regexp(field: bytes, pattern: bytes) -> RegexpQuery:
    return RegexpQuery(field, pattern)


def conj(*qs: Query) -> ConjunctionQuery:
    return ConjunctionQuery(tuple(qs))


def disj(*qs: Query) -> DisjunctionQuery:
    return DisjunctionQuery(tuple(qs))


def neg(q: Query) -> NegationQuery:
    return NegationQuery(q)


def search_segment(seg, query: Query, cache=None, prematched=None) -> np.ndarray:
    """Postings for one segment (search/searcher dispatch); sorted unique.

    A device-resident segment (index/device/segment.py DeviceSegment)
    evaluates the WHOLE AST on device — bitmap algebra instead of the
    sorted merges below — with bit-identical results; when its tier is
    evicted / was never admitted, it answers None and the segment falls
    through to this host path transparently (the wrapper implements the
    full sealed surface by delegation).

    ``cache`` is a PostingsListCache: regexp/field scans over IMMUTABLE
    segments are O(total terms) to compute, so repeated queries serve from
    the LRU (postings_list_cache.go:59). The device path skips it — a
    bitmap recompute is cheaper than uploading a cached array back."""
    if hasattr(seg, "search_ast"):
        # ``prematched``: this segment's slice of the cross-segment
        # batched leaf match (index/device/batch.py) — all exact leaves
        # of the query resolved in ONE launch across segments
        out = seg.search_ast(query, prematched=prematched)
        if out is not None:
            return out
        seg = seg.host  # transparent host fallback
    if isinstance(query, TermQuery):
        return np.asarray(seg.postings(query.field, query.value), np.int32)
    if isinstance(query, RegexpQuery):
        hit, key = _cache_lookup(cache, seg, ("re", query.field, query.pattern))
        if hit is not None:
            return hit
        if hasattr(seg, "postings_regexp"):
            out = seg.postings_regexp(query.field, query.pattern)
        else:
            import re

            rx = re.compile(b"^(?:" + query.pattern + b")$")
            if hasattr(seg, "postings_for_terms"):
                out = seg.postings_for_terms(query.field, rx.match)
            else:
                found = [
                    np.asarray(seg.postings(query.field, t), np.int32)
                    for t in seg.terms(query.field)
                    if rx.match(t)
                ]
                out = (
                    np.unique(np.concatenate(found))
                    if found
                    else np.zeros(0, np.int32)
                )
        if key is not None:
            cache.put(key, out)
        return out
    if isinstance(query, FieldQuery):
        hit, key = _cache_lookup(cache, seg, ("field", query.field))
        if hit is not None:
            return hit
        if hasattr(seg, "postings_for_terms"):
            out = seg.postings_for_terms(query.field, lambda t: True)
        else:
            found = [
                np.asarray(seg.postings(query.field, t), np.int32)
                for t in seg.terms(query.field)
            ]
            out = (
                np.unique(np.concatenate(found)) if found else np.zeros(0, np.int32)
            )
        if key is not None:
            cache.put(key, out)
        return out
    if isinstance(query, AllQuery):
        return np.arange(len(seg), dtype=np.int32)
    if isinstance(query, ConjunctionQuery):
        if not query.queries:
            return np.zeros(0, np.int32)
        # negations subtract from the positive intersection (idx/query.go)
        pos = [q for q in query.queries if not isinstance(q, NegationQuery)]
        negs = [q for q in query.queries if isinstance(q, NegationQuery)]
        if pos:
            acc = search_segment(seg, pos[0], cache)
            for q in pos[1:]:
                acc = np.intersect1d(
                    acc, search_segment(seg, q, cache), assume_unique=False
                )
        else:
            acc = np.arange(len(seg), dtype=np.int32)
        for q in negs:
            acc = np.setdiff1d(
                acc, search_segment(seg, q.query, cache), assume_unique=False
            )
        return acc.astype(np.int32)
    if isinstance(query, DisjunctionQuery):
        out = [search_segment(seg, q, cache) for q in query.queries]
        out = [o for o in out if len(o)]
        return np.unique(np.concatenate(out)).astype(np.int32) if out else np.zeros(0, np.int32)
    if isinstance(query, NegationQuery):
        return np.setdiff1d(
            np.arange(len(seg), dtype=np.int32), search_segment(seg, query.query, cache)
        ).astype(np.int32)
    raise TypeError(f"unknown query {query!r}")


def _cache_lookup(cache, seg, subkey):
    """(cached postings | None, cache key | None)."""
    if cache is None:
        return None, None
    from .postings_cache import segment_cache_key

    sk = segment_cache_key(seg)
    if sk is None:
        return None, None
    key = (sk,) + subkey
    return cache.get(key), key


class MatchedDocs:
    """Lazy matched-document sequence (search/executor iterator role).

    Postings are computed eagerly (cheap, postings-cache-served); Document
    objects materialize only on access — so `len(result.docs)`, id-only
    consumers (series select), and partial iteration never pay the per-doc
    tag decode that dominated large regexp fan-outs (2.5s at 500k docs).
    Cross-segment id-dedupe extracts only ids, via the segment's batch
    ``doc_ids`` fast path when it has one; the common single-segment case
    (ids unique within a segment by construction) skips dedupe entirely."""

    def __init__(self, parts, limit: int | None = None) -> None:
        """``parts`` is an ITERABLE of (segment, postings): it is consumed
        lazily so a satisfied ``limit`` stops searching later segments
        entirely (the executor's early exit). Id-dedupe engages only once a
        SECOND non-empty segment appears — the common single-segment case
        never extracts ids at all."""
        self._parts: list = []
        seen: set[bytes] | None = None
        total = 0
        for seg, post in parts:
            if limit is not None and total >= limit:
                break
            if not len(post):
                continue
            if self._parts and seen is None:
                # second live part: seed the dedupe set from earlier parts
                seen = set()
                for s0, p0 in self._parts:
                    seen.update(self._ids_of(s0, p0))
            if seen is None:
                if limit is not None and total + len(post) > limit:
                    post = post[: limit - total]
                self._parts.append((seg, post))
                total += len(post)
            else:
                ids = self._ids_of(seg, post)
                keep = []
                for j, did in enumerate(ids):
                    if did in seen:
                        continue
                    seen.add(did)
                    keep.append(j)
                    total += 1
                    if limit is not None and total >= limit:
                        break
                self._parts.append(
                    (seg, post[np.asarray(keep, np.int64)] if keep else post[:0])
                )
        self._len = total
        self._offsets = np.cumsum([0] + [len(p) for _, p in self._parts])

    @staticmethod
    def _ids_of(seg, post):
        if hasattr(seg, "doc_ids"):
            return seg.doc_ids(post)
        docs = seg.docs
        return [docs[int(i)].id for i in post]

    def ids(self) -> list[bytes]:
        """All matched doc ids without tag materialization."""
        out: list[bytes] = []
        for seg, post in self._parts:
            out.extend(self._ids_of(seg, post))
        return out

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._len))]
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        k = int(np.searchsorted(self._offsets, i, side="right")) - 1
        seg, post = self._parts[k]
        return seg.docs[int(post[i - int(self._offsets[k])])]

    def __iter__(self):
        for seg, post in self._parts:
            docs = seg.docs
            for i in post:
                yield docs[int(i)]


def execute(segments, query: Query, limit: int | None = None, cache=None,
            prematched=None) -> MatchedDocs:
    """search/executor: matched docs across segments as a LAZY sequence
    (docs dedupe by id — later segments don't re-emit ids already seen).
    Segments are searched lazily: once ``limit`` is reached, remaining
    segments are never scanned. ``prematched`` maps id(segment) to its
    slice of a cross-segment batched leaf match (device/batch.py)."""
    pm = prematched or {}
    return MatchedDocs(
        (
            (seg, search_segment(seg, query, cache, prematched=pm.get(id(seg))))
            for seg in segments
        ),
        limit=limit,
    )
