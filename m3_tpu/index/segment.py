"""Inverted index segments: mutable ingest segment + sealed immutable segment.

Reference: /root/reference/src/m3ninx/ — doc model (doc/), mutable segment
(index/segment/mem: concurrent postings map field→term→roaring bitmap),
immutable FST segment (index/segment/fst: fields FST → terms FST → postings
bitsets, mmap'd), segment builder (index/segment/builder merges segments).

TPU-native stance: postings are sorted int32 numpy arrays (the role roaring
bitmaps play), term dictionaries are sorted arrays searched by np.searchsorted
(the role the FST plays), and set algebra is vectorized numpy — all host-side,
feeding series batches to the device scan.
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass, field

import numpy as np

from ..block.core import Tags


@dataclass(frozen=True)
class Document:
    """doc.Document{ID, Fields} (m3ninx/doc/document.go)."""

    id: bytes
    fields: Tags


def _top_level_alternation(pattern: bytes) -> bool:
    """True if the pattern has an unparenthesized '|' — then NO prefix is
    common to all alternatives and pruning is unsafe."""
    depth = 0
    in_class = False
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == 0x5C:  # backslash: skip escaped char
            i += 2
            continue
        if in_class:
            if c == 0x5D:  # ]
                in_class = False
        elif c == 0x5B:  # [
            in_class = True
        elif c == 0x28:  # (
            depth += 1
        elif c == 0x29:  # )
            depth -= 1
        elif c == 0x7C and depth == 0:  # |
            return True
        i += 1
    return False


# regexp metacharacters — ONE definition shared by the host prefix prune
# below and the device executor's pattern classification
# (index/device/segment.py): if the sets diverged, the device literal/
# prefix classes would silently disagree with the host prune the
# bit-identity contract depends on
REGEXP_SPECIALS = b".^$*+?{}[]|()\\"


def literal_prefix(pattern: bytes) -> bytes:
    """Longest literal prefix of a regexp — the prune the reference gets
    from intersecting the compiled automaton with the term FST
    (segment/fst/regexp/regexp.go): only terms in [prefix, next(prefix))
    can match, so the scan touches a fraction of the dictionary."""
    if pattern.startswith(b"^"):
        pattern = pattern[1:]
    if _top_level_alternation(pattern):
        return b""
    out = bytearray()
    i = 0
    while i < len(pattern):
        c = pattern[i : i + 1]
        if c in REGEXP_SPECIALS:
            break
        out += c
        i += 1
    # a quantifier after the last literal makes that char optional
    if i < len(pattern) and pattern[i : i + 1] in b"*+?{" and out:
        out = out[:-1]
    return bytes(out)


def prefix_upper(pre: bytes) -> bytes | None:
    """Smallest byte string greater than every string with prefix ``pre``
    (None if unbounded)."""
    b = bytearray(pre)
    while b:
        if b[-1] < 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return None


class MutableSegment:
    """segment/mem: built live on ingest."""

    def __init__(self) -> None:
        self.docs: list[Document] = []
        self._ids: dict[bytes, int] = {}
        self._postings: dict[tuple[bytes, bytes], list[int]] = {}
        self._fields: dict[bytes, set[bytes]] = {}

    def __len__(self) -> int:
        return len(self.docs)

    def insert(self, doc: Document) -> int:
        existing = self._ids.get(doc.id)
        if existing is not None:
            return existing
        idx = len(self.docs)
        self.docs.append(doc)
        self._ids[doc.id] = idx
        for name, value in doc.fields:
            self._postings.setdefault((name, value), []).append(idx)
            self._fields.setdefault(name, set()).add(value)
        return idx

    def postings(self, name: bytes, value: bytes) -> np.ndarray:
        return np.asarray(self._postings.get((name, value), []), np.int32)

    def terms(self, name: bytes) -> list[bytes]:
        return sorted(self._fields.get(name, ()))

    def fields(self) -> list[bytes]:
        return sorted(self._fields)

    def seal(self) -> "SealedSegment":
        return SealedSegment.from_mutable(self)


class SealedSegment:
    """Immutable segment: sorted term dict per field + packed postings —
    the fst segment's role (segment/fst/segment.go) in array form."""

    def __init__(self, docs, field_terms, postings_index, postings_data) -> None:
        self.docs: list[Document] = docs
        # field -> (sorted term list, [start, end) into postings_data per term)
        self._field_terms: dict[bytes, list[bytes]] = field_terms
        self._postings_index: dict[bytes, np.ndarray] = postings_index  # [n_terms, 2]
        self._postings_data: np.ndarray = postings_data  # int32 concatenated
        # per-field object arrays for searchsorted, built once — rebuilding
        # them per postings() call made persist O(n_terms^2)
        self._term_arrs: dict[bytes, np.ndarray] = {}

    def __len__(self) -> int:
        return len(self.docs)

    def _term_arr(self, name: bytes) -> np.ndarray | None:
        arr = self._term_arrs.get(name)
        if arr is None:
            terms = self._field_terms.get(name)
            if not terms:
                return None
            arr = np.asarray(terms, object)
            self._term_arrs[name] = arr
        return arr

    @staticmethod
    def from_mutable(seg: MutableSegment) -> "SealedSegment":
        field_terms: dict[bytes, list[bytes]] = {}
        postings_index: dict[bytes, np.ndarray] = {}
        chunks: list[np.ndarray] = []
        offset = 0
        for name in seg.fields():
            terms = seg.terms(name)
            field_terms[name] = terms
            idx = np.zeros((len(terms), 2), np.int64)
            for i, t in enumerate(terms):
                p = seg.postings(name, t)
                chunks.append(p)
                idx[i] = (offset, offset + len(p))
                offset += len(p)
            postings_index[name] = idx
        data = np.concatenate(chunks) if chunks else np.zeros(0, np.int32)
        return SealedSegment(list(seg.docs), field_terms, postings_index, data)

    def fields(self) -> list[bytes]:
        return sorted(self._field_terms)

    def terms(self, name: bytes) -> list[bytes]:
        return self._field_terms.get(name, [])

    def postings(self, name: bytes, value: bytes) -> np.ndarray:
        arr = self._term_arr(name)
        if arr is None:
            return np.zeros(0, np.int32)
        terms = self._field_terms[name]
        i = np.searchsorted(arr, value)
        if i >= len(terms) or terms[i] != value:
            return np.zeros(0, np.int32)
        s, e = self._postings_index[name][i]
        return self._postings_data[s:e]

    def iter_term_postings(self, name: bytes):
        """(term, postings) pairs in sorted term order — the segment
        writer's walk, without a per-term search."""
        idx = self._postings_index.get(name)
        for i, t in enumerate(self._field_terms.get(name, [])):
            s, e = idx[i]
            yield t, self._postings_data[s:e]

    def postings_regexp(self, name: bytes, pattern: bytes) -> np.ndarray:
        """segment/fst/regexp: regex → automaton intersected with the term
        dict; here literal-prefix pruning narrows the sorted dict to the
        only range that can match, then a compiled re filters it."""
        arr = self._term_arr(name)
        if arr is None:
            return np.zeros(0, np.int32)
        terms = self._field_terms[name]
        lo, hi = 0, len(terms)
        pre = literal_prefix(pattern)
        if pre:
            lo = int(np.searchsorted(arr, pre))
            up = prefix_upper(pre)
            hi = int(np.searchsorted(arr, up)) if up is not None else len(terms)
        rx = re.compile(b"^(?:" + pattern + b")$")
        out = []
        idx = self._postings_index[name]
        for i in range(lo, hi):
            if rx.match(terms[i]):
                s, e = idx[i]
                out.append(self._postings_data[s:e])
        if not out:
            return np.zeros(0, np.int32)
        return np.unique(np.concatenate(out))

    # --- persistence (m3ninx/persist segment file sets) ---

    def serialize(self) -> bytes:
        from ..utils.serialize import encode_tags

        parts = [struct.pack("<I", len(self.docs))]
        for d in self.docs:
            enc_fields = encode_tags(d.fields)
            parts.append(struct.pack("<II", len(d.id), len(enc_fields)))
            parts.append(d.id)
            parts.append(enc_fields)
        parts.append(struct.pack("<I", len(self._field_terms)))
        for name in self.fields():
            terms = self._field_terms[name]
            idx = self._postings_index[name]
            parts.append(struct.pack("<II", len(name), len(terms)))
            parts.append(name)
            for i, t in enumerate(terms):
                parts.append(struct.pack("<IQQ", len(t), idx[i][0], idx[i][1]))
                parts.append(t)
        raw = self._postings_data.astype("<i4").tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
        return b"".join(parts)

    @staticmethod
    def deserialize(buf: bytes) -> "SealedSegment":
        from ..utils.serialize import decode_tags

        pos = 0
        (n_docs,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        docs = []
        for _ in range(n_docs):
            id_len, f_len = struct.unpack_from("<II", buf, pos)
            pos += 8
            did = buf[pos : pos + id_len]
            pos += id_len
            enc = buf[pos : pos + f_len]
            pos += f_len
            docs.append(Document(did, decode_tags(enc)))
        (n_fields,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        field_terms: dict[bytes, list[bytes]] = {}
        postings_index: dict[bytes, np.ndarray] = {}
        for _ in range(n_fields):
            name_len, n_terms = struct.unpack_from("<II", buf, pos)
            pos += 8
            name = buf[pos : pos + name_len]
            pos += name_len
            terms = []
            idx = np.zeros((n_terms, 2), np.int64)
            for i in range(n_terms):
                t_len, s, e = struct.unpack_from("<IQQ", buf, pos)
                pos += 20
                terms.append(buf[pos : pos + t_len])
                pos += t_len
                idx[i] = (s, e)
            field_terms[name] = terms
            postings_index[name] = idx
        (raw_len,) = struct.unpack_from("<Q", buf, pos)
        pos += 8
        data = np.frombuffer(buf, "<i4", count=raw_len // 4, offset=pos).copy()
        return SealedSegment(docs, field_terms, postings_index, data)


def merge_segments(segments) -> "SealedSegment":
    """Merge immutable segments into one, deduping docs by id — the
    reference's multi-segment builder used for flush compaction
    (m3ninx/index/segment/builder/multi_segments_*). Doc order follows the
    input segment order (earlier segments win duplicates, matching the
    executor's dedupe)."""
    m = MutableSegment()
    for seg in segments:
        docs = seg.docs
        for i in range(len(seg)):
            m.insert(docs[i])
    return m.seal()
