"""Postings-list LRU cache.

Reference: /root/reference/src/dbnode/storage/index/postings_list_cache.go:59
— the reference caches computed postings lists per (segment, pattern) for
regexp/term searches so repeated queries against immutable segments skip
the FST walk. Here the cache keys (segment, kind, field, pattern); only
IMMUTABLE segments (sealed / on-disk) are cacheable — mutable segments
mutate under writes, so they bypass the cache entirely.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict

import numpy as np

_seg_keys = itertools.count(1)


def segment_cache_key(seg) -> int | None:
    """Stable per-immutable-segment identity; None = not cacheable."""
    # mutable segments grow in place: never cache them
    if hasattr(seg, "insert"):
        return None
    key = getattr(seg, "_plc_key", None)
    if key is None:
        key = next(_seg_keys)
        try:
            seg._plc_key = key
        except AttributeError:
            return None
    return key


class PostingsListCache:
    """LRU of computed postings arrays."""

    def __init__(self, capacity: int = 1024) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._od: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> np.ndarray | None:
        with self._lock:
            arr = self._od.get(key)
            if arr is None:
                self.misses += 1
                return None
            self._od.move_to_end(key)
            self.hits += 1
            return arr

    def put(self, key: tuple, arr: np.ndarray) -> None:
        with self._lock:
            self._od[key] = arr
            self._od.move_to_end(key)
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)

    def __len__(self) -> int:
        return len(self._od)
