"""Postings-list LRU cache.

Reference: /root/reference/src/dbnode/storage/index/postings_list_cache.go:59
— the reference caches computed postings lists per (segment, pattern) for
regexp/term searches so repeated queries against immutable segments skip
the FST walk. Here the cache keys (segment, kind, field, pattern); only
IMMUTABLE segments (sealed / on-disk) are cacheable — mutable segments
mutate under writes, so they bypass the cache entirely.

Coherence: cache keys are bound to a segment OBJECT (the per-object
``_plc_key``), so a superseded segment can never serve wrong results —
but before PR 10 its entries could outlive it, squatting capacity until
LRU churn found them. ``invalidate_segment`` drops a segment's entries
the moment seal compaction, persist, or retention expiry replaces it
(ns_index.py calls it at every segment-replacement site).

Observability: hits/misses are counted both per-instance (``stats()``)
and in the process registry as
``m3tpu_index_postings_cache_{hits,misses}_total``, so the self-scrape
pipeline stores cache effectiveness as series.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict

import numpy as np

from ..utils.instrument import DEFAULT as METRICS

_seg_keys = itertools.count(1)

_M_HITS = METRICS.counter(
    "index_postings_cache_hits_total",
    "postings-list cache hits (regexp/field scans served without a "
    "term-dictionary walk)",
)
_M_MISSES = METRICS.counter(
    "index_postings_cache_misses_total", "postings-list cache misses"
)


def segment_cache_key(seg) -> int | None:
    """Stable per-immutable-segment identity; None = not cacheable."""
    # mutable segments grow in place: never cache them
    if hasattr(seg, "insert"):
        return None
    key = getattr(seg, "_plc_key", None)
    if key is None:
        key = next(_seg_keys)
        try:
            seg._plc_key = key
        except AttributeError:
            return None
    return key


class PostingsListCache:
    """LRU of computed postings arrays."""

    def __init__(self, capacity: int = 1024) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._od: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def get(self, key: tuple) -> np.ndarray | None:
        with self._lock:
            arr = self._od.get(key)
            if arr is None:
                self.misses += 1
                _M_MISSES.inc()
                return None
            self._od.move_to_end(key)
            self.hits += 1
            _M_HITS.inc()
            return arr

    def put(self, key: tuple, arr: np.ndarray) -> None:
        with self._lock:
            self._od[key] = arr
            self._od.move_to_end(key)
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)

    def invalidate_segment(self, seg) -> int:
        """Drop every entry computed against ``seg`` (and, for a device
        wrapper, against its wrapped host segment — fallback searches
        cache under the host object). Called when a segment is sealed
        away, compacted into a persisted segment, or expired; returns
        the number of entries dropped."""
        seg_keys = set()
        for s in (seg, getattr(seg, "host", None)):
            k = getattr(s, "_plc_key", None) if s is not None else None
            if k is not None:
                seg_keys.add(k)
        if not seg_keys:
            return 0
        with self._lock:
            doomed = [k for k in self._od if k[0] in seg_keys]
            for k in doomed:
                del self._od[k]
            self.invalidations += len(doomed)
            return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._od),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
            }

    def __len__(self) -> int:
        return len(self._od)
