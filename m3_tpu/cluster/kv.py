"""Versioned KV store with watches — the control-plane foundation.

Reference: /root/reference/src/cluster/kv/ — kv.Store/TxnStore
(kv/types.go), etcd implementation with watches + caching overlays
(kv/etcd/store.go). This is the in-process equivalent the reference's
integration tests use (fake cluster services); an optional JSON file backing
makes values durable across restarts (the role of etcd persistence for a
single-node deployment).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class VersionedValue:
    version: int
    value: Any


class LeaseHeld(Exception):
    """Lease acquisition rejected: another holder's lease is still live."""

    def __init__(self, holder: str, expires_in: float) -> None:
        super().__init__(f"lease held by {holder} for another {expires_in:.3f}s")
        self.holder = holder
        self.expires_in = expires_in


class FenceError(Exception):
    """A fenced write's lease token no longer matches the live lease
    (the writer's leadership was lost or superseded)."""


class KVStore:
    """kv.Store: Get/Set/SetIfNotExists/CheckAndSet + watches + leases.

    Leases (etcd lease/session role, arbitrated on the STORE's clock — not
    the clients', so cross-process clock skew cannot yield two leaders):
    a lease is an ordinary versioned KV record whose value is
    ``{"holder", "token", "ttl", "acquired_at"}``; watches, persistence and
    CAS therefore work on it unchanged. ``token`` is a per-key fencing
    counter that increases on every distinct acquisition; fenced writes
    (``fence=(lease_key, holder, token)``) are rejected once the token is
    stale, which makes a suspended ex-leader's late flushes harmless
    (the etcd-session + STM pattern of the reference's election_mgr)."""

    def __init__(self, backing_path: str | None = None, clock=time.time) -> None:
        self.clock = clock
        self._lock = threading.RLock()
        self._change = threading.Condition(self._lock)
        self._data: dict[str, VersionedValue] = {}
        # last version at deletion: a re-created key resumes from here so
        # version-gated watchers (remote long-polls) never miss the rebirth
        self._tombstones: dict[str, int] = {}
        self._watchers: dict[str, list[Callable[[VersionedValue], None]]] = {}
        self._path = backing_path
        if backing_path and os.path.exists(backing_path):
            with open(backing_path) as f:
                raw = json.load(f)
            if isinstance(raw, dict) and set(raw) == {"data", "tombstones"}:
                data, self._tombstones = raw["data"], {
                    k: int(v) for k, v in raw["tombstones"].items()
                }
            else:  # legacy flat format
                data = raw
            self._data = {
                k: VersionedValue(v["version"], v["value"]) for k, v in data.items()
            }

    def _persist(self) -> None:
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "data": {
                        k: {"version": v.version, "value": v.value}
                        for k, v in self._data.items()
                    },
                    "tombstones": self._tombstones,
                },
                f,
            )
        os.replace(tmp, self._path)

    def get(self, key: str) -> VersionedValue | None:
        with self._lock:
            return self._data.get(key)

    # -- leases (server-clock arbitration + fencing tokens) --

    def _lease_rec(self, key: str) -> dict | None:
        vv = self._data.get(key)
        if vv is None or not isinstance(vv.value, dict) or "token" not in vv.value:
            return None
        return vv.value

    @staticmethod
    def _live(rec: dict | None, now: float) -> bool:
        return (
            rec is not None
            and rec.get("holder") is not None
            and now - rec["acquired_at"] <= rec["ttl"]
        )

    def lease_acquire(
        self, key: str, holder: str, ttl: float, now: float | None = None
    ) -> int:
        """Acquire/refresh ``key``'s lease for ``holder``; returns the
        fencing token. The token is stable across refreshes by the same
        live holder and strictly increases on every distinct acquisition.
        Raises LeaseHeld while another holder's lease is live."""
        now = self.clock() if now is None else now
        with self._lock:
            rec = self._lease_rec(key)
            if self._live(rec, now):
                if rec["holder"] != holder:
                    raise LeaseHeld(
                        rec["holder"], rec["ttl"] - (now - rec["acquired_at"])
                    )
                token = rec["token"]  # refresh, keep fencing token
            else:
                token = (rec["token"] if rec else 0) + 1
            _, vv, watchers = self._set_locked(
                key, {"holder": holder, "token": token, "ttl": ttl, "acquired_at": now}
            )
        for w in watchers:
            w(vv)
        return token

    def lease_keepalive(
        self, key: str, holder: str, token: int, now: float | None = None
    ) -> bool:
        """Refresh the lease iff ``holder`` still holds it under ``token``."""
        now = self.clock() if now is None else now
        with self._lock:
            rec = self._lease_rec(key)
            if not self._live(rec, now) or rec["holder"] != holder or rec["token"] != token:
                return False
            _, vv, watchers = self._set_locked(key, {**rec, "acquired_at": now})
        for w in watchers:
            w(vv)
        return True

    def lease_release(self, key: str, holder: str, token: int) -> bool:
        """Vacate the lease (holder -> None; token survives in the record so
        the next acquisition still fences out stale writers)."""
        with self._lock:
            rec = self._lease_rec(key)
            if rec is None or rec.get("holder") != holder or rec["token"] != token:
                return False
            _, vv, watchers = self._set_locked(key, {**rec, "holder": None})
        for w in watchers:
            w(vv)
        return True

    def lease_get(self, key: str, now: float | None = None) -> tuple[str, int] | None:
        """(holder, token) if the lease is live on the store's clock."""
        now = self.clock() if now is None else now
        with self._lock:
            rec = self._lease_rec(key)
            return (rec["holder"], rec["token"]) if self._live(rec, now) else None

    def lease_expire(self, key: str) -> None:
        """Force-expire (test hook: simulates the holder's death without
        waiting out the TTL)."""
        with self._lock:
            rec = self._lease_rec(key)
            if rec is None:
                return
            _, vv, watchers = self._set_locked(
                key, {**rec, "acquired_at": -float(rec["ttl"]) - 1e9}
            )
        for w in watchers:
            w(vv)

    def _fence_check(self, fence, now: float) -> None:
        lease_key, holder, token = fence
        rec = self._lease_rec(lease_key)
        if not self._live(rec, now) or rec["holder"] != holder or rec["token"] != token:
            raise FenceError(
                f"stale fence for {lease_key}: held={rec.get('holder') if rec else None}"
                f" token={rec.get('token') if rec else None}, writer={holder}/{token}"
            )

    def _set_locked(self, key: str, value: Any):
        cur = self._data.get(key)
        version = (cur.version if cur else self._tombstones.get(key, 0)) + 1
        vv = VersionedValue(version, value)
        self._data[key] = vv
        self._persist()
        self._change.notify_all()
        return version, vv, list(self._watchers.get(key, ()))

    def set(self, key: str, value: Any, fence=None, now: float | None = None) -> int:
        """Plain set; with ``fence=(lease_key, holder, token)`` the write is
        rejected (FenceError) unless that lease is live for that token —
        check and write are atomic under the store lock."""
        with self._lock:
            if fence is not None:
                self._fence_check(fence, self.clock() if now is None else now)
            version, vv, watchers = self._set_locked(key, value)
        for w in watchers:
            w(vv)
        return version

    def set_if_not_exists(self, key: str, value: Any) -> int:
        with self._lock:
            if key in self._data:
                raise KeyError(f"key {key} already exists")
            version, vv, watchers = self._set_locked(key, value)
        for w in watchers:
            w(vv)
        return version

    def check_and_set(
        self, key: str, expect_version: int, value: Any, fence=None,
        now: float | None = None,
    ) -> int:
        """CAS (kv/types.go CheckAndSet): version 0 = must not exist.
        Check and write are atomic under the store lock. ``fence`` as in
        :meth:`set`."""
        with self._lock:
            if fence is not None:
                self._fence_check(fence, self.clock() if now is None else now)
            cur = self._data.get(key)
            cur_version = cur.version if cur else 0
            if cur_version != expect_version:
                raise ValueError(
                    f"version mismatch for {key}: have {cur_version}, want {expect_version}"
                )
            version, vv, watchers = self._set_locked(key, value)
        for w in watchers:
            w(vv)
        return version

    def delete(self, key: str) -> None:
        with self._lock:
            gone = self._data.pop(key, None)
            if gone is not None:
                self._tombstones[key] = gone.version
            self._persist()
            self._change.notify_all()

    def keys(self, prefix: str = "") -> list[str]:
        """Sorted keys under a prefix (etcd range-read role; service
        discovery and topic listing scan by prefix)."""
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def get_prefix(self, prefix: str = "") -> dict[str, VersionedValue]:
        """Bulk range read: key → VersionedValue under a prefix in ONE call
        (one RPC over the networked store — discovery and detector passes
        must not pay a round trip per instance)."""
        with self._lock:
            return {
                k: v for k, v in sorted(self._data.items()) if k.startswith(prefix)
            }

    # -- wholesale snapshot (raft install-snapshot / compaction) --

    def dump(self) -> dict:
        with self._lock:
            return {
                "data": {
                    k: {"version": v.version, "value": v.value}
                    for k, v in self._data.items()
                },
                "tombstones": dict(self._tombstones),
            }

    def restore(self, snap: dict) -> None:
        """Replace the entire contents (follower installing a snapshot).
        Long-poll watchers wake and re-read; per-key callbacks fire for
        keys whose version advanced."""
        with self._lock:
            old = self._data
            self._data = {
                k: VersionedValue(v["version"], v["value"])
                for k, v in snap["data"].items()
            }
            self._tombstones = {k: int(v) for k, v in snap["tombstones"].items()}
            self._persist()
            self._change.notify_all()
            fired = [
                (w, vv)
                for k, vv in self._data.items()
                if (not (o := old.get(k)) or o.version != vv.version)
                for w in self._watchers.get(k, ())
            ]
        for w, vv in fired:
            w(vv)

    def wait_for_version_gt(
        self, key: str, after_version: int, timeout: float
    ) -> VersionedValue | None:
        """Block until key's version exceeds ``after_version`` (long-poll
        watch primitive for the networked KV service). Returns the current
        value immediately if already newer; None on timeout. Deletions are
        not delivered (matching in-process watch semantics) — but a
        re-created key resumes versioning past its tombstone, so watchers
        always see the rebirth."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                cur = self._data.get(key)
                if cur is not None and cur.version > after_version:
                    return cur
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._change.wait(remaining)

    def watch(self, key: str, fn: Callable[[VersionedValue], None]) -> Callable[[], None]:
        """Register a watcher; returns an unsubscribe fn. Fires immediately
        with the current value if one exists (etcd watch + get semantics)."""
        with self._lock:
            self._watchers.setdefault(key, []).append(fn)
            cur = self._data.get(key)
        if cur is not None:
            fn(cur)

        def unsub() -> None:
            with self._lock:
                try:
                    self._watchers[key].remove(fn)
                except (KeyError, ValueError):
                    pass

        return unsub
