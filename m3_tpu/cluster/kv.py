"""Versioned KV store with watches — the control-plane foundation.

Reference: /root/reference/src/cluster/kv/ — kv.Store/TxnStore
(kv/types.go), etcd implementation with watches + caching overlays
(kv/etcd/store.go). This is the in-process equivalent the reference's
integration tests use (fake cluster services); an optional JSON file backing
makes values durable across restarts (the role of etcd persistence for a
single-node deployment).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class VersionedValue:
    version: int
    value: Any


class KVStore:
    """kv.Store: Get/Set/SetIfNotExists/CheckAndSet + watches."""

    def __init__(self, backing_path: str | None = None) -> None:
        self._lock = threading.RLock()
        self._change = threading.Condition(self._lock)
        self._data: dict[str, VersionedValue] = {}
        # last version at deletion: a re-created key resumes from here so
        # version-gated watchers (remote long-polls) never miss the rebirth
        self._tombstones: dict[str, int] = {}
        self._watchers: dict[str, list[Callable[[VersionedValue], None]]] = {}
        self._path = backing_path
        if backing_path and os.path.exists(backing_path):
            with open(backing_path) as f:
                raw = json.load(f)
            if isinstance(raw, dict) and set(raw) == {"data", "tombstones"}:
                data, self._tombstones = raw["data"], {
                    k: int(v) for k, v in raw["tombstones"].items()
                }
            else:  # legacy flat format
                data = raw
            self._data = {
                k: VersionedValue(v["version"], v["value"]) for k, v in data.items()
            }

    def _persist(self) -> None:
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "data": {
                        k: {"version": v.version, "value": v.value}
                        for k, v in self._data.items()
                    },
                    "tombstones": self._tombstones,
                },
                f,
            )
        os.replace(tmp, self._path)

    def get(self, key: str) -> VersionedValue | None:
        with self._lock:
            return self._data.get(key)

    def _set_locked(self, key: str, value: Any):
        cur = self._data.get(key)
        version = (cur.version if cur else self._tombstones.get(key, 0)) + 1
        vv = VersionedValue(version, value)
        self._data[key] = vv
        self._persist()
        self._change.notify_all()
        return version, vv, list(self._watchers.get(key, ()))

    def set(self, key: str, value: Any) -> int:
        with self._lock:
            version, vv, watchers = self._set_locked(key, value)
        for w in watchers:
            w(vv)
        return version

    def set_if_not_exists(self, key: str, value: Any) -> int:
        with self._lock:
            if key in self._data:
                raise KeyError(f"key {key} already exists")
            version, vv, watchers = self._set_locked(key, value)
        for w in watchers:
            w(vv)
        return version

    def check_and_set(self, key: str, expect_version: int, value: Any) -> int:
        """CAS (kv/types.go CheckAndSet): version 0 = must not exist.
        Check and write are atomic under the store lock."""
        with self._lock:
            cur = self._data.get(key)
            cur_version = cur.version if cur else 0
            if cur_version != expect_version:
                raise ValueError(
                    f"version mismatch for {key}: have {cur_version}, want {expect_version}"
                )
            version, vv, watchers = self._set_locked(key, value)
        for w in watchers:
            w(vv)
        return version

    def delete(self, key: str) -> None:
        with self._lock:
            gone = self._data.pop(key, None)
            if gone is not None:
                self._tombstones[key] = gone.version
            self._persist()
            self._change.notify_all()

    def keys(self, prefix: str = "") -> list[str]:
        """Sorted keys under a prefix (etcd range-read role; service
        discovery and topic listing scan by prefix)."""
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def get_prefix(self, prefix: str = "") -> dict[str, VersionedValue]:
        """Bulk range read: key → VersionedValue under a prefix in ONE call
        (one RPC over the networked store — discovery and detector passes
        must not pay a round trip per instance)."""
        with self._lock:
            return {
                k: v for k, v in sorted(self._data.items()) if k.startswith(prefix)
            }

    def wait_for_version_gt(
        self, key: str, after_version: int, timeout: float
    ) -> VersionedValue | None:
        """Block until key's version exceeds ``after_version`` (long-poll
        watch primitive for the networked KV service). Returns the current
        value immediately if already newer; None on timeout. Deletions are
        not delivered (matching in-process watch semantics) — but a
        re-created key resumes versioning past its tombstone, so watchers
        always see the rebirth."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                cur = self._data.get(key)
                if cur is not None and cur.version > after_version:
                    return cur
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._change.wait(remaining)

    def watch(self, key: str, fn: Callable[[VersionedValue], None]) -> Callable[[], None]:
        """Register a watcher; returns an unsubscribe fn. Fires immediately
        with the current value if one exists (etcd watch + get semantics)."""
        with self._lock:
            self._watchers.setdefault(key, []).append(fn)
            cur = self._data.get(key)
        if cur is not None:
            fn(cur)

        def unsub() -> None:
            with self._lock:
                try:
                    self._watchers[key].remove(fn)
                except (KeyError, ValueError):
                    pass

        return unsub
